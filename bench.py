#!/usr/bin/env python
"""Flagship benchmark: GPT training-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The reference publishes no in-repo numbers (BASELINE.md — all N/A), so
``vs_baseline`` reports measured model-FLOPs-utilization (MFU) against the
chip's peak — an absolute, hardware-grounded yardstick that carries across
rounds.

Hardened launcher/worker design: backend init in this environment can block
indefinitely inside ``import jax`` when the TPU tunnel is down (the axon PJRT
plugin dials out at import). The launcher therefore never imports jax itself;
it probes the accelerator in a subprocess under a timeout and falls back to a
CPU run marked ``"degraded": true`` so a JSON line is always produced within
the time budget. Progress streams to stderr throughout.

``--tuned=TUNED.json`` applies the autotuner's winning train config
(tools/autotune.py, docs/autotune.md): model-side knobs (remat policy,
fused_ln, CE vocab chunk) scale the bench config, step-side knobs
(grad reduction, wire dtype, bucket cap, fused optimizer) ride
``make_train_step(tuned=)``. Fingerprint-gated; explicit flags
(--remat=, --ce-vchunk=) beat the tuner.
"""
import json
import os
import subprocess
import sys
import time

TOTAL_BUDGET_S = 390       # stay under the driver's ~7 min ceiling
PROBE_TIMEOUT_S = 90       # device init should be fast; compile comes later
PROBE_ATTEMPTS = 2         # r03 forfeited the round on ONE timed-out probe;
                           # a wedged relay claim often clears on the retry
CPU_RESERVE_S = 80         # always keep room for the CPU fallback run


def _log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _cpu_env():
    env = dict(os.environ)
    # PALLAS_AXON_POOL_IPS triggers the axon PJRT plugin registration in
    # sitecustomize, which blocks `import jax` on the tunnel — strip it.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _expects_accelerator():
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS")) or \
        os.environ.get("JAX_PLATFORMS", "").lower() in ("tpu", "axon")


def _run_timed(cmd, env, timeout_s):
    """Run cmd under a timeout with a graceful teardown.

    Killing a python process mid-TPU-session wedges the axon relay (see
    .claude/skills/verify/SKILL.md), so on timeout send SIGINT first and give
    the child a grace period to unwind the PJRT client before SIGKILL.
    """
    import signal
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=None, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return proc.returncode, out
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGINT)
        try:
            out, _ = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        return None, out or ""


def _probe(attempts=PROBE_ATTEMPTS):
    """Initialize the backend in a subprocess; return (platform, kind),
    (None, None) when no backend comes up.

    Retries: a single timed-out probe must not forfeit the round's hardware
    number (BENCH_r03 lesson) — the axon relay claim left by a dead process
    typically expires within the first probe's window, so a second attempt
    succeeds where the first hung.
    """
    code = ("import jax; d = jax.devices()[0]; "
            "print('PLATFORM=%s KIND=%s' % (d.platform, "
            "str(d.device_kind).replace(' ', '_')))")
    for attempt in range(1, attempts + 1):
        rc, out = _run_timed([sys.executable, "-c", code], dict(os.environ),
                             PROBE_TIMEOUT_S)
        if rc is None:
            _log(f"probe attempt {attempt}/{attempts} timed out "
                 f"after {PROBE_TIMEOUT_S}s")
            continue
        if rc != 0:
            _log(f"probe attempt {attempt}/{attempts} failed rc={rc}")
            continue
        platform = kind = None
        for tok in out.split():
            if tok.startswith("PLATFORM="):
                platform = tok.split("=", 1)[1]
            elif tok.startswith("KIND="):
                kind = tok.split("=", 1)[1].replace("_", " ")
        if platform:
            return platform, kind
    return None, None


def _run_worker(env, timeout_s, extra_args):
    """Run the worker; return the parsed JSON result line or None."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker"] + extra_args
    _log(f"worker start (timeout {int(timeout_s)}s): {' '.join(extra_args)}")
    rc, out = _run_timed(cmd, env, timeout_s)
    if rc is None:
        _log("worker timed out")
        return None
    if rc != 0:
        _log(f"worker failed rc={rc}")
        return None
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    _log("worker produced no JSON line")
    return None


def launcher():
    t0 = time.time()
    remaining = lambda: TOTAL_BUDGET_S - (time.time() - t0)
    result = None

    platform, device_kind = _probe()
    _log(f"probe platform: {platform} kind: {device_kind}")
    saw_accelerator = platform not in (None, "cpu")
    if saw_accelerator:
        budget = max(60.0, remaining() - CPU_RESERVE_S - 90)
        flash_args = []
        # config ladder: measured-known-good first (r05 session-4 sweep:
        # b=16 remat=dots + bf16 Adam moments is the measured winner at
        # 0.7168 MFU — no-remat fits with bf16 moments but loses, 0.691
        # at b=8; KERNEL_NOTES.md session-4 table holds the evidence).
        # A failed attempt costs ~90 s of the ~390 s budget, so the
        # ladder leads with what fits and keeps --no-flash only for a
        # Pallas-kernel regression.
        result = _run_worker(dict(os.environ), budget, [])
        if result is None and remaining() > CPU_RESERVE_S + 120:
            flash_args = ["--no-flash"]
            result = _run_worker(dict(os.environ),
                                 remaining() - CPU_RESERVE_S, flash_args)
        if result is not None and remaining() > CPU_RESERVE_S + 60:
            # informational second config in its own process, so a crash
            # (OOM kill etc.) cannot lose the primary number above; inherits
            # the flash setting the primary run actually succeeded with
            wide = _run_worker(dict(os.environ),
                               remaining() - CPU_RESERVE_S,
                               ["--wide"] + flash_args)
            if wide is not None:
                # the better-MFU config is the headline (both reported)
                if wide.get("vs_baseline", 0) > result.get("vs_baseline", 0):
                    wide.setdefault("detail", {})["small_config"] = \
                        result.get("detail", result)
                    result = wide
                else:
                    result.setdefault("detail", {})["wide_config"] = \
                        wide.get("detail", wide)
        def side_lane(flag, detail_key, value_key):
            # informational north-star lanes (BASELINE.md rows) in their
            # own processes, so a crash cannot lose the primary number
            if result is None or remaining() <= CPU_RESERVE_S + 60:
                return
            r = _run_worker(dict(os.environ),
                            remaining() - CPU_RESERVE_S, [flag])
            if r is not None:
                result.setdefault("detail", {})[detail_key] = {
                    value_key: r.get("value"),
                    "mfu": r.get("vs_baseline"),
                    **r.get("detail", {}),
                }

        side_lane("--resnet", "resnet50", "images_per_sec_per_chip")
        side_lane("--ernie", "ernie_base", "samples_per_sec_per_chip")

    if result is None:
        degraded = saw_accelerator or _expects_accelerator()
        if degraded:
            _log("falling back to CPU (degraded)")
        result = _run_worker(_cpu_env(), max(60.0, remaining()), [])
        if result is not None:
            result["degraded"] = degraded

    if result is None:
        result = {"metric": "gpt_train_tokens_per_sec_per_chip", "value": 0.0,
                  "unit": "tokens/s", "vs_baseline": None, "degraded": True,
                  "detail": {"error": "all bench attempts failed/timed out"}}
    result.setdefault("degraded", False)
    # stamp the backend + device kind the NUMBER was measured on (from the
    # worker that produced it, falling back to the probe), and never let a
    # non-TPU backend masquerade as a chip number (the BENCH_r05.json
    # failure mode): backend != tpu forces degraded.
    det = result.get("detail", {})
    backend = det.get("platform") or platform or "unknown"
    result["backend"] = backend
    result["device_kind"] = det.get("device") or device_kind or backend
    if backend != "tpu" and not result.get("degraded"):
        _log(f"backend {backend!r} is not TPU — marking degraded")
        result["degraded"] = True
    if result.get("degraded"):
        # a CPU toy's MFU-shaped number must never masquerade as the hardware
        # yardstick: null it and say why, keeping the raw value in detail
        det = result.setdefault("detail", {})
        det["degraded_reason"] = (
            ("accelerator bench attempts failed/timed out after a successful "
             "probe" if saw_accelerator else
             "accelerator probe failed" if _expects_accelerator() else
             f"measured on backend {backend!r}, not TPU") +
            "; non-TPU run — vs_baseline (MFU) is only meaningful on the "
            "real chip")
        if result.get("vs_baseline") is not None:
            det["cpu_mfu_not_comparable"] = result["vs_baseline"]
        result["vs_baseline"] = None
    print(json.dumps(result), flush=True)


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def _peak_flops(device) -> float:
    """Peak *bf16* FLOP/s for the device — one shared table
    (paddle_tpu/observability/hw.py) so bench, mfu_sweep and the
    TrainMonitor all divide by the same denominator. v5e is 197 TFLOP/s
    bf16 (394 is its int8 rate — the table briefly held 394 and understated
    every reported MFU 2x; PEAK_PROBE.json measures 171.3 TF on a dense
    bf16 matmul, 87% of 197)."""
    from paddle_tpu.observability import hw

    return hw.peak_bf16_flops(device)


def _program_train_flops(program, batch):
    """Analytic fwd+bwd FLOPs of a built fluid program (shared helper in
    paddle_tpu/observability/hw.py)."""
    from paddle_tpu.observability import hw

    return hw.program_train_flops(program, batch)


def resnet_worker():
    """ResNet-50 training throughput on one chip through the REAL user path:
    fluid program -> whole-block jit, bf16 AMP, momentum. Synthetic data is
    generated on-device (uniform_random/randint ops) so the tunnel RTT and
    host->device feeds don't pollute the compute measurement; steps dispatch
    async (no fetch) and are forced once at the end."""
    _log("resnet worker: importing")
    from paddle_tpu.sysconfig import tpu_perf_flags

    tpu_perf_flags()
    import numpy as np
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet as R

    from paddle_tpu.tuning.probe import device_info

    di = device_info()
    dev, on_acc = di.device, di.on_acc
    batch = 128 if on_acc else 2
    hw = 224 if on_acc else 32
    steps = 8 if on_acc else 2
    _log(f"resnet worker: device {di.platform} batch={batch}")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.uniform_random(
            [batch, 3, hw, hw], min=-1.0, max=1.0, dtype="float32")
        img.stop_gradient = True
        label = fluid.layers.randint(0, 1000, shape=[batch, 1], dtype="int64")
        logits = R.resnet(img, class_dim=1000, depth=50)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        from paddle_tpu.contrib.mixed_precision import decorate

        opt = decorate(fluid.optimizer.Momentum(0.01, 0.9), use_bf16=True)
        opt.minimize(loss)
    flops = _program_train_flops(main, batch)
    _log(f"resnet worker: {flops/1e9:.1f} GFLOP/step analytic")

    exe = fluid.Executor(fluid.XLAPlace(0))
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    # a tiny persistable whose device->host read forces the async step chain
    probe = main.global_block().all_parameters()[-1].name
    tc = time.perf_counter()
    exe.run(main, feed={}, fetch_list=[], scope=scope)
    np.asarray(scope.find_var(probe))
    _log(f"resnet worker: compile+step {time.perf_counter() - tc:.1f}s")
    t0 = time.perf_counter()
    for _ in range(steps):
        exe.run(main, feed={}, fetch_list=[], scope=scope)
    np.asarray(scope.find_var(probe))  # force chain inside the timed region
    dt = time.perf_counter() - t0
    (loss_v,) = exe.run(main, feed={}, fetch_list=[loss], scope=scope)
    loss_v = float(np.asarray(loss_v))
    img_s = steps * batch / dt
    mfu = img_s * (flops / batch) / _peak_flops(dev)
    _log(f"resnet worker: {img_s:.0f} img/s mfu={mfu:.3f}")
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(img_s, 2), "unit": "images/s",
        "vs_baseline": round(mfu, 4),
        "detail": {"config": "resnet50_bf16", "batch": batch,
                   "image": hw, "steps": steps,
                   "flops_per_step_g": round(flops / 1e9, 1),
                   "loss": round(loss_v, 4),
                   "platform": dev.platform,
                   "device": str(getattr(dev, "device_kind", dev.platform))},
    }), flush=True)


def ernie_worker():
    """ERNIE-base pretraining throughput (BASELINE.md north-star row):
    MLM + NSP train step on one chip, bf16, flash attention, momentum —
    models/ernie.py make_pretrain_step (the reference's ERNIE config is
    the dist_transformer/ERNIE encoder family)."""
    _log("ernie worker: importing")
    from paddle_tpu.sysconfig import tpu_perf_flags

    tpu_perf_flags()
    import numpy as np
    import jax

    from paddle_tpu.models import ernie as E

    from paddle_tpu.tuning.probe import device_info

    di = device_info()
    dev, on_acc = di.device, di.on_acc
    # remat off on-chip: ERNIE-base's optimizer state is only ~1 GB, so the
    # full-remat forward replay (~1/4 of step FLOPs) buys nothing — but the
    # saved activations are ~170 MB/layer per 8 samples, so batch sizes the
    # HBM budget (see the batch comment below)
    cfg = E.ERNIE_BASE.scaled(use_flash=on_acc, remat=False) if on_acc else \
        E.ERNIE_TINY
    # batch 48 keeps no-remat's saved activations (~8 GB) comfortably inside
    # HBM — an OOM crash here is a relay-wedge risk for the rest of the
    # session, not just a lost side lane
    batch, T, steps = (48, 512, 10) if on_acc else (4, 64, 2)
    _log(f"ernie worker: device {di.platform} batch={batch}")

    params = E.init_params(jax.random.PRNGKey(0), cfg)
    opt = E.init_opt(params)
    step = E.make_pretrain_step(cfg)
    rng = np.random.default_rng(0)
    M = cfg.max_masked
    batch_np = {
        "tokens": rng.integers(0, cfg.vocab_size, (batch, T), dtype=np.int32),
        "seg_ids": rng.integers(0, 2, (batch, T), dtype=np.int32),
        "pad_mask": np.ones((batch, T), bool),
        "mlm_pos": rng.integers(0, T, (batch, M), dtype=np.int32),
        "mlm_ids": rng.integers(0, cfg.vocab_size, (batch, M),
                                dtype=np.int32),
        "mlm_valid": np.ones((batch, M), bool),
        "nsp_label": rng.integers(0, 2, (batch,), dtype=np.int32),
    }
    _log("ernie worker: compiling")
    tc = time.perf_counter()
    params, opt, loss = step(params, opt, batch_np)
    loss0 = float(loss)
    _log(f"ernie worker: compile+step {time.perf_counter() - tc:.1f}s "
         f"loss={loss0:.4f}")
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch_np)
    loss_v = float(loss)
    dt = time.perf_counter() - t0
    samples_s = steps * batch / dt
    n_params = E.num_params(params)
    # honest numerator (models/ernie.py pretrain_flops_per_token): embedding
    # gathers excluded, tied MLM decoder matmul counted at max_masked of T
    per_token = E.pretrain_flops_per_token(cfg, n_params, T)
    mfu = samples_s * T * per_token / _peak_flops(dev)
    _log(f"ernie worker: {samples_s:.1f} samples/s mfu={mfu:.3f}")
    print(json.dumps({
        "metric": "ernie_base_samples_per_sec_per_chip",
        "value": round(samples_s, 2), "unit": "samples/s",
        "vs_baseline": round(mfu, 4),
        "detail": {"config": "ernie_base_bf16" if on_acc else
                   "ernie_tiny_cpu", "batch": batch,
                   "seq_len": T, "steps": steps,
                   "model_params": int(n_params),
                   "loss": round(loss_v, 4),
                   "platform": dev.platform,
                   "device": str(getattr(dev, "device_kind", dev.platform))},
    }), flush=True)


def worker(use_flash: bool):
    _log("worker: importing jax")
    # comm/compute-overlap preset (async collectives + latency-hiding
    # scheduler) must land in XLA_FLAGS before the backend initializes;
    # no-op off-TPU (paddle_tpu.sysconfig.tpu_perf_flags platform gate)
    from paddle_tpu.sysconfig import tpu_perf_flags

    tpu_perf_flags()
    import numpy as np
    import jax

    # one derivation of platform/device_kind/degraded for every lane —
    # the shared probe harness owns it (paddle_tpu/tuning/probe.py)
    from paddle_tpu.tuning import probe as tuning_probe

    di = tuning_probe.device_info()
    dev, on_acc = di.device, di.on_acc
    _log(f"worker: device {di.platform}/{di.device_kind}"
         + (" (degraded)" if di.degraded else ""))

    from paddle_tpu.models import gpt as G
    from paddle_tpu.parallel import parallelize as PZ

    monitor_path = next((a.split("=", 1)[1] for a in sys.argv
                         if a.startswith("--monitor=")), None)
    # --tuned=TUNED.json: apply the autotuner's winning train config
    # (tools/autotune.py, docs/autotune.md). Fingerprint-gated — a
    # document recorded on different hardware warns and the committed
    # defaults run instead of silently applying foreign knobs.
    tuned_path = next((a.split("=", 1)[1] for a in sys.argv
                       if a.startswith("--tuned=")), None)
    tuned_doc = None
    if tuned_path:
        from paddle_tpu.tuning import tuned as tuned_mod

        tuned_doc = tuned_mod.load_for_device(tuned_path, di)
        _log(f"worker: tuned config {'applied' if tuned_doc else 'REFUSED'}"
             f" from {tuned_path}")
    # --checkpoint-dir=DIR [--checkpoint-interval=N]: periodic crash-safe
    # checkpointing through the elastic store (docs/elastic.md); an existing
    # committed checkpoint resumes the measured run (restored steps are
    # skipped, so a preempted bench continues instead of restarting)
    ckpt_dir = next((a.split("=", 1)[1] for a in sys.argv
                     if a.startswith("--checkpoint-dir=")), None)
    ckpt_interval = int(next((a.split("=", 1)[1] for a in sys.argv
                              if a.startswith("--checkpoint-interval=")), 5))
    # --dump-on-anomaly=DIR: a NaN/Inf loss or a grad-norm blowup during a
    # monitored run writes a self-contained forensics directory (monitor
    # tail, fetch summaries, active program reports, flag state); implies
    # per-step monitoring even without --monitor
    dump_dir = next((a.split("=", 1)[1] for a in sys.argv
                     if a.startswith("--dump-on-anomaly=")), None)
    # --skip-nonfinite: in-jit divergence guardrail (docs/health.md) — a
    # step whose psum'd loss/grad-norm goes NaN/Inf keeps the old state
    # wholesale, identically on every dp rank
    skip_nonfinite = "--skip-nonfinite" in sys.argv
    # hang watchdog + heartbeat from the launcher env contract (no-op
    # when PADDLE_HEALTH_DEADLINE_S / PADDLE_HEALTH_DIR are unset)
    from paddle_tpu.parallel import health as health_mod

    health_mod.maybe_install_from_env()
    # --profile[=PATH]: after the measured loop, trace a few extra steps
    # and emit the roofline attribution (ATTRIBUTION.json, ISSUE 14 —
    # observability/attribution.py): every fusion placed on the roofline,
    # residue ranking, config levers stamped for tools/perf_diff.py
    profile_path = next((a.split("=", 1)[1] for a in sys.argv
                         if a.startswith("--profile=")), None)
    if profile_path is None and "--profile" in sys.argv:
        profile_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "ATTRIBUTION.json")
    attr_stats = {}
    # --stream-input: feed the measured loop from the fault-tolerant
    # sharded streaming engine (docs/data.md) instead of one fixed tensor
    # pair — token shards are written once, read+decoded by the stream's
    # worker pool, and the result's detail gains the goodput ledger's
    # input_stall share so "is the input engine keeping up with the step"
    # is a measured number
    stream_input = "--stream-input" in sys.argv
    stream_stats = {}

    def _tuned_config_stamp():
        if tuned_doc is None:
            return {}
        from paddle_tpu.tuning import tuned as tuned_mod

        return tuned_mod.config_stamp(tuned_doc, tuned_path)

    def measure(tag, cfg, batch, T, steps):
        """Compile + run one config; returns (tokens/s, mfu, loss, params).

        Steps are dispatched asynchronously and the chain is forced once at
        the end — donated params serialize the steps on-device, and syncing
        per step would bill one tunnel round-trip per step (~25ms here)
        against pure device time. With --monitor=PATH the loop instead
        syncs every step and emits one TrainMonitor JSONL record per step
        (step time, dispatch/wait split, tokens/s, MFU, loss, NaN flags) —
        the monitored number includes that per-step sync by design.
        """
        import jax.numpy as jnp
        pcfg = PZ.ParallelConfig(dp=1, pp=1, tp=1, microbatches=1)
        mesh = PZ.build_mesh(pcfg, devices=[dev])
        _log(f"worker[{tag}]: init params")
        # bf16 Adam moments on the accelerator: halves optimizer HBM (the
        # difference between dots-remat fitting at useful batch) and
        # measured +1.7% MFU (MFU_SWEEP.json r05 session 4)
        params, opt = PZ.init_sharded(
            jax.random.PRNGKey(0), cfg, pcfg, mesh,
            moment_dtype=jnp.bfloat16 if on_acc else None,
            tuned=tuned_doc)
        step = PZ.make_train_step(cfg, pcfg, mesh, lr=1e-4,
                                  skip_nonfinite=skip_nonfinite,
                                  tuned=tuned_doc)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, cfg.vocab_size, (1, batch, T),
                              dtype=np.int32)
        labels = rng.integers(0, cfg.vocab_size, (1, batch, T),
                              dtype=np.int32)
        _log(f"worker[{tag}]: compiling train step (first call)")
        tc = time.perf_counter()
        params, opt, loss, _ = step(params, opt, tokens, labels)
        loss0 = float(loss)
        _log(f"worker[{tag}]: compile+step done in "
             f"{time.perf_counter() - tc:.1f}s loss={loss0:.4f}")
        n_params = G.num_params(params)
        flops_tok = G.train_flops_per_token(cfg, n_params, T)
        stream_iter = None
        if stream_input:
            import tempfile as _tf

            from paddle_tpu.dataset import streaming as STR
            from paddle_tpu.observability import goodput as _gp_mod

            sdir = _tf.mkdtemp(prefix="bench_stream_")
            n_shards = 4
            per_shard = (steps * batch + n_shards - 1) // n_shards
            paths, rec_no = [], 0
            for si in range(n_shards):
                p = os.path.join(sdir, f"shard-{si}")
                with open(p, "w") as f:
                    for _ in range(per_shard):
                        r = np.random.default_rng(rec_no)
                        row = np.concatenate([
                            r.integers(0, cfg.vocab_size, T),
                            r.integers(0, cfg.vocab_size, T)])
                        f.write(" ".join(map(str, row)) + "\n")
                        rec_no += 1
                paths.append(p)

            def _decode(raw):
                v = np.array(raw.split(), dtype=np.int64)
                if v.size != 2 * T:
                    raise ValueError(f"expected {2 * T} tokens, got {v.size}")
                return v[:T].astype(np.int32), v[T:].astype(np.int32)

            bench_stream = STR.ShardedStream(
                paths, _decode, STR.StreamConfig(
                    batch_size=batch, drop_last=True, num_workers=2))
            stream_iter = bench_stream.batches()
            stall0 = _gp_mod.ledger().category_seconds("input_stall")
            _log(f"worker[{tag}]: stream-input lane — {rec_no} records in "
                 f"{n_shards} shards under {sdir}")

        def next_batch():
            nonlocal stream_iter
            if stream_iter is None:
                return tokens, labels
            try:
                recs = next(stream_iter)
            except StopIteration:    # epoch boundary: keep streaming
                stream_iter = bench_stream.batches()
                recs = next(stream_iter)
            return (np.stack([x[0] for x in recs])[None],
                    np.stack([x[1] for x in recs])[None])
        ck = start_step = None
        if ckpt_dir:
            from paddle_tpu.parallel.checkpoint import (ElasticCheckpointer,
                                                        restore_train_state)

            ck = ElasticCheckpointer(ckpt_dir, keep_last=2)
            start_step = ck.latest_valid_step() or 0
            if start_step:
                params, opt, _man = restore_train_state(
                    ck, params, opt, step=start_step)
                _log(f"worker[{tag}]: resumed from checkpoint step "
                     f"{start_step}")
        mon = None
        if monitor_path or dump_dir:
            from paddle_tpu.observability import TrainMonitor

            mon = TrainMonitor(
                path=monitor_path, examples_per_step=batch,
                tokens_per_step=batch * T,
                flops_per_step=flops_tok * batch * T,
                peak_flops=_peak_flops(dev),
                extra_static={"config": tag},
                dump_on_anomaly=dump_dir)
        start0 = min(start_step or 0, steps)
        ran = max(1, steps - start0)

        hb_dir = os.environ.get(health_mod.ENV_DIR)
        hb = health_mod.RankHeartbeat(
            hb_dir, int(os.environ.get("PADDLE_TRAINER_ID", "0"))) \
            if hb_dir else None

        def maybe_ckpt(i):
            # async save (host snapshot is the only sync point); the final
            # step commits synchronously so a resumed bench is consistent
            if hb is not None:
                hb.beat(i + 1)
            if ck is not None and (i + 1 == steps or
                                   (i + 1) % ckpt_interval == 0):
                ck.save(i + 1, {"params": params, "opt": opt},
                        data_state={"epoch": 0, "offset": i + 1})

        t0 = time.perf_counter()
        if mon is not None:
            for i in range(start0, steps):
                with mon.step() as s:
                    toks_i, labs_i = next_batch()
                    params, opt, loss, gnorm = step(params, opt, toks_i,
                                                    labs_i)
                    s.dispatched()
                    s.observe(loss=loss, grad_norm=gnorm)
                maybe_ckpt(i)
            loss_v = mon.last_record.get("loss")
            mon.close()
        else:
            for i in range(start0, steps):
                toks_i, labs_i = next_batch()
                params, opt, loss, _ = step(params, opt, toks_i, labs_i)
                maybe_ckpt(i)
            loss_v = float(loss)  # forces the whole chain
        dt = time.perf_counter() - t0
        if stream_input:
            stall_s = _gp_mod.ledger().category_seconds("input_stall") \
                - stall0
            stream_stats.update(
                records=int(bench_stream.state.records),
                input_stall_s=round(stall_s, 4),
                input_stall_fraction=round(stall_s / max(dt, 1e-9), 4),
                retries=int(bench_stream.retries),
                quarantined=int(bench_stream.quarantined))
            _log(f"worker[{tag}]: stream-input stall {stall_s:.3f}s "
                 f"({stream_stats['input_stall_fraction']:.1%} of loop)")
        if hb is not None:
            hb.flush()
        if ck is not None:
            ck.close()
        if profile_path:
            # attribution lane OUTSIDE the timed loop: the measured
            # number stays clean, the extra traced steps feed the join
            import tempfile as _tf

            from paddle_tpu.observability import attribution as ATT
            from paddle_tpu.observability import program_report as PREP

            tdir = _tf.mkdtemp(prefix="bench_attr_")
            psteps = min(4, max(2, steps // 2))
            _log(f"worker[{tag}]: tracing {psteps} steps for attribution")
            tp0 = time.perf_counter()
            with jax.profiler.trace(tdir):
                for _ in range(psteps):
                    params, opt, loss, _ = step(params, opt, tokens,
                                                labels)
                float(loss)
            p_wall_ms = (time.perf_counter() - tp0) * 1e3 / psteps
            hlo = step.hlo_text() if hasattr(step, "hlo_text") else None
            report = next(
                (r for r in reversed(PREP.recent_reports())
                 if r.get("program") == getattr(step, "report_name",
                                                None)), {})
            attribution = ATT.build_from_trace(
                tdir, steps=psteps, wall_ms_per_step=p_wall_ms,
                hlo_texts=[hlo] if hlo else [], device=dev, mode="train",
                spec=f"bench:{tag}",
                step_flops=report.get("flops"),
                step_bytes=report.get("bytes_accessed"),
                programs=[report] if report else None,
                config={"mode": "train", "config": tag,
                        "remat": (cfg.remat_policy if cfg.remat
                                  else "none"),
                        "flash": bool(cfg.use_flash),
                        "fused_opt": False, "batch": batch, "seq": T,
                        "d_model": cfg.d_model,
                        "layers": cfg.num_layers,
                        # full tuned-knob vector + provenance pointer so
                        # perf_diff cause-attributes a regression to the
                        # tuner's choice, not "config lever unknown"
                        **(_tuned_config_stamp())},
                generated_by="bench.py --profile")
            ATT.write(attribution, profile_path)
            res = attribution["residue"]
            attr_stats.update(
                path=profile_path,
                device_busy_ms_per_step=attribution[
                    "device_busy_ms_per_step"],
                gap_share=attribution["gap_share"],
                residue_share=res["share_of_busy"],
                residue_groups=[g["label"] for g in res["groups"][:4]])
            _log(f"worker[{tag}]: attribution -> {profile_path} "
                 f"(residue {res['share_of_busy']:.1%}, groups "
                 f"{attr_stats['residue_groups']})")
        _log(f"worker[{tag}]: {ran} steps in {dt:.2f}s "
             f"({dt / ran * 1000:.0f} ms/step)")
        tokens_per_s = ran * batch * T / dt
        mfu = tokens_per_s * flops_tok / _peak_flops(dev)
        return tokens_per_s, mfu, loss_v, n_params

    wide_mode = "--wide" in sys.argv
    no_remat = "--no-remat" in sys.argv
    # remat selectable BY NAME through the first-class policy API
    # (paddle_tpu.parallel.remat): --remat=none|full|dots|save_only_flash.
    # The legacy spellings stay: --no-remat == --remat=none, and the
    # default remains the measured winner "dots".
    from paddle_tpu.parallel import remat as remat_mod

    remat_name = next((a.split("=", 1)[1] for a in sys.argv
                       if a.startswith("--remat=")), None)
    remat_explicit = remat_name is not None or no_remat
    if remat_name is None:
        remat_name = "none" if no_remat else "dots"
    rpolicy = remat_mod.resolve(remat_name)
    # A/B lever: --ce-vchunk=N routes the LM-head loss through the
    # vocab-chunked chunked_lm_loss path (docs/memory_levers.md)
    ce_vchunk = int(next((a.split("=", 1)[1] for a in sys.argv
                          if a.startswith("--ce-vchunk=")), 0))
    if on_acc and wide_mode:
        # MXU-saturating width (d_model 2048, head_dim 128) shows the
        # framework ceiling — GPT_SMALL's 768-wide matmuls cap its MFU well
        # below what the same code reaches on wider layers. The r05 sweep's
        # measured winner: batch 16, remat=dots (save matmul outputs,
        # recompute elementwise), chunked CE — 0.7168 MFU vs 0.7099 for the
        # previous b=32 full-remat default; no-remat both fits (bf16
        # moments) and measures WORSE (0.691 at b=8), see KERNEL_NOTES.md.
        cfg = G.GPT_SMALL.scaled(
            max_seq_len=1024, use_flash=use_flash, d_model=2048,
            num_heads=16, d_ff=8192, num_layers=6,
            remat=not rpolicy.is_none, remat_policy=rpolicy.name,
            ce_direct_bytes_limit=(1 << 30))
        batch, T, steps = (16, 1024, 10)
        tag = "gpt_wide" + ("" if rpolicy.name == "dots"
                            else f"_remat_{rpolicy.name}")
    elif on_acc:
        cfg = G.GPT_SMALL.scaled(max_seq_len=1024, use_flash=use_flash,
                                 remat=not rpolicy.is_none,
                                 remat_policy=rpolicy.name)
        batch, T, steps = 16, 1024, 10
        tag = "gpt_small" + ("" if rpolicy.name == "dots"
                             else f"_remat_{rpolicy.name}")
    else:  # CPU smoke path so the bench always produces a line
        cfg = G.GPT_TINY.scaled(num_layers=2)
        batch, T, steps = 4, 32, 3
        tag = "gpt_tiny_cpu"
    if ce_vchunk:
        cfg = cfg.scaled(ce_vocab_chunk=ce_vchunk, ce_direct_bytes_limit=0)
        tag += f"_vchunk{ce_vchunk}"
    if tuned_doc is not None:
        from paddle_tpu.tuning import tuned as tuned_mod

        ckw = tuned_mod.train_cfg_kwargs(tuned_doc)
        if remat_explicit:          # an explicit --remat= / --no-remat
            ckw.pop("remat", None)  # always beats the tuner
            ckw.pop("remat_policy", None)
        if ce_vchunk:               # likewise an explicit --ce-vchunk=
            ckw.pop("ce_vocab_chunk", None)
            ckw.pop("ce_direct_bytes_limit", None)
        if ckw:
            cfg = cfg.scaled(**ckw)
        tag += "_tuned"

    tokens_per_s, mfu, loss_v, n_params = measure(
        tag, cfg, batch, T, steps)

    detail = {
        "config": tag,
        "model_params": int(n_params),
        "d_model": cfg.d_model, "num_layers": cfg.num_layers,
        "seq_len": T, "batch": batch, "steps": steps,
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "platform": dev.platform,
        "remat_policy": cfg.remat_policy if cfg.remat else "none",
        "flash": bool(on_acc and use_flash),
        "loss": round(loss_v, 4),
        "tokens_per_s": round(tokens_per_s, 2),
        "mfu": round(mfu, 4),
    }
    if stream_stats:
        detail["stream_input"] = stream_stats
    if attr_stats:
        detail["attribution"] = attr_stats
    if tuned_doc is not None:
        from paddle_tpu.tuning import tuned as tuned_mod

        detail["tuned"] = tuned_mod.config_stamp(tuned_doc, tuned_path)
    print(json.dumps({
        "metric": "gpt_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu, 4),
        "detail": detail,
    }), flush=True)


def main():
    if "--worker" in sys.argv and "--ernie" in sys.argv:
        ernie_worker()
    elif "--worker" in sys.argv and "--resnet" in sys.argv:
        resnet_worker()
    elif "--worker" in sys.argv:
        worker(use_flash="--no-flash" not in sys.argv)
    else:
        launcher()


if __name__ == "__main__":
    main()
