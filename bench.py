"""Flagship benchmark: GPT training-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The reference publishes no in-repo numbers (BASELINE.md — all N/A), so
``vs_baseline`` reports measured model-FLOPs-utilization (MFU) against the
chip's peak — an absolute, hardware-grounded yardstick that carries across
rounds.
"""
import json
import time

import numpy as np

import jax
import jax.numpy as jnp


def _peak_flops(device) -> float:
    """Best-effort peak bf16 FLOP/s for the device (fallbacks are rough)."""
    kind = getattr(device, "device_kind", "cpu").lower()
    table = {
        "v6e": 918e12, "v6 lite": 918e12, "v5e": 394e12, "v5 lite": 394e12,
        "v5p": 459e12, "v4": 275e12, "v3": 123e12, "v2": 45e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 1e12  # CPU / unknown


def main():
    from paddle_tpu.models import gpt as G
    from paddle_tpu.parallel import parallelize as PZ

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        cfg = G.GPT_SMALL.scaled(max_seq_len=1024, use_flash=True)
        batch, T, steps = 32, 1024, 10
    else:  # CPU smoke path so the bench always produces a line
        cfg = G.GPT_TINY.scaled(num_layers=2)
        batch, T, steps = 4, 32, 3

    pcfg = PZ.ParallelConfig(dp=1, pp=1, tp=1, microbatches=1)
    mesh = PZ.build_mesh(pcfg, devices=[dev])
    params, opt = PZ.init_sharded(jax.random.PRNGKey(0), cfg, pcfg, mesh)
    step = PZ.make_train_step(cfg, pcfg, mesh, lr=1e-4)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (1, batch, T), dtype=np.int32)
    labels = rng.integers(0, cfg.vocab_size, (1, batch, T), dtype=np.int32)

    # warmup (compile)
    params, opt, loss, _ = step(params, opt, tokens, labels)
    float(loss)

    # sync each step: block_until_ready on a chained async queue is not
    # reliable through the remote-TPU tunnel, and fetching the scalar loss
    # costs ~nothing against a full train step
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss, _ = step(params, opt, tokens, labels)
        float(loss)
    dt = time.perf_counter() - t0

    tokens_per_s = steps * batch * T / dt
    n_params = G.num_params(params)
    # fwd+bwd ~= 6 * N FLOPs/token (+ attention term), standard estimate:
    # per layer fwd QK^T + AV = 4*T*d FLOPs/token, x3 for fwd+bwd
    attn = 12 * cfg.num_layers * cfg.d_model * T
    flops_per_token = 6 * n_params + attn
    mfu = tokens_per_s * flops_per_token / _peak_flops(dev)

    print(json.dumps({
        "metric": "gpt_small_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu, 4),
        "detail": {
            "model_params": int(n_params),
            "seq_len": T, "batch": batch, "steps": steps,
            "device": str(getattr(dev, "device_kind", dev.platform)),
            "loss": round(float(loss), 4),
            "mfu": round(mfu, 4),
        },
    }))


if __name__ == "__main__":
    main()
