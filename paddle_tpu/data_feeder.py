"""DataFeeder — convert python/numpy minibatches into Executor feed dicts.

Capability parity with fluid/data_feeder.py: a DataFeeder is constructed from
a feed_list of data Variables and converts an iterable of samples (each a
tuple aligned with feed_list) into {name: batched numpy} with dtype/shape
checks against the Variable declarations.  The reference converts to
LoDTensor on the target place; here the Executor device-puts numpy directly
(XLA owns transfers), so the feeder stops at numpy.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from .framework.core import convert_dtype
from .framework.program import Variable

__all__ = ["DataFeeder", "check_feed_shape_type"]


def _np_dtype(var: Variable):
    return np.dtype(convert_dtype(var.dtype))


def check_feed_shape_type(var: Variable, arr: np.ndarray):
    """Shape/dtype validation like the reference's need_check_feed path
    (framework/executor.py check_feed_shape_type)."""
    declared = list(var.shape)
    actual = list(arr.shape)
    if len(declared) == len(actual):
        for d, a in zip(declared, actual):
            if d not in (-1, None) and d != a:
                raise ValueError(
                    f"feed '{var.name}': declared shape {declared} but got "
                    f"{actual}")
    want = _np_dtype(var)
    if arr.dtype != want:
        # allow safe same-kind casts (int32->int64 etc.), reject e.g. float->int
        if np.can_cast(arr.dtype, want, casting="same_kind"):
            arr = arr.astype(want)
        else:
            raise ValueError(
                f"feed '{var.name}': declared dtype {want} but got {arr.dtype}")
    return arr


class DataFeeder:
    def __init__(self, feed_list: Sequence[Variable], place=None,
                 program=None):
        self.feed_list = list(feed_list)
        self.place = place

    def feed(self, iterable: Sequence[Sequence[Any]]) -> Dict[str, np.ndarray]:
        """iterable: list of samples, each sample aligned with feed_list."""
        cols: List[List[Any]] = [[] for _ in self.feed_list]
        for sample in iterable:
            if len(sample) != len(self.feed_list):
                raise ValueError(
                    f"sample has {len(sample)} fields, feed_list expects "
                    f"{len(self.feed_list)}")
            for c, v in zip(cols, sample):
                c.append(np.asarray(v))
        out: Dict[str, np.ndarray] = {}
        for var, c in zip(self.feed_list, cols):
            # stack WITHOUT casting: check_feed_shape_type below performs the
            # validated same-kind conversion (float fed to an int64 var must
            # raise, not silently truncate)
            arr = np.stack(c)
            # fluid.layers.data declares [-1, d...]; samples may come flat
            want_rank = len(var.shape)
            if arr.ndim == want_rank - 1 and var.shape[-1] == 1:
                arr = arr.reshape(arr.shape + (1,))
            elif arr.ndim < want_rank:
                static = [d for d in var.shape if d not in (-1, None)]
                if static and int(np.prod(arr.shape[1:])) == int(np.prod(static)):
                    arr = arr.reshape((arr.shape[0], *static))
            out[var.name] = check_feed_shape_type(var, arr)
        return out

    def feed_parallel(self, iterable, num_places: int):
        """Split one batch across num_places shards (ParallelExecutor-era
        API, fluid/data_feeder.py feed_parallel)."""
        feeds = self.feed(iterable)
        shards = []
        for i in range(num_places):
            shard = {}
            for k, v in feeds.items():
                shard[k] = np.array_split(v, num_places)[i]
            shards.append(shard)
        return shards
