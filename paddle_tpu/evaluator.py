"""fluid.evaluator — parity with python/paddle/fluid/evaluator.py
(Evaluator:40, ChunkEvaluator:118, EditDistance:197, DetectionMAP:273).

Deprecated in the reference in favor of fluid.metrics, but still part of
the API surface: each evaluator appends its metric op plus accumulator
state updates to the CURRENT main program, and ``eval`` computes the
final value from the carried state.
"""
from __future__ import annotations

import numpy as np

from .framework.executor import global_scope
from .framework.program import (Program, default_main_program,
                                default_startup_program)

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP", "Evaluator"]


class Evaluator:
    """evaluator.py:40 — base: per-pass state vars created in the main
    program and zero-initialized from the startup program; reset() zeroes
    them again between passes."""

    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper_name = name

    def _create_state(self, suffix, dtype, shape):
        main = default_main_program()
        startup = default_startup_program()
        name = f"{self.helper_name}_{suffix}_{len(self.states)}"
        var = main.global_block().create_var(
            name=name, shape=list(shape), dtype=dtype, persistable=True)
        sblock = startup.global_block()
        sblock.create_var(name=name, shape=list(shape), dtype=dtype,
                          persistable=True)
        from .framework.core import VarType, _DTYPE_TO_VARTYPE

        sblock.append_op(
            type="fill_constant", inputs={}, outputs={"Out": [name]},
            attrs={"shape": list(shape), "value": 0.0,
                   "dtype": int(_DTYPE_TO_VARTYPE[dtype])})
        self.states.append(var)
        return var

    def _accumulate(self, state, value):
        """state += value, appended to the main program."""
        main = default_main_program()
        main.global_block().append_op(
            type="elementwise_add",
            inputs={"X": [state.name], "Y": [value.name]},
            outputs={"Out": [state.name]}, attrs={"axis": -1})

    def reset(self, executor, reset_program=None):
        if reset_program is None:
            reset_program = Program()
        from .framework.core import _DTYPE_TO_VARTYPE

        block = reset_program.global_block()
        for var in self.states:
            block.create_var(name=var.name, shape=var.shape,
                             dtype=var.dtype, persistable=True)
            block.append_op(
                type="fill_constant", inputs={},
                outputs={"Out": [var.name]},
                attrs={"shape": [int(s) if s and int(s) > 0 else 1
                                 for s in (var.shape or [1])],
                       "value": 0.0,
                       "dtype": int(_DTYPE_TO_VARTYPE[var.dtype])})
        executor.run(reset_program)

    def _state_np(self, var):
        v = global_scope().find_var(var.name)
        return None if v is None else np.asarray(v)


class ChunkEvaluator(Evaluator):
    """evaluator.py:118 — accumulate chunk_eval counters; eval() ->
    (precision, recall, f1) over the whole pass."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, seq_length=None):
        super().__init__("chunk_evaluator")
        from . import layers

        kwargs = {"chunk_scheme": chunk_scheme,
                  "num_chunk_types": num_chunk_types}
        if excluded_chunk_types:
            kwargs["excluded_chunk_types"] = list(excluded_chunk_types)
        args = [input, label] + ([seq_length] if seq_length is not None
                                 else [])
        (precision, recall, f1, num_infer, num_label, num_correct) = \
            layers.chunk_eval(*args, **kwargs)
        self.num_infer_chunks = self._create_state(
            "num_infer_chunks", "int64", [1])
        self.num_label_chunks = self._create_state(
            "num_label_chunks", "int64", [1])
        self.num_correct_chunks = self._create_state(
            "num_correct_chunks", "int64", [1])
        self._accumulate(self.num_infer_chunks, num_infer)
        self._accumulate(self.num_label_chunks, num_label)
        self._accumulate(self.num_correct_chunks, num_correct)
        self.precision, self.recall, self.f1_score = precision, recall, f1
        self.metrics = [precision, recall, f1]

    def eval(self, executor, eval_program=None):
        infer = float(self._state_np(self.num_infer_chunks)[0])
        lab = float(self._state_np(self.num_label_chunks)[0])
        correct = float(self._state_np(self.num_correct_chunks)[0])
        precision = correct / infer if infer else 0.0
        recall = correct / lab if lab else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if correct else 0.0)
        return np.asarray([precision]), np.asarray([recall]), \
            np.asarray([f1])


class EditDistance(Evaluator):
    """evaluator.py:197 — average edit distance + instance error rate
    accumulated over the pass."""

    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super().__init__("edit_distance")
        from . import layers

        distances, seq_num = layers.edit_distance(
            input, label, ignored_tokens=ignored_tokens)
        self.total_distance = self._create_state(
            "total_distance", "float32", [1])
        self.seq_num = self._create_state("seq_num", "int64", [1])
        self.instance_error = self._create_state(
            "instance_error", "int64", [1])
        batch_dist = layers.reduce_sum(distances)
        batch_err = layers.reduce_sum(
            layers.cast(layers.greater_than(
                distances, layers.fill_constant(
                    shape=[1], dtype=distances.dtype, value=0.0)),
                "int64"))
        main = default_main_program()
        block = main.global_block()
        # reshape the scalar sums to the state shapes, then accumulate
        self._accumulate(self.total_distance,
                         layers.reshape(batch_dist, [1]))
        self._accumulate(self.seq_num, layers.reshape(seq_num, [1]))
        self._accumulate(self.instance_error,
                         layers.reshape(batch_err, [1]))
        self.distances, self.seq_num_batch = distances, seq_num

    def eval(self, executor, eval_program=None):
        total = float(self._state_np(self.total_distance)[0])
        n = float(self._state_np(self.seq_num)[0])
        err = float(self._state_np(self.instance_error)[0])
        if n == 0:
            return np.asarray([0.0]), np.asarray([0.0])
        return np.asarray([total / n], np.float32), \
            np.asarray([err / n], np.float32)


def DetectionMAP(input, gt_label, gt_box, gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral", **kwargs):
    """evaluator.py:273 — delegates to the metrics implementation (the
    reference likewise forwards users there)."""
    from .metrics import DetectionMAP as _M

    return _M(input, gt_label, gt_box, gt_difficult=gt_difficult,
              class_num=class_num, background_label=background_label,
              overlap_threshold=overlap_threshold,
              evaluate_difficult=evaluate_difficult,
              ap_version=ap_version, **kwargs)
