"""fluid.lod_tensor — parity with python/paddle/fluid/lod_tensor.py
(create_lod_tensor:25, create_random_int_lodtensor:100).

The reference packs ragged rows contiguously and carries LoD offsets;
the TPU-native representation is padded [B, Tmax, ...] + explicit
lengths (ops/sequence.py:6). ``LoDTensor`` here is the bridge object:
it exposes the reference surface (recursive_sequence_lengths, lod,
set_lod) while materializing as the padded array (``np.asarray`` /
executor feeds), with ``.lengths`` for the companion length tensor.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor",
           "create_random_int_lodtensor"]


class LoDTensor:
    def __init__(self, padded: np.ndarray, seq_lens: Sequence[int]):
        self._data = np.asarray(padded)
        self._lens = [int(x) for x in seq_lens]

    # -- reference surface -------------------------------------------------
    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [list(self._lens)]

    def lod(self) -> List[List[int]]:
        off = [0]
        for n in self._lens:
            off.append(off[-1] + n)
        return [off]

    def set_lod(self, lod):
        off = lod[0]
        self._lens = [off[i + 1] - off[i] for i in range(len(off) - 1)]

    def set_recursive_sequence_lengths(self, lens):
        self._lens = [int(x) for x in lens[0]]

    def shape(self):
        return list(self._data.shape)

    # -- padded-convention accessors --------------------------------------
    @property
    def lengths(self) -> np.ndarray:
        return np.asarray(self._lens, np.int64)

    def __array__(self, dtype=None):
        return (self._data if dtype is None
                else self._data.astype(dtype))

    def __repr__(self):
        return (f"LoDTensor(padded {self._data.shape} "
                f"{self._data.dtype}, lens={self._lens})")


def create_lod_tensor(data, recursive_seq_lens, place=None) -> LoDTensor:
    """lod_tensor.py:25 — build from a flat [sum(lens), ...] array (or a
    list of rows) + one-level recursive sequence lengths; stored padded."""
    lens = [int(x) for x in recursive_seq_lens[0]]
    if isinstance(data, (list, tuple)):
        rows = [np.asarray(r) for r in data]
        flat = np.concatenate([r.reshape(len(r), -1) for r in rows], axis=0)
    else:
        flat = np.asarray(data)
    if flat.ndim == 1:
        flat = flat[:, None]
    if flat.shape[0] != sum(lens):
        raise ValueError(
            f"data rows {flat.shape[0]} != sum(recursive_seq_lens) "
            f"{sum(lens)}")
    tmax = max(lens) if lens else 0
    out = np.zeros((len(lens), tmax) + flat.shape[1:], flat.dtype)
    s = 0
    for i, n in enumerate(lens):
        out[i, :n] = flat[s:s + n]
        s += n
    return LoDTensor(out, lens)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high) -> LoDTensor:
    """lod_tensor.py:100 — random ints in [low, high]."""
    lens = [int(x) for x in recursive_seq_lens[0]]
    flat = np.random.randint(
        low, high + 1, size=[sum(lens)] + list(base_shape)).astype("int64")
    return create_lod_tensor(flat, recursive_seq_lens, place)
