"""Inference engine — capability parity with paddle/fluid/inference/
(AnalysisPredictor + AnalysisConfig, inference/api/analysis_predictor.cc).

TPU-native design: the reference runs a pruned ProgramDesc through IR fuse
passes and optional TensorRT subgraphs; here the pruned program is lowered
whole into one XLA computation (fusion is XLA's job) and can additionally be
exported as a serialized StableHLO artifact (jax.export) — the deployment
format that replaces paddle_fluid shared-lib packaging.
"""
from .predictor import (  # noqa: F401
    Config,
    Predictor,
    create_predictor,
    export_stablehlo,
    load_stablehlo_predictor,
)

__all__ = ["Config", "Predictor", "create_predictor", "export_stablehlo",
           "load_stablehlo_predictor"]
