"""Minimal model server over an export_stablehlo artifact — the serving
shell the reference exposes through its C API + demo servers
(paddle/fluid/inference/capi/pd_predictor.cc, demo_ci/). TPU-native
deployment artifact = serialized StableHLO (jax.export), so the server is
a ~100-line stdlib HTTP host with zero framework dependency at request
time.

Protocol (JSON):
    GET  /health            -> {"status": "ok", "inputs": [...], ...}
    POST /predict           body {"inputs": {name: nested-list, ...}}
                            -> {"outputs": [nested-list, ...]}

Run:  python -m paddle_tpu.inference.serving --model-dir DIR --port 8866
"""
from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

__all__ = ["ModelServer", "serve"]


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet by default
        if self.server.verbose:
            super().log_message(fmt, *args)

    def _json(self, code: int, obj) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path != "/health":
            return self._json(404, {"error": "unknown path"})
        pred = self.server.predictor
        self._json(200, {"status": "ok",
                         "inputs": pred.get_input_names(),
                         "outputs": pred.get_output_names()})

    def do_POST(self):
        if self.path != "/predict":
            return self._json(404, {"error": "unknown path"})
        n = int(self.headers.get("Content-Length", 0))
        if n > self.server.max_body_bytes:
            return self._json(413, {"error": "body too large"})
        try:
            req = json.loads(self.rfile.read(n).decode())
            feed = {k: np.asarray(v) for k, v in req["inputs"].items()}
            with self.server.lock:          # jax arrays: serialize calls
                outs = self.server.predictor.run(feed)
            self._json(200, {"outputs": [np.asarray(o).tolist()
                                         for o in outs]})
        except Exception as e:
            self._json(400, {"error": f"{type(e).__name__}: {e}"})


class ModelServer:
    """Load a StableHLO export dir (or a save_inference_model dir) and
    serve predictions on localhost."""

    def __init__(self, model_dir: str, port: int = 0, host: str = "127.0.0.1",
                 stablehlo: Optional[bool] = None, verbose: bool = False):
        import os

        if stablehlo is None:
            stablehlo = os.path.exists(os.path.join(model_dir, "model.shlo"))
        if stablehlo:
            from .predictor import load_stablehlo_predictor

            self.predictor = load_stablehlo_predictor(model_dir)
        else:
            from .predictor import Config, create_predictor

            self.predictor = create_predictor(Config(model_dir))
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.predictor = self.predictor
        self.httpd.lock = threading.Lock()
        self.httpd.verbose = verbose
        self.httpd.max_body_bytes = 256 << 20
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def serve(model_dir: str, port: int = 8866, host: str = "127.0.0.1"):
    srv = ModelServer(model_dir, port=port, host=host, verbose=True)
    print(f"serving {model_dir} on http://{host}:{srv.port}")
    try:
        srv.httpd.serve_forever()
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--port", type=int, default=8866)
    ap.add_argument("--host", default="127.0.0.1")
    a = ap.parse_args()
    serve(a.model_dir, a.port, a.host)
