"""Model server — the serving front door over an exported artifact or a
generation engine.

Historically this was a ~100-line stdlib HTTP wrapper around a StableHLO
export (the reference's capi/pd_predictor.cc demo-server parity). It is
now a thin facade over :mod:`paddle_tpu.serving` (docs/serving.md): the
same ``ModelServer``/``serve()`` surface, but requests flow through the
production front door — bounded admission (429 on queue-full), per-request
deadlines (504), JSON error bodies for handler failures (400 client / 500
internal), graceful drain on SIGTERM, and ``paddle_serve_*`` metrics with
a ``/metrics`` exposition endpoint.

Protocol (JSON):
    GET  /health            -> {"status": "ok"|"draining", "inputs": [...]}
    GET  /metrics           -> Prometheus text exposition
    POST /predict           body {"inputs": {name: nested-list, ...}}
                            -> {"outputs": [nested-list, ...]}
    POST /generate          (engine-backed servers) body
                            {"prompt": [ids], "max_new_tokens": N}
                            -> {"tokens": [...], "ttft_ms": ...}

Run:  python -m paddle_tpu.inference.serving --model-dir DIR --port 8866
"""
from __future__ import annotations

import argparse
from typing import Optional

__all__ = ["ModelServer", "serve"]


class ModelServer:
    """Load a StableHLO export dir (or a save_inference_model dir) — or
    wrap an already-built generation engine — and serve on localhost.

    Artifact mode (compat with the pre-ISSUE-9 surface)::

        srv = ModelServer(model_dir).start()     # POST /predict

    Engine mode (docs/serving.md)::

        srv = ModelServer(scheduler=sched).start()   # POST /generate
    """

    def __init__(self, model_dir: Optional[str] = None, port: int = 0,
                 host: str = "127.0.0.1", stablehlo: Optional[bool] = None,
                 verbose: bool = False, scheduler=None,
                 max_queue: int = 64, request_timeout_s: float = 30.0):
        from ..serving.server import FrontDoor

        predictor = None
        if model_dir is not None:
            import os

            if stablehlo is None:
                stablehlo = os.path.exists(
                    os.path.join(model_dir, "model.shlo"))
            if stablehlo:
                from .predictor import load_stablehlo_predictor

                predictor = load_stablehlo_predictor(model_dir)
            else:
                from .predictor import Config, create_predictor

                predictor = create_predictor(Config(model_dir))
        self.predictor = predictor
        self._front = FrontDoor(
            scheduler=scheduler, predictor=predictor, host=host, port=port,
            max_queue=max_queue, request_timeout_s=request_timeout_s,
            verbose=verbose)
        # compat: callers (and the old tests) reach for srv.httpd
        self.httpd = self._front.httpd

    @property
    def port(self) -> int:
        return self._front.port

    @property
    def front(self):
        return self._front

    def start(self):
        self._front.start()
        return self

    def stop(self):
        self._front.stop()

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Refuse new work, finish in-flight requests, then stop."""
        return self._front.drain(timeout_s=timeout_s)

    def install_signal_handlers(self, drain_timeout_s: float = 60.0):
        """SIGTERM/SIGINT -> graceful drain (docs/serving.md runbook)."""
        self._front.install_signal_handlers(drain_timeout_s)
        return self


def serve(model_dir: str, port: int = 8866, host: str = "127.0.0.1"):
    """Thin compat shim: host an artifact dir in the foreground with
    graceful SIGTERM/SIGINT drain installed."""
    srv = ModelServer(model_dir, port=port, host=host, verbose=True)
    srv.install_signal_handlers()
    print(f"serving {model_dir} on http://{host}:{srv.port}")
    srv.start()
    try:
        while srv._front._thread is not None and \
                srv._front._thread.is_alive():
            srv._front._thread.join(timeout=0.5)
    except KeyboardInterrupt:
        srv.drain(timeout_s=10.0)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--port", type=int, default=8866)
    ap.add_argument("--host", default="127.0.0.1")
    a = ap.parse_args()
    serve(a.model_dir, a.port, a.host)
