"""Predictor — AnalysisPredictor/AnalysisConfig parity on XLA.

Reference surface (inference/api/paddle_api.h, analysis_predictor.cc):
  config = Config(model_dir)            # AnalysisConfig
  predictor = create_predictor(config)
  predictor.run({"x": batch})           # ZeroCopy-style dict in/out
Plus the TPU-native export path: ``export_stablehlo`` serializes the pruned
program (params baked in) via jax.export for serving without Python graph
machinery.
"""
from __future__ import annotations

import json
import os
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import io as fluid_io
from ..framework.core import XLAPlace, dtype_to_jax
from ..framework.executor import Executor, Scope
from ..framework.program import Program

__all__ = ["Config", "Predictor", "create_predictor", "export_stablehlo",
           "load_stablehlo_predictor"]


class Config:
    """AnalysisConfig parity (subset: model paths + precision switches)."""

    def __init__(self, model_dir: Optional[str] = None,
                 prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._bf16 = False
        self._memory_optimize = True  # XLA always does this; kept for parity

    def enable_bf16(self):
        """Low-precision inference — reference enable_mkldnn_bfloat16 /
        TensorRT fp16 analogues; on TPU this is the MXU-native mode."""
        self._bf16 = True

    def switch_ir_optim(self, flag: bool = True):
        pass  # XLA always optimizes; accepted for parity

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optimize = flag


class Predictor:
    def __init__(self, program: Program, feed_names: List[str],
                 fetch_names: List[str], scope: Scope,
                 bf16: bool = False):
        if bf16:
            from ..contrib.mixed_precision import cast_model_to_fp16
            cast_model_to_fp16(program, dest_dtype="bfloat16")
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        self._scope = scope
        self._exe = Executor(XLAPlace(0))
        # served programs are first-class observability citizens: the
        # executor's program report / recompile-explainer lines carry a
        # recognizable serving label instead of the "<fetch>#Nops" default
        program._annotations.setdefault(
            "report_name",
            f"predict/{fetch_names[0] if fetch_names else 'main'}")

    # -- reference API surface ---------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def run(self, feed: Optional[Dict[str, Any]] = None) -> List[np.ndarray]:
        """Dict form runs directly; zero-copy form (run() with no args)
        consumes inputs staged via get_input_handle().copy_from_cpu(), like
        the reference AnalysisPredictor::ZeroCopyRun
        (api_impl.cc / analysis_predictor.cc)."""
        if feed is None:
            feed = dict(getattr(self, "_staged", {}))
        missing = set(self._feed_names) - set(feed)
        if missing:
            raise ValueError(f"missing inputs: {sorted(missing)}")
        outs = self._exe.run(self._program,
                             feed={k: feed[k] for k in self._feed_names},
                             fetch_list=self._fetch_names, scope=self._scope)
        self._outputs = dict(zip(self._fetch_names, outs))
        return outs

    # -- zero-copy handle surface (get_input_tensor/get_input_handle) ------
    class _Handle:
        def __init__(self, pred, name, is_input):
            self._pred, self._name, self._is_input = pred, name, is_input

        def copy_from_cpu(self, arr):
            if not self._is_input:
                raise ValueError("cannot write an output handle")
            staged = self._pred.__dict__.setdefault("_staged", {})
            staged[self._name] = np.asarray(arr)

        def reshape(self, shape):
            pass  # shapes come from the staged array

        def copy_to_cpu(self) -> np.ndarray:
            outs = getattr(self._pred, "_outputs", None)
            if outs is None or self._name not in outs:
                raise RuntimeError("run() has not produced this output yet")
            return np.asarray(outs[self._name])

    def get_input_handle(self, name: str) -> "Predictor._Handle":
        if name not in self._feed_names:
            raise KeyError(name)
        return Predictor._Handle(self, name, True)

    def get_output_handle(self, name: str) -> "Predictor._Handle":
        if name not in self._fetch_names:
            raise KeyError(name)
        return Predictor._Handle(self, name, False)

    # 1.8 zero-copy spelling (analysis_predictor.cc GetInputTensor)
    get_input_tensor = get_input_handle
    get_output_tensor = get_output_handle

    def zero_copy_run(self):
        return self.run()

    @property
    def program(self) -> Program:
        return self._program


def create_predictor(config: Config) -> Predictor:
    if not config.model_dir:
        raise ValueError("Config.model_dir is required")
    exe = Executor(XLAPlace(0))
    scope = Scope()
    from ..framework.executor import scope_guard
    with scope_guard(scope):
        program, feed_names, fetch_vars = fluid_io.load_inference_model(
            config.model_dir, exe, model_filename=config.prog_file,
            params_filename=config.params_file)
    fetch_names = [v.name if hasattr(v, "name") else str(v)
                   for v in fetch_vars]
    return Predictor(program, feed_names, fetch_names, scope,
                     bf16=config._bf16)


# ---------------------------------------------------------------------------
# StableHLO export of a static program
# ---------------------------------------------------------------------------

def _program_fn(program: Program, feed_names: Sequence[str],
                fetch_names: Sequence[str], scope: Scope):
    """Build fn(feeds_tuple) -> fetches_tuple with params baked as constants
    from scope (deployment artifact = weights frozen, reference
    save_inference_model semantics)."""
    from ..framework.registry import LowerCtx, run_lowering

    block = program.global_block()
    params = {}
    for name, var in block.vars.items():
        if var.persistable:
            v = scope.find_var(name)
            if v is not None:
                params[name] = jnp.asarray(v)

    def fn(*feed_vals):
        env: Dict[str, Any] = dict(params)
        env.update(dict(zip(feed_names, feed_vals)))
        ctx = LowerCtx(program, block, env,
                       rng_key=jax.random.PRNGKey(0), mesh_axes={})
        for op in block.ops:
            run_lowering(ctx, op)
        return tuple(env[n] for n in fetch_names)

    return fn


def export_stablehlo(dirname: str, program: Program,
                     feed_specs: Dict[str, Any], fetch_names: Sequence[str],
                     scope: Optional[Scope] = None):
    """Serialize the program as StableHLO bytes + meta.

    feed_specs: name -> (shape, dtype) or an example ndarray.
    """
    from jax import export as jexport
    from ..framework.executor import global_scope

    scope = scope or global_scope()
    feed_names = sorted(feed_specs)
    # export only the feed->fetch slice (reference prune.cc before save)
    program = fluid_io.prune_program(program, list(feed_names),
                                     list(fetch_names))
    sds = []
    for n in feed_names:
        spec = feed_specs[n]
        if hasattr(spec, "shape"):
            sds.append(jax.ShapeDtypeStruct(tuple(spec.shape),
                                            np.asarray(spec).dtype))
        else:
            shape, dtype = spec
            sds.append(jax.ShapeDtypeStruct(
                tuple(int(d) for d in shape), dtype_to_jax(dtype)))
    fn = _program_fn(program, feed_names, list(fetch_names), scope)
    exp = jexport.export(jax.jit(fn))(*sds)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "model.shlo"), "wb") as f:
        f.write(exp.serialize())
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump({"feed_names": feed_names,
                   "fetch_names": list(fetch_names)}, f)
    return exp


class StableHLOPredictor:
    """Runs a serialized StableHLO artifact — no Program machinery needed.

    Serving-path discipline (ISSUE 9 satellite): calls dispatch through a
    per-signature AOT-compiled executable (the PR 1 steady-state shape:
    compile once, then a dict hit per request) instead of re-tracing
    ``exported.call`` every time, and every compile emits a PR 4 program
    report plus a recompile-explainer line when a signature churns — a
    shape-unstable client shows up in ``paddle_recompiles_total`` exactly
    like a shape-unstable training loop would.
    """

    _MAX_EXECUTABLES = 64   # per-signature cache bound (bucketed clients)

    def __init__(self, exported, feed_names, fetch_names,
                 name: str = "stablehlo"):
        self._exported = exported
        self._feed_names = feed_names
        self._fetch_names = fetch_names
        self._report_name = f"serve/{name}"
        # LRU: hits move to the end, overflow evicts only the coldest
        # signature (a wholesale clear would recompile every warm shape)
        self._compiled: "OrderedDict[tuple, Any]" = OrderedDict()
        self._sig_history: List[dict] = []

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def _executable(self, vals):
        import time

        from ..observability import program_report as _prep

        key = tuple((tuple(v.shape), str(v.dtype)) for v in vals)
        exe = self._compiled.get(key)
        if exe is not None:
            self._compiled.move_to_end(key)
            return exe
        sig = _prep.make_sig(
            [(n, tuple(v.shape), str(v.dtype))
             for n, v in zip(self._feed_names, vals)], self._fetch_names)
        if self._sig_history:
            cause, detail = _prep.explain_recompile(sig, self._sig_history)
            _prep.note_recompile(self._report_name, cause, detail)
        self._sig_history.append(sig)
        del self._sig_history[:-8]
        t0 = time.perf_counter_ns()
        exe = jax.jit(self._exported.call).lower(*vals).compile()
        _prep.capture(
            self._report_name, compiled=exe,
            compile_ms=(time.perf_counter_ns() - t0) / 1e6,
            inputs=list(vals),
            extra={"feeds": list(self._feed_names),
                   "fetches": list(self._fetch_names)})
        if len(self._compiled) >= self._MAX_EXECUTABLES:
            self._compiled.popitem(last=False)
        self._compiled[key] = exe
        return exe

    def run(self, feed: Dict[str, Any]) -> List[np.ndarray]:
        vals = [jnp.asarray(feed[n]) for n in self._feed_names]
        outs = self._executable(vals)(*vals)
        return [np.asarray(o) for o in outs]


def load_stablehlo_predictor(dirname: str) -> StableHLOPredictor:
    from jax import export as jexport
    with open(os.path.join(dirname, "model.shlo"), "rb") as f:
        exp = jexport.deserialize(f.read())
    with open(os.path.join(dirname, "meta.json")) as f:
        meta = json.load(f)
    return StableHLOPredictor(exp, meta["feed_names"], meta["fetch_names"])
