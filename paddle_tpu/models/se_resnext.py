"""SE-ResNeXt-50/101/152 — the reference's flagship distributed-test model
(python/paddle/fluid/tests/unittests/dist_se_resnext.py:54 SE_ResNeXt),
rebuilt in the fluid layer style: grouped 3x3 (cardinality) bottlenecks with
squeeze-and-excitation channel gating.

TPU notes: grouped convs lower through lax.conv feature_group_count; the
SE gate is two tiny fcs + broadcast multiply — pure fusion food for XLA.
"""
from __future__ import annotations

import math

from .. import layers
from ..framework.param_attr import ParamAttr

__all__ = ["SE_ResNeXt", "se_resnext50", "se_resnext101", "se_resnext152"]

_CFG = {
    50: (32, [3, 4, 6, 3], [128, 256, 512, 1024]),
    101: (32, [3, 4, 23, 3], [128, 256, 512, 1024]),
    152: (64, [3, 8, 36, 3], [128, 256, 512, 1024]),
}


class SE_ResNeXt:
    def __init__(self, layers_: int = 50, prefix: str = "se"):
        if layers_ not in _CFG:
            raise ValueError(f"supported layers are {sorted(_CFG)}, "
                             f"got {layers_}")
        self.layers = layers_
        self.prefix = prefix
        self._n = 0

    def _name(self, tag):
        self._n += 1
        return f"{self.prefix}_{tag}{self._n}"

    def conv_bn_layer(self, input, num_filters, filter_size, stride=1,
                      groups=1, act=None, is_test=False):
        name = self._name("conv")
        conv = layers.conv2d(
            input, num_filters, filter_size, stride=stride,
            padding=(filter_size - 1) // 2, groups=groups,
            param_attr=ParamAttr(name=name + "_w"), bias_attr=False,
            name=name)
        return layers.batch_norm(conv, act=act, is_test=is_test,
                                 param_attr=ParamAttr(name=name + "_bn_s"),
                                 bias_attr=ParamAttr(name=name + "_bn_b"),
                                 moving_mean_name=name + "_bn_mean",
                                 moving_variance_name=name + "_bn_var")

    def squeeze_excitation(self, input, num_channels, reduction_ratio,
                           is_test=False):
        pool = layers.pool2d(input, pool_type="avg", global_pooling=True)
        stdv = 1.0 / math.sqrt(pool.shape[1] * 1.0)
        from ..framework.initializer import UniformInitializer

        squeeze = layers.fc(
            pool, size=num_channels // reduction_ratio, act="relu",
            param_attr=ParamAttr(
                name=self._name("sq") + "_w",
                initializer=UniformInitializer(-stdv, stdv)))
        stdv = 1.0 / math.sqrt(squeeze.shape[1] * 1.0)
        excitation = layers.fc(
            squeeze, size=num_channels, act="sigmoid",
            param_attr=ParamAttr(
                name=self._name("ex") + "_w",
                initializer=UniformInitializer(-stdv, stdv)))
        return layers.elementwise_mul(input, excitation, axis=0)

    def shortcut(self, input, ch_out, stride, is_test=False):
        ch_in = input.shape[1]
        if ch_in != ch_out or stride != 1:
            return self.conv_bn_layer(input, ch_out, 1, stride,
                                      is_test=is_test)
        return input

    def bottleneck_block(self, input, num_filters, stride, cardinality,
                         reduction_ratio, is_test=False):
        conv0 = self.conv_bn_layer(input, num_filters, 1, act="relu",
                                   is_test=is_test)
        conv1 = self.conv_bn_layer(conv0, num_filters, 3, stride=stride,
                                   groups=cardinality, act="relu",
                                   is_test=is_test)
        conv2 = self.conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                                   is_test=is_test)
        scale = self.squeeze_excitation(conv2, num_filters * 2,
                                        reduction_ratio, is_test=is_test)
        short = self.shortcut(input, num_filters * 2, stride,
                              is_test=is_test)
        return layers.relu(short + scale)

    def net(self, input, class_dim: int = 1000, is_test: bool = False,
            dropout_prob: float = 0.2):
        cardinality, depth, num_filters = _CFG[self.layers]
        reduction_ratio = 16
        if self.layers == 152:
            conv = self.conv_bn_layer(input, 64, 3, stride=2, act="relu",
                                      is_test=is_test)
            conv = self.conv_bn_layer(conv, 64, 3, act="relu",
                                      is_test=is_test)
            conv = self.conv_bn_layer(conv, 128, 3, act="relu",
                                      is_test=is_test)
        else:
            conv = self.conv_bn_layer(input, 64, 7, stride=2, act="relu",
                                      is_test=is_test)
        conv = layers.pool2d(conv, pool_size=3, pool_stride=2,
                             pool_padding=1, pool_type="max")
        for block in range(len(depth)):
            for i in range(depth[block]):
                conv = self.bottleneck_block(
                    conv, num_filters[block],
                    stride=2 if i == 0 and block != 0 else 1,
                    cardinality=cardinality,
                    reduction_ratio=reduction_ratio, is_test=is_test)
        pool = layers.pool2d(conv, pool_type="avg", global_pooling=True)
        drop = layers.dropout(pool, dropout_prob=dropout_prob,
                              is_test=is_test)
        from ..framework.initializer import ConstantInitializer

        return layers.fc(drop, size=class_dim, act="softmax",
                         param_attr=ParamAttr(
                             name=self.prefix + "_fc_w",
                             initializer=ConstantInitializer(0.05)))


def se_resnext50(input, class_dim=1000, **kw):
    return SE_ResNeXt(50).net(input, class_dim, **kw)


def se_resnext101(input, class_dim=1000, **kw):
    return SE_ResNeXt(101).net(input, class_dim, **kw)


def se_resnext152(input, class_dim=1000, **kw):
    return SE_ResNeXt(152).net(input, class_dim, **kw)
