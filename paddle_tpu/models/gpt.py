"""Flagship GPT decoder — pure JAX, designed for TPU mesh execution.

The reference has no GPT implementation (2020-era); its largest NLP config is
ERNIE/transformer encoder (python/paddle/fluid/tests/unittests/dist_transformer.py).
This model is the north-star GPT-3-style decoder (BASELINE.md: GPT-3-1.3B
pipeline+tensor parallel) built TPU-first:

- parameters are a flat pytree with per-layer leaves stacked on a leading
  ``num_layers`` axis so the layer loop is a single ``lax.scan`` (one XLA
  While, compiled once per layer shape — no unrolled 48-layer HLO),
- every leaf has a declared :class:`jax.sharding.PartitionSpec` over the
  ``(dp, pp, tp)`` mesh (see :mod:`paddle_tpu.parallel.parallelize` for the
  shard_map execution engine: GPipe over pp, Megatron TP + sequence
  parallelism over tp, data parallel over dp),
- compute dtype is configurable (bf16 by default on TPU — MXU-native),
  master params stay f32.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    num_layers: int = 24
    num_heads: int = 16
    d_model: int = 2048
    d_ff: int = 8192
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16   # compute dtype (params stay f32)
    remat: bool = True          # jax.checkpoint each block (HBM <-> FLOPs)
    # named policy from paddle_tpu.parallel.remat: none|full|dots|
    # save_only_flash ("full" recomputes everything, "dots" saves matmul
    # outputs and recomputes elementwise, "save_only_flash" saves only the
    # tagged attention outputs). Old spellings remain valid aliases.
    remat_policy: str = "full"
    use_flash: bool = False     # Pallas flash-attention kernel on TPU
    # True: one lax.scan over the stacked layer axis (HLO size O(1) in
    # depth — right for 48-layer configs). False: unroll the layer loop in
    # the trace; at bench depths (6-12 layers) this removes the scan's
    # per-iteration weight dynamic-slice copies and the backward's
    # dynamic-update-slice grad accumulation, both measured as top sinks in
    # PROFILE_STEP.json on v5e.
    scan_layers: bool = True
    # chunked-CE threshold: f32 logits above this never materialize
    # (ce_from_hidden); lower it to trade ~1/6 vocab-head FLOPs for HBM
    # headroom (e.g. to fit no-remat training)
    ce_direct_bytes_limit: int = 4 << 30
    # rows per CE chunk: bigger chunks = fewer, larger (more MXU-efficient)
    # vocab matmuls in the scan, at chunk*V*4 bytes of live logits each
    ce_chunk: int = 2048
    # columns per CE vocab chunk: >0 additionally blocks the vocab axis with
    # an online-logsumexp forward + chunked custom_vjp backward
    # (ops/pallas_kernels.chunked_lm_loss) so even one row-chunk's logits
    # never materialize at full vocab width
    ce_vocab_chunk: int = 0
    # route every block layernorm (and the residual+bias add feeding ln2)
    # through ops/pallas_kernels.fused_ln — one Pallas launch fwd, one bwd,
    # instead of the add/layernorm small-fusion residue ATTRIBUTION.json
    # ranks (docs/kernels.md). Opt-in: interpret-mode Pallas is slower
    # than XLA off-TPU.
    fused_ln: bool = False

    def __post_init__(self):
        from ..parallel import remat as remat_mod

        # validates the name (old spellings resolve as aliases)
        remat_mod.resolve(self.remat_policy)

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.num_heads == 0
        return self.d_model // self.num_heads

    def scaled(self, **kw) -> "GPTConfig":
        return dataclasses.replace(self, **kw)


# 124M-ish config for single-chip benches; tiny config for tests/dryruns.
GPT_SMALL = GPTConfig(vocab_size=50304, max_seq_len=1024, num_layers=12,
                      num_heads=12, d_model=768, d_ff=3072)
GPT_TINY = GPTConfig(vocab_size=256, max_seq_len=64, num_layers=4,
                     num_heads=4, d_model=64, d_ff=128, dtype=jnp.float32,
                     remat=False)


def init_params(key, cfg: GPTConfig) -> Dict[str, Any]:
    """GPT-2-style init. Per-layer leaves are stacked on axis 0 (num_layers).

    QKV is stored as [L, D, 3, nh, hd] and the output projection as
    [L, nh, hd, D] so tensor parallelism shards the *head* dimension — the
    natural Megatron split (column-parallel QKV, row-parallel proj).
    """
    L, D, F = cfg.num_layers, cfg.d_model, cfg.d_ff
    nh, hd, V = cfg.num_heads, cfg.head_dim, cfg.vocab_size
    ks = jax.random.split(key, 8)
    std = 0.02
    resid_std = std / math.sqrt(2 * L)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape) * s).astype(jnp.float32)

    return {
        "wte": norm(ks[0], (V, D)),
        "wpe": norm(ks[1], (cfg.max_seq_len, D), s=0.01),
        "lm_head": norm(ks[2], (D, V)),
        "ln_f_scale": jnp.ones((D,), jnp.float32),
        "ln_f_bias": jnp.zeros((D,), jnp.float32),
        "blocks": {
            "ln1_scale": jnp.ones((L, D), jnp.float32),
            "ln1_bias": jnp.zeros((L, D), jnp.float32),
            "w_qkv": norm(ks[3], (L, D, 3, nh, hd)),
            "b_qkv": jnp.zeros((L, 3, nh, hd), jnp.float32),
            "w_proj": norm(ks[4], (L, nh, hd, D), s=resid_std),
            "b_proj": jnp.zeros((L, D), jnp.float32),
            "ln2_scale": jnp.ones((L, D), jnp.float32),
            "ln2_bias": jnp.zeros((L, D), jnp.float32),
            "w_fc": norm(ks[5], (L, D, F)),
            "b_fc": jnp.zeros((L, F), jnp.float32),
            "w_out": norm(ks[6], (L, F, D), s=resid_std),
            "b_out": jnp.zeros((L, D), jnp.float32),
        },
    }


def param_specs(cfg: GPTConfig, pp: str = "pp", tp: str = "tp") -> Dict[str, Any]:
    """PartitionSpec per leaf over mesh axes (pp, tp). dp never shards params.

    Block leaves are stage-sharded on the stacked layer axis (pp) and
    head/ffn-sharded (tp) where Megatron splits them; embeddings / final
    ln / head are replicated (they live on every stage — grads from unused
    stages are exactly zero, see parallelize.py psum rule).
    """
    return {
        "wte": P(),
        "wpe": P(),
        "lm_head": P(),
        "ln_f_scale": P(),
        "ln_f_bias": P(),
        "blocks": {
            "ln1_scale": P(pp, None),
            "ln1_bias": P(pp, None),
            "w_qkv": P(pp, None, None, tp, None),
            "b_qkv": P(pp, None, tp, None),
            "w_proj": P(pp, tp, None, None),
            "b_proj": P(pp, None),
            "ln2_scale": P(pp, None),
            "ln2_bias": P(pp, None),
            "w_fc": P(pp, None, tp),
            "b_fc": P(pp, tp),
            "w_out": P(pp, tp, None),
            "b_out": P(pp, None),
        },
    }


def _layer_norm(x, scale, bias, eps=1e-5):
    # the named_scope lands in every HLO instruction's metadata op_name —
    # forward AND grad ops — so the roofline attribution's residue
    # ranking (observability/attribution.py) names the layernorm tail
    # instead of an anonymous elementwise fusion
    with jax.named_scope("layer_norm"):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + eps)
        return (y * scale + bias).astype(x.dtype)


def _causal_attention(q, k, v, cfg: GPTConfig):
    """q,k,v: [B, T, nh, hd] -> [B, T, nh, hd]. Plain XLA path; the Pallas
    flash kernel (ops/pallas_kernels.py) replaces this on TPU when
    cfg.use_flash — same signature, tiled online-softmax in VMEM."""
    from ..parallel import remat as remat_mod

    if cfg.use_flash:
        from ..ops.pallas_kernels import flash_attention

        # tagged so the save_only_flash remat policy can keep exactly these
        return remat_mod.checkpoint_name(flash_attention(q, k, v, causal=True))
    T = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = jnp.tril(jnp.ones((T, T), jnp.bool_))
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return remat_mod.checkpoint_name(jnp.einsum("bhqk,bkhd->bqhd", probs, v))


def block_fn(p, x, cfg: GPTConfig, tp_axis: Optional[str] = None):
    """One transformer block. ``p`` holds this layer's leaves (no L axis —
    possibly tp-local shards when run under shard_map).

    With ``tp_axis`` the activation ``x`` arrives *sequence-sharded*
    ([B, T/tp, D], Megatron sequence parallelism): all_gather(seq) before the
    matmuls, reduce_scatter(seq) after the row-parallel ones. Biases are added
    on the sequence-sharded side so every bias grad is a partial sum over tp
    (parallelize.py relies on this for its uniform grad-psum rule).
    """
    dt = cfg.dtype

    def gather(y):
        if tp_axis is None:
            return y
        return jax.lax.all_gather(y, tp_axis, axis=1, tiled=True)

    def scatter_sum(y):
        if tp_axis is None:
            return y
        return jax.lax.psum_scatter(y, tp_axis, scatter_dimension=1, tiled=True)

    if cfg.fused_ln:
        from ..ops.pallas_kernels import fused_ln as _fln

    # --- attention ---
    if cfg.fused_ln:
        h = _fln(x, p["ln1_scale"], p["ln1_bias"], eps=1e-5)
    else:
        h = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
    h = gather(h)                                     # [B, T, D]
    qkv = jnp.einsum("btd,dcnh->btcnh", h, p["w_qkv"].astype(dt))
    qkv = qkv + p["b_qkv"].astype(dt)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    a = _causal_attention(q, k, v, cfg)               # [B, T, nh_local, hd]
    o = jnp.einsum("btnh,nhd->btd", a, p["w_proj"].astype(dt))
    o = scatter_sum(o)                                # [B, T/tp, D]

    # --- mlp ---
    if cfg.fused_ln:
        # one launch for the (x + o) + b_proj residual AND ln2; the summed
        # stream comes back as the next residual input
        h, x = _fln(o, p["ln2_scale"], p["ln2_bias"], residual=x,
                    bias_add=p["b_proj"].astype(dt), eps=1e-5,
                    return_residual=True)
    else:
        x = x + o + p["b_proj"].astype(dt)
        h = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
    h = gather(h)
    h = jnp.einsum("btd,df->btf", h, p["w_fc"].astype(dt)) + p["b_fc"].astype(dt)
    h = jax.nn.gelu(h, approximate=True)
    o = jnp.einsum("btf,fd->btd", h, p["w_out"].astype(dt))
    o = scatter_sum(o)
    x = x + o + p["b_out"].astype(dt)
    return x


def run_blocks(blocks, x, cfg: GPTConfig, tp_axis: Optional[str] = None):
    """lax.scan over the stacked layer axis of ``blocks``."""
    from ..parallel import remat as remat_mod

    policy = remat_mod.resolve(cfg.remat_policy, remat=cfg.remat)
    f = policy.wrap(block_fn, static_argnums=(2, 3))

    if not cfg.scan_layers:
        L = jax.tree_util.tree_leaves(blocks)[0].shape[0]
        for i in range(L):
            layer_p = jax.tree_util.tree_map(lambda a: a[i], blocks)
            x = f(layer_p, x, cfg, tp_axis)
        return x

    def body(h, layer_p):
        return f(layer_p, h, cfg, tp_axis), None

    x, _ = jax.lax.scan(body, x, blocks)
    return x


def embed(p, tokens, cfg: GPTConfig, pos_offset=0):
    """tokens [B, T] -> [B, T, D] (compute dtype)."""
    T = tokens.shape[1]
    pos = pos_offset + jnp.arange(T)
    x = p["wte"][tokens] + p["wpe"][pos]
    return x.astype(cfg.dtype)


def _final_ln(p, x, cfg: GPTConfig):
    if cfg.fused_ln:
        from ..ops.pallas_kernels import fused_ln as _fln

        return _fln(x, p["ln_f_scale"], p["ln_f_bias"], eps=1e-5)
    return _layer_norm(x, p["ln_f_scale"], p["ln_f_bias"])


def logits_fn(p, x, cfg: GPTConfig):
    x = _final_ln(p, x, cfg)
    return jnp.einsum("btd,dv->btv", x, p["lm_head"].astype(cfg.dtype))


def forward(params, tokens, cfg: GPTConfig):
    """Single-device (or GSPMD) forward: tokens [B, T] -> logits [B, T, V]."""
    x = embed(params, tokens, cfg)
    x = run_blocks(params["blocks"], x, cfg)
    return logits_fn(params, x, cfg)


def token_ce(logits, labels, valid=None):
    """Summed (not mean) token cross-entropy in f32 — callers normalize, so
    distributed shards can psum partial sums. ``valid`` masks padding rows.

    lse - gold instead of materializing log_softmax: the full [B,T,V] f32
    log-prob tensor (3+ GB at GPT-scale vocab) never hits HBM; the cast
    fuses into the logsumexp reduction.
    """
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)                       # [B,T]
    gold = jnp.take_along_axis(logits, labels[..., None],
                               axis=-1)[..., 0]                   # [B,T]
    ce = lse - gold
    if valid is not None:
        ce = jnp.where(valid, ce, 0.0)
    return jnp.sum(ce)


def ce_from_hidden(params, x, labels, cfg: GPTConfig,
                   chunk: Optional[int] = None,
                   direct_bytes_limit: Optional[int] = None):
    """Summed token CE straight from hidden states, chunked over rows so the
    full [rows, V] logits tensor never materializes (at GPT vocab sizes the
    f32 logits alone are gigabytes — the usual OOM at wide batch). Each
    chunk recomputes its logits in the backward (jax.checkpoint), costing
    one extra [chunk, D] x [D, V] matmul per chunk (~1/6 of the vocab-head
    FLOPs) for an S-fold cut in live logits memory."""
    if chunk is None:
        chunk = cfg.ce_chunk
    if direct_bytes_limit is None:
        direct_bytes_limit = cfg.ce_direct_bytes_limit
    head = params["lm_head"]
    B, T, D = x.shape
    V = head.shape[-1]
    x = _final_ln(params, x, cfg)
    rows = x.reshape(B * T, D)
    labs = labels.reshape(B * T)
    n = rows.shape[0]
    if cfg.ce_vocab_chunk:
        # vocab-blocked online-logsumexp CE: neither the row-chunk nor the
        # full [rows, V] logits ever materialize (Pallas-tiled on TPU,
        # pure-lax elsewhere)
        from ..ops.pallas_kernels import chunked_lm_loss

        return chunked_lm_loss(
            rows, head.astype(cfg.dtype), labs,
            vocab_chunk=cfg.ce_vocab_chunk, row_chunk=chunk)
    # direct path when the f32 logits comfortably fit (chunking buys memory
    # at ~1/6 extra vocab-head FLOPs — not worth it below ~4 GiB, a quarter
    # of v5e HBM)
    if n * V * 4 <= direct_bytes_limit:
        logits = jnp.einsum("btd,dv->btv", x, head.astype(cfg.dtype))
        return token_ce(logits, labels)
    pad = (-n) % chunk
    if pad:  # remainder rows are masked out of the sum
        rows = jnp.concatenate([rows, jnp.zeros((pad, D), rows.dtype)])
        labs = jnp.concatenate([labs, jnp.zeros((pad,), labs.dtype)])
    valid = (jnp.arange(n + pad) < n).reshape(-1, chunk)

    @jax.checkpoint
    def chunk_ce(xc, lc, vc):
        logits = jnp.einsum("rd,dv->rv", xc, head.astype(cfg.dtype))
        return token_ce(logits, lc, valid=vc)

    def body(acc, args):
        return acc + chunk_ce(*args), None

    xcs = rows.reshape(-1, chunk, D)
    lcs = labs.reshape(-1, chunk)
    total, _ = jax.lax.scan(body, jnp.float32(0.0), (xcs, lcs, valid))
    return total


def loss_fn(params, tokens, labels, cfg: GPTConfig):
    """Mean next-token loss, single-device semantics."""
    x = embed(params, tokens, cfg)
    x = run_blocks(params["blocks"], x, cfg)
    return ce_from_hidden(params, x, labels, cfg) / labels.size


def num_params(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def train_flops_per_token(cfg: GPTConfig, n_params: int, T: int) -> float:
    """Analytic fwd+bwd FLOPs per trained token: the standard 6N estimate
    plus the attention term (per layer fwd QK^T + AV = 4*T*d FLOPs/token,
    x3 fwd+bwd). Shared by bench.py and the TrainMonitor so every MFU
    number uses the same numerator."""
    return 6 * n_params + 12 * cfg.num_layers * cfg.d_model * T
