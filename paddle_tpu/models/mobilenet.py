"""MobileNetV1/V2 — static-graph builders in the fluid layer style.

Depthwise convs hit the conv2d lowering with feature_group_count == channels
(paddle_tpu/ops/nn.py conv2d/depthwise_conv2d); XLA lowers grouped convs to
the TPU conv unit directly.
"""
from __future__ import annotations

from .. import layers
from ..framework.param_attr import ParamAttr

__all__ = ["mobilenet_v1", "mobilenet_v2", "MobileNet"]


def _conv_bn(x, num_filters, filter_size, stride=1, groups=1, act="relu",
             is_test=False, name: str = ""):
    x = layers.conv2d(
        x, num_filters, filter_size, stride=stride,
        padding=(filter_size - 1) // 2, groups=groups,
        param_attr=ParamAttr(name=name + "_weights"), bias_attr=False,
        name=name + ".conv")
    return layers.batch_norm(
        x, act=act, is_test=is_test,
        param_attr=ParamAttr(name=name + "_bn_scale"),
        bias_attr=ParamAttr(name=name + "_bn_offset"),
        moving_mean_name=name + "_bn_mean",
        moving_variance_name=name + "_bn_variance")


def _depthwise_separable(x, ch_out, stride, scale, is_test, name):
    ch_in = x.shape[1]
    x = _conv_bn(x, ch_in, 3, stride=stride, groups=ch_in, is_test=is_test,
                 name=name + "_dw")
    return _conv_bn(x, int(ch_out * scale), 1, is_test=is_test,
                    name=name + "_sep")


def mobilenet_v1(input, class_dim: int = 1000, scale: float = 1.0,
                 is_test: bool = False, prefix: str = "mbv1"):
    s = lambda c: int(c * scale)
    x = _conv_bn(input, s(32), 3, stride=2, is_test=is_test,
                 name=prefix + "_conv1")
    cfg = [  # (ch_out, stride)
        (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
        (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1),
    ]
    for i, (ch, st) in enumerate(cfg):
        x = _depthwise_separable(x, ch, st, scale, is_test,
                                 f"{prefix}_ds{i + 2}")
    x = layers.pool2d(x, pool_type="avg", global_pooling=True)
    return layers.fc(x, class_dim,
                     param_attr=ParamAttr(name=prefix + "_fc_weights"),
                     bias_attr=ParamAttr(name=prefix + "_fc_offset"))


def _inverted_residual(x, ch_out, stride, expansion, is_test, name):
    ch_in = x.shape[1]
    hidden = ch_in * expansion
    y = x
    if expansion != 1:
        y = _conv_bn(y, hidden, 1, act="relu6", is_test=is_test,
                     name=name + "_expand")
    y = _conv_bn(y, hidden, 3, stride=stride, groups=hidden, act="relu6",
                 is_test=is_test, name=name + "_dw")
    y = _conv_bn(y, ch_out, 1, act=None, is_test=is_test,
                 name=name + "_project")
    if stride == 1 and ch_in == ch_out:
        return layers.elementwise_add(x, y)
    return y


def mobilenet_v2(input, class_dim: int = 1000, scale: float = 1.0,
                 is_test: bool = False, prefix: str = "mbv2"):
    s = lambda c: max(8, int(c * scale))
    x = _conv_bn(input, s(32), 3, stride=2, act="relu6", is_test=is_test,
                 name=prefix + "_conv1")
    cfg = [  # (expansion, ch_out, repeats, stride)
        (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
    ]
    idx = 0
    for t, c, n, st in cfg:
        for i in range(n):
            x = _inverted_residual(x, s(c), st if i == 0 else 1, t, is_test,
                                   f"{prefix}_ir{idx}")
            idx += 1
    x = _conv_bn(x, s(1280), 1, act="relu6", is_test=is_test,
                 name=prefix + "_conv_last")
    x = layers.pool2d(x, pool_type="avg", global_pooling=True)
    return layers.fc(x, class_dim,
                     param_attr=ParamAttr(name=prefix + "_fc_weights"),
                     bias_attr=ParamAttr(name=prefix + "_fc_offset"))


class MobileNet:
    def __init__(self, scale: float = 1.0, version: int = 1,
                 prefix: str = "mbv"):
        self.scale = scale
        self.version = version
        self.prefix = prefix + str(version)

    def net(self, input, class_dim: int = 1000, is_test: bool = False):
        fn = mobilenet_v1 if self.version == 1 else mobilenet_v2
        return fn(input, class_dim=class_dim, scale=self.scale,
                  is_test=is_test, prefix=self.prefix)
