"""Transformer encoder as a fluid-layer builder — the static-program
counterpart of models/ernie.py (the reference drives its largest NLP
configs through this surface: tests/unittests/dist_transformer.py and the
ERNIE stack).

Uses the fused multihead_matmul op for attention (one op = QKV projection
+ scaled-dot softmax + context), pre/post layer-norm selectable, standard
FFN. Everything static-shape; AMP/recompute/parallel decorators apply as
to any fluid program.
"""
from __future__ import annotations

import math
from typing import Optional

from .. import layers
from ..framework.param_attr import ParamAttr

__all__ = ["encoder_layer", "encoder", "transformer_encoder_classifier"]


def _mha(x, num_heads, d_model, name, attn_bias=None):
    helper_name = name + "_mha"
    w = layers.create_parameter([d_model, 3 * d_model], "float32",
                                name=helper_name + "_qkv_w")
    b = layers.create_parameter([3 * d_model], "float32",
                                name=helper_name + "_qkv_b")
    from ..framework.layer_helper import LayerHelper

    helper = LayerHelper("multihead_matmul", name=helper_name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"Input": [x], "W": [w], "Bias": [b]}
    if attn_bias is not None:
        ins["BiasQK"] = [attn_bias]
    helper.append_op(
        type="multihead_matmul", inputs=ins, outputs={"Out": [out]},
        attrs={"head_number": int(num_heads),
               "alpha": 1.0 / math.sqrt(d_model // num_heads)})
    return layers.fc(out, d_model, num_flatten_dims=2,
                     name=helper_name + "_out")


def encoder_layer(x, num_heads, d_model, d_ff, name, attn_bias=None,
                  dropout=0.0, postprocess="da"):  # da = dropout+add(+ln)
    """One post-LN encoder block (dist_transformer's encoder_layer)."""
    attn = _mha(x, num_heads, d_model, name, attn_bias)
    if dropout:
        attn = layers.dropout(attn, dropout_prob=dropout)
    x = layers.layer_norm(x + attn, begin_norm_axis=2,
                          name=name + "_ln1")
    ff = layers.fc(x, d_ff, num_flatten_dims=2, act="relu",
                   name=name + "_fc1")
    ff = layers.fc(ff, d_model, num_flatten_dims=2, name=name + "_fc2")
    if dropout:
        ff = layers.dropout(ff, dropout_prob=dropout)
    return layers.layer_norm(x + ff, begin_norm_axis=2,
                             name=name + "_ln2")


def encoder(src_ids, pos_ids, vocab_size, max_pos, num_layers, num_heads,
            d_model, d_ff, name="enc", attn_bias=None, dropout=0.0,
            sent_ids=None, sent_vocab=2):
    """Token (+position, +optional sentence) embeddings -> N blocks."""
    emb = layers.embedding(src_ids, size=[vocab_size, d_model],
                           param_attr=ParamAttr(name=name + "_word_emb"))
    pos = layers.embedding(pos_ids, size=[max_pos, d_model],
                           param_attr=ParamAttr(name=name + "_pos_emb"))
    x = emb + pos
    if sent_ids is not None:
        x = x + layers.embedding(
            sent_ids, size=[sent_vocab, d_model],
            param_attr=ParamAttr(name=name + "_sent_emb"))
    x = layers.layer_norm(x, begin_norm_axis=2, name=name + "_emb_ln")
    for i in range(num_layers):
        x = encoder_layer(x, num_heads, d_model, d_ff, f"{name}_l{i}",
                          attn_bias=attn_bias, dropout=dropout)
    return x


def transformer_encoder_classifier(src_ids, pos_ids, label, vocab_size,
                                   max_pos, num_layers=2, num_heads=4,
                                   d_model=64, d_ff=256, num_classes=2,
                                   name="enc"):
    """CLS-token classifier head over the encoder (ERNIE-style fine-tune
    program shape); returns (loss, logits)."""
    x = encoder(src_ids, pos_ids, vocab_size, max_pos, num_layers,
                num_heads, d_model, d_ff, name=name)
    cls = layers.slice(x, axes=[1], starts=[0], ends=[1])
    cls = layers.reshape(cls, [-1, d_model])
    pooled = layers.fc(cls, d_model, act="tanh", name=name + "_pool")
    logits = layers.fc(pooled, num_classes, name=name + "_cls")
    loss = layers.reduce_mean(
        layers.softmax_with_cross_entropy(logits, label))
    return loss, logits
