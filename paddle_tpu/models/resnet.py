"""ResNet family — static-graph builders in the fluid layer style.

The reference ships ResNet as a test/demo model (dist_se_resnext.py and the
image-classification book tests drive SE-ResNeXt/ResNet through the same
conv2d/batch_norm/pool2d layer surface); this module provides the standard
torchvision-graded ResNet-18/34/50/101/152 as reusable builders.

TPU notes: convs lower to lax.conv_general_dilated (MXU-tiled by XLA);
batch_norm folds into the conv epilogue under XLA fusion; use bf16 input +
AMP decorator for MXU-native throughput.
"""
from __future__ import annotations

from typing import Optional

from .. import layers
from ..framework.param_attr import ParamAttr

__all__ = ["resnet", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152"]

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(x, num_filters, filter_size, stride=1, groups=1, act=None,
             is_test=False, name: str = ""):
    x = layers.conv2d(
        x, num_filters, filter_size, stride=stride,
        padding=(filter_size - 1) // 2, groups=groups,
        param_attr=ParamAttr(name=name + "_weights"), bias_attr=False,
        name=name + ".conv")
    return layers.batch_norm(
        x, act=act, is_test=is_test,
        param_attr=ParamAttr(name=name + "_bn_scale"),
        bias_attr=ParamAttr(name=name + "_bn_offset"),
        moving_mean_name=name + "_bn_mean",
        moving_variance_name=name + "_bn_variance")


def _shortcut(x, out_ch, stride, is_test, name):
    in_ch = x.shape[1]
    if in_ch != out_ch or stride != 1:
        return _conv_bn(x, out_ch, 1, stride=stride, is_test=is_test,
                        name=name)
    return x


def _basic_block(x, num_filters, stride, is_test, name):
    y = _conv_bn(x, num_filters, 3, stride=stride, act="relu",
                 is_test=is_test, name=name + "_branch2a")
    y = _conv_bn(y, num_filters, 3, is_test=is_test, name=name + "_branch2b")
    short = _shortcut(x, num_filters, stride, is_test, name + "_branch1")
    return layers.relu(layers.elementwise_add(short, y))


def _bottleneck_block(x, num_filters, stride, is_test, name):
    y = _conv_bn(x, num_filters, 1, act="relu", is_test=is_test,
                 name=name + "_branch2a")
    y = _conv_bn(y, num_filters, 3, stride=stride, act="relu",
                 is_test=is_test, name=name + "_branch2b")
    y = _conv_bn(y, num_filters * 4, 1, is_test=is_test,
                 name=name + "_branch2c")
    short = _shortcut(x, num_filters * 4, stride, is_test, name + "_branch1")
    return layers.relu(layers.elementwise_add(short, y))


def resnet(input, class_dim: int = 1000, depth: int = 50,
           is_test: bool = False, prefix: str = "res"):
    """Build a ResNet classifier head over ``input`` (NCHW float tensor).

    Returns pre-softmax logits [N, class_dim].
    """
    if depth not in _DEPTH_CFG:
        raise ValueError(f"depth must be one of {sorted(_DEPTH_CFG)}")
    kind, counts = _DEPTH_CFG[depth]
    block = _basic_block if kind == "basic" else _bottleneck_block

    x = _conv_bn(input, 64, 7, stride=2, act="relu", is_test=is_test,
                 name=prefix + "_conv1")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    num_filters = [64, 128, 256, 512]
    for stage, count in enumerate(counts):
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            x = block(x, num_filters[stage], stride, is_test,
                      f"{prefix}{stage + 2}{chr(ord('a') + i)}")
    x = layers.pool2d(x, pool_type="avg", global_pooling=True)
    return layers.fc(x, class_dim,
                     param_attr=ParamAttr(name=prefix + "_fc_weights"),
                     bias_attr=ParamAttr(name=prefix + "_fc_offset"))


class ResNet:
    """Class-style wrapper matching PaddleClas-era usage:
    ``ResNet(layers=50).net(input, class_dim=1000)``."""

    def __init__(self, layers: int = 50, prefix: str = "res"):
        self.depth = layers
        self.prefix = prefix

    def net(self, input, class_dim: int = 1000, is_test: bool = False):
        return resnet(input, class_dim=class_dim, depth=self.depth,
                      is_test=is_test, prefix=self.prefix)


def resnet18(input, class_dim=1000, **kw):
    return resnet(input, class_dim, depth=18, **kw)


def resnet34(input, class_dim=1000, **kw):
    return resnet(input, class_dim, depth=34, **kw)


def resnet50(input, class_dim=1000, **kw):
    return resnet(input, class_dim, depth=50, **kw)


def resnet101(input, class_dim=1000, **kw):
    return resnet(input, class_dim, depth=101, **kw)


def resnet152(input, class_dim=1000, **kw):
    return resnet(input, class_dim, depth=152, **kw)
