"""Model zoo for the TPU-native framework.

The reference ships models as book examples and external repos
(python/paddle/fluid/tests/book/, PaddleRec/PaddleNLP configs referenced from
README.md). Here the zoo is first-class: static-graph builders (LeNet, ResNet,
word2vec-style) mirroring the book tests, plus a pure-JAX flagship GPT decoder
designed for dp/pp/tp/sp execution on a TPU mesh (the reference's 2020-era
stack had no tensor/sequence parallelism — SURVEY.md §2.3; this is the
north-star GPT config built TPU-first).
"""
from . import gpt  # noqa: F401
from . import resnet  # noqa: F401
from . import mobilenet  # noqa: F401
from . import ernie  # noqa: F401
from . import se_resnext  # noqa: F401
from . import transformer_encoder  # noqa: F401
from .se_resnext import SE_ResNeXt, se_resnext50, se_resnext101, se_resnext152  # noqa: F401
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152  # noqa: F401
from .mobilenet import MobileNet, mobilenet_v1, mobilenet_v2  # noqa: F401
