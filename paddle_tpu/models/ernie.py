"""ERNIE/BERT-style transformer encoder — the second north-star model family
(BASELINE.md: "ERNIE-base pretraining, >=90% scaling efficiency").

The reference's largest NLP config is the ERNIE/transformer encoder driven
through fluid layers (python/paddle/fluid/tests/unittests/dist_transformer.py);
this is the TPU-first re-design in the same style as models/gpt.py:

- per-layer leaves stacked on a leading [num_layers] axis -> the encoder is
  ONE lax.scan (one compiled block regardless of depth),
- bidirectional flash attention (the Pallas kernel with causal=False) or
  plain XLA attention,
- declared PartitionSpecs over a (dp, tp) mesh: Megatron column/row splits
  on QKV/FFN, batch over dp — gspmd inserts the collectives,
- pretraining losses the ERNIE way: masked-LM over gathered mask positions
  (static max_masked count) + next-sentence prediction.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ErnieConfig:
    vocab_size: int = 30522
    type_vocab_size: int = 2
    max_seq_len: int = 512
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # named policy (paddle_tpu.parallel.remat): none|full|dots|save_only_flash
    remat_policy: str = "full"
    use_flash: bool = False
    max_masked: int = 20          # MLM positions per sample (static)
    # >0: the tied-decoder MLM projection + CE runs vocab-chunked
    # (ops/pallas_kernels.chunked_lm_loss) — [B, M, V] f32 logits never
    # materialize
    ce_vocab_chunk: int = 0
    # route the post-LN blocks through ops/pallas_kernels.fused_ln (the
    # residual add + layernorm in one launch fwd and bwd); opt-in —
    # interpret-mode Pallas is slower than XLA off-TPU (docs/kernels.md)
    fused_ln: bool = False

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.num_heads == 0
        return self.d_model // self.num_heads

    def scaled(self, **kw) -> "ErnieConfig":
        return dataclasses.replace(self, **kw)


ERNIE_BASE = ErnieConfig()
ERNIE_TINY = ErnieConfig(vocab_size=256, type_vocab_size=2, max_seq_len=64,
                         num_layers=2, num_heads=4, d_model=32, d_ff=64,
                         dtype=jnp.float32, remat=False, max_masked=4)


def init_params(key, cfg: ErnieConfig) -> Dict[str, Any]:
    L, D, F = cfg.num_layers, cfg.d_model, cfg.d_ff
    nh, hd, V = cfg.num_heads, cfg.head_dim, cfg.vocab_size
    ks = jax.random.split(key, 10)
    std = 0.02

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape) * s).astype(jnp.float32)

    return {
        "wte": norm(ks[0], (V, D)),
        "wpe": norm(ks[1], (cfg.max_seq_len, D)),
        "wse": norm(ks[2], (cfg.type_vocab_size, D)),
        "ln_emb_scale": jnp.ones((D,), jnp.float32),
        "ln_emb_bias": jnp.zeros((D,), jnp.float32),
        "blocks": {
            "w_qkv": norm(ks[3], (L, D, 3, nh, hd)),
            "b_qkv": jnp.zeros((L, 3, nh, hd), jnp.float32),
            "w_proj": norm(ks[4], (L, nh, hd, D), s=std / math.sqrt(2 * L)),
            "b_proj": jnp.zeros((L, D), jnp.float32),
            "ln1_scale": jnp.ones((L, D), jnp.float32),
            "ln1_bias": jnp.zeros((L, D), jnp.float32),
            "w_fc": norm(ks[5], (L, D, F)),
            "b_fc": jnp.zeros((L, F), jnp.float32),
            "w_out": norm(ks[6], (L, F, D), s=std / math.sqrt(2 * L)),
            "b_out": jnp.zeros((L, D), jnp.float32),
            "ln2_scale": jnp.ones((L, D), jnp.float32),
            "ln2_bias": jnp.zeros((L, D), jnp.float32),
        },
        # heads: MLM transform + shared-embedding decoder bias, NSP pooler
        "mlm_w": norm(ks[7], (D, D)),
        "mlm_b": jnp.zeros((D,), jnp.float32),
        "mlm_ln_scale": jnp.ones((D,), jnp.float32),
        "mlm_ln_bias": jnp.zeros((D,), jnp.float32),
        "mlm_dec_bias": jnp.zeros((V,), jnp.float32),
        "pool_w": norm(ks[8], (D, D)),
        "pool_b": jnp.zeros((D,), jnp.float32),
        "nsp_w": norm(ks[9], (D, 2)),
        "nsp_b": jnp.zeros((2,), jnp.float32),
    }


def param_specs(cfg: ErnieConfig, tp: str = "tp") -> Dict[str, Any]:
    """(dp, tp) mesh: embeddings/heads replicated (vocab matmul batch-bound
    at base scale), blocks Megatron-split on heads/ffn. Layer axis stays
    unsharded — ERNIE-base depth fits; pp composes via the GPT engine."""
    return {
        "wte": P(), "wpe": P(), "wse": P(),
        "ln_emb_scale": P(), "ln_emb_bias": P(),
        "blocks": {
            "w_qkv": P(None, None, None, tp, None),
            "b_qkv": P(None, None, tp, None),
            "w_proj": P(None, tp, None, None),
            "b_proj": P(None, None),
            "ln1_scale": P(None, None), "ln1_bias": P(None, None),
            "w_fc": P(None, None, tp), "b_fc": P(None, tp),
            "w_out": P(None, tp, None), "b_out": P(None, None),
            "ln2_scale": P(None, None), "ln2_bias": P(None, None),
        },
        "mlm_w": P(), "mlm_b": P(), "mlm_ln_scale": P(), "mlm_ln_bias": P(),
        "mlm_dec_bias": P(), "pool_w": P(), "pool_b": P(),
        "nsp_w": P(), "nsp_b": P(),
    }


def _ln(x, scale, bias, eps=1e-12):
    # named_scope lands in the HLO op_name of the forward AND grad
    # instructions, so the roofline attribution's residue ranking
    # (observability/attribution.py) names the ernie layernorm groups
    # instead of lumping them into anonymous elementwise fusions —
    # mirror of gpt._layer_norm's scope
    with jax.named_scope("layer_norm"):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        return ((x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias).astype(
            x.dtype)


def _attention(q, k, v, pad_mask, cfg: ErnieConfig):
    """Bidirectional attention with padding mask. q,k,v [B,T,nh,hd]."""
    if cfg.use_flash:
        from ..ops.pallas_kernels import flash_attention

        bias = None
        if pad_mask is not None:
            # O(B*T) padding form [B,1,1,Tk], broadcast inside the kernel
            # tiles (the [T,T] mask square never materializes); the mask is
            # a constant w.r.t. grad, matching the kernel's bias contract
            bias = jnp.where(pad_mask, 0.0,
                             -0.5 * jnp.finfo(jnp.float32).max
                             )[:, None, None, :].astype(jnp.float32)
        return flash_attention(q, k, v, causal=False, bias=bias)
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if pad_mask is not None:
        big_neg = jnp.finfo(jnp.float32).min
        logits = jnp.where(pad_mask[:, None, None, :], logits, big_neg)
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block(p, x, pad_mask, cfg: ErnieConfig):
    dt = cfg.dtype
    if cfg.fused_ln:
        from ..ops.pallas_kernels import fused_ln as _fln

        def post_ln(res, o, scale, bias):
            # residual add + post-LN in one Pallas launch (fwd and bwd)
            return _fln(o, scale, bias, residual=res, eps=1e-12)
    else:
        def post_ln(res, o, scale, bias):
            return _ln(res + o, scale, bias)

    qkv = jnp.einsum("btd,dcnh->btcnh", x, p["w_qkv"].astype(dt)) \
        + p["b_qkv"].astype(dt)
    a = _attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], pad_mask, cfg)
    o = jnp.einsum("btnh,nhd->btd", a, p["w_proj"].astype(dt)) \
        + p["b_proj"].astype(dt)
    x = post_ln(x, o, p["ln1_scale"], p["ln1_bias"])   # post-LN (BERT)
    h = jnp.einsum("btd,df->btf", x, p["w_fc"].astype(dt)) \
        + p["b_fc"].astype(dt)
    h = jax.nn.gelu(h, approximate=False)
    o = jnp.einsum("btf,fd->btd", h, p["w_out"].astype(dt)) \
        + p["b_out"].astype(dt)
    return post_ln(x, o, p["ln2_scale"], p["ln2_bias"])


def encode(params, tokens, seg_ids, pad_mask, cfg: ErnieConfig):
    """tokens/seg_ids [B, T] -> hidden [B, T, D] (compute dtype)."""
    T = tokens.shape[1]
    x = params["wte"][tokens] + params["wpe"][jnp.arange(T)] \
        + params["wse"][seg_ids]
    x = _ln(x.astype(cfg.dtype), params["ln_emb_scale"],
            params["ln_emb_bias"])

    from ..parallel import remat as remat_mod

    f = remat_mod.resolve(cfg.remat_policy, remat=cfg.remat).wrap(
        _block, static_argnums=(3,))

    def body(h, layer_p):
        return f(layer_p, h, pad_mask, cfg), None

    x, _ = jax.lax.scan(body, x, params["blocks"])
    return x


def pretrain_loss(params, batch, cfg: ErnieConfig):
    """ERNIE/BERT pretraining: masked-LM over the (static count) masked
    positions + next-sentence prediction on the pooled [CLS].

    batch: tokens [B,T] (mask token substituted), seg_ids [B,T],
    pad_mask [B,T] bool, mlm_pos [B,M] int (0-padded), mlm_ids [B,M],
    mlm_valid [B,M] bool, nsp_label [B]."""
    h = encode(params, batch["tokens"], batch["seg_ids"],
               batch["pad_mask"], cfg)
    B, T, D = h.shape
    M = batch["mlm_pos"].shape[1]
    b_idx = jnp.arange(B)[:, None]
    hm = h[b_idx, batch["mlm_pos"]]                    # [B, M, D]
    hm = jax.nn.gelu(
        jnp.einsum("bmd,de->bme", hm, params["mlm_w"].astype(cfg.dtype))
        + params["mlm_b"].astype(cfg.dtype), approximate=False)
    hm = _ln(hm, params["mlm_ln_scale"], params["mlm_ln_bias"])
    n_masked = jnp.maximum(jnp.sum(batch["mlm_valid"]), 1)
    if cfg.ce_vocab_chunk:
        # vocab-chunked tied-decoder CE: [B, M, V] f32 logits never
        # materialize (head_layout="vd" slices wte rows — no transpose)
        from ..ops.pallas_kernels import chunked_lm_loss

        mlm_loss = chunked_lm_loss(
            hm, params["wte"].astype(cfg.dtype), batch["mlm_ids"],
            bias=params["mlm_dec_bias"], valid=batch["mlm_valid"],
            vocab_chunk=cfg.ce_vocab_chunk, head_layout="vd") / n_masked
    else:
        logits = jnp.einsum("bmd,vd->bmv", hm,
                            params["wte"].astype(cfg.dtype)) \
            + params["mlm_dec_bias"].astype(cfg.dtype)     # tied decoder
        logits = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, batch["mlm_ids"][..., None], axis=-1)[..., 0]
        mlm_ce = jnp.where(batch["mlm_valid"], lse - gold, 0.0)
        mlm_loss = jnp.sum(mlm_ce) / n_masked

    pooled = jnp.tanh(h[:, 0] @ params["pool_w"].astype(cfg.dtype)
                      + params["pool_b"].astype(cfg.dtype))
    nsp_logits = (pooled @ params["nsp_w"].astype(cfg.dtype)
                  + params["nsp_b"].astype(cfg.dtype)).astype(jnp.float32)
    nsp_lse = jax.nn.logsumexp(nsp_logits, axis=-1)
    nsp_gold = jnp.take_along_axis(
        nsp_logits, batch["nsp_label"][:, None], axis=-1)[:, 0]
    nsp_loss = jnp.mean(nsp_lse - nsp_gold)
    return mlm_loss + nsp_loss, {"mlm": mlm_loss, "nsp": nsp_loss}


def make_pretrain_step(cfg: ErnieConfig, mesh=None, dp: str = "dp",
                       tp: str = "tp", lr: float = 1e-4):
    """Jitted pretrain step. With a mesh: params sharded per param_specs,
    batch over dp; gspmd inserts the tp collectives (the encode einsums
    contract sharded dims) — no shard_map needed at encoder scale."""
    from jax.sharding import NamedSharding

    specs = param_specs(cfg, tp=tp)

    def loss_fn(params, batch):
        return pretrain_loss(params, batch, cfg)[0]

    def step(params, opt, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        m = jax.tree_util.tree_map(
            lambda mo, g: 0.9 * mo + g.astype(mo.dtype), opt["m"], grads)
        new_params = jax.tree_util.tree_map(
            lambda p, mo: p - lr * mo.astype(p.dtype), params, m)
        return new_params, {"m": m}, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    opt_sh = {"m": param_sh}
    data_sh = NamedSharding(mesh, P(dp))
    batch_sh = {
        "tokens": data_sh, "seg_ids": data_sh, "pad_mask": data_sh,
        "mlm_pos": data_sh, "mlm_ids": data_sh, "mlm_valid": data_sh,
        "nsp_label": data_sh,
    }
    return jax.jit(step, in_shardings=(param_sh, opt_sh, batch_sh),
                   out_shardings=(param_sh, opt_sh, None),
                   donate_argnums=(0, 1))


def init_opt(params):
    return {"m": jax.tree_util.tree_map(jnp.zeros_like, params)}


def num_params(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def pretrain_flops_per_token(cfg: ErnieConfig, n_params: int, T: int) -> float:
    """Analytic fwd+bwd FLOPs per pretraining token. Honest numerator:
    embedding tables (wte/wpe/wse) are gathers, not per-token matmuls — 6N
    over all params would inflate MFU ~20% here (unlike GPT, whose lm_head
    matmul runs at every position). The tied MLM decoder matmul runs at
    max_masked of T positions and is counted explicitly. Shared by
    bench.py's ernie lane and the TrainMonitor."""
    D, V, M = cfg.d_model, cfg.vocab_size, cfg.max_masked
    n_emb = V * D + cfg.max_seq_len * D + cfg.type_vocab_size * D
    attn = 12 * cfg.num_layers * D * T
    return 6 * (n_params - n_emb) + attn + 6 * M * D * V // T
