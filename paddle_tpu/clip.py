"""Gradient clipping — parity with python/paddle/fluid/clip.py
(GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm + the
set_gradient_clip legacy API)."""
from __future__ import annotations

from typing import List, Tuple

from .framework.layer_helper import LayerHelper


class GradientClipBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        from .layers.nn import clip as clip_layer

        out = []
        for p, g in params_grads:
            if g is None or not p.trainable:
                out.append((p, g))
                continue
            out.append((p, clip_layer(g, self.min, self.max)))
        return out


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        from .layers.nn import clip_by_norm

        out = []
        for p, g in params_grads:
            if g is None or not p.trainable:
                out.append((p, g))
                continue
            out.append((p, clip_by_norm(g, self.clip_norm)))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        from .layers import tensor as tl

        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        sq_sums = []
        for g in grads:
            sq = tl.square(g)
            sq_sums.append(tl.reduce_sum(sq))
        total = tl.sums(sq_sums) if len(sq_sums) > 1 else sq_sums[0]
        global_norm = tl.sqrt(total)
        # scale = clip_norm / max(global_norm, clip_norm)
        max_norm = tl.fill_constant([1], "float32", self.clip_norm)
        denom = tl.elementwise_max(global_norm, max_norm)
        scale_var = tl.elementwise_div(max_norm, denom)
        out = []
        for p, g in params_grads:
            if g is None or not p.trainable:
                out.append((p, g))
                continue
            out.append((p, tl.elementwise_mul(g, scale_var)))
        return out


# legacy fluid.clip.set_gradient_clip support
ClipGradByValue = GradientClipByValue
ClipGradByNorm = GradientClipByNorm
ClipGradByGlobalNorm = GradientClipByGlobalNorm

_clip_attr = {}


def set_gradient_clip(clip, param_list=None, program=None):
    _clip_attr["default"] = clip


def get_gradient_clip():
    return _clip_attr.get("default")
