"""Per-op micro-benchmark harness — parity with
operators/benchmark/op_tester.cc (+ op_tester.proto configs): build a one-op
program, run it through the Executor with warmup, report wall latency.

Under whole-program XLA the "op" is one fused computation; the number is the
dispatch+execute wall time on the current backend (block_until_ready'd).
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["bench_op"]


def bench_op(op_type: str, inputs: Dict[str, Any],
             attrs: Optional[Dict[str, Any]] = None,
             outputs: Optional[Dict[str, list]] = None,
             repeat: int = 50, warmup: int = 5) -> Dict[str, Any]:
    """Run one op `repeat` times; returns latency stats in microseconds.

    inputs: slot -> numpy array (single-var slots) or list of arrays.
    outputs: slot -> [names]; defaults to {"Out": ["out0"]}.
    """
    import jax
    import paddle_tpu as fluid

    attrs = dict(attrs or {})
    outputs = outputs or {"Out": ["out0"]}

    prog = fluid.Program()
    block = prog.global_block()
    feed = {}
    in_map: Dict[str, list] = {}
    for slot, arrs in inputs.items():
        arrs = arrs if isinstance(arrs, (list, tuple)) else [arrs]
        names = []
        for i, a in enumerate(arrs):
            a = np.asarray(a)
            name = f"{slot.lower()}_{i}"
            block.create_var(name=name, shape=list(a.shape),
                             dtype=str(a.dtype), is_data=True)
            feed[name] = a
            names.append(name)
        in_map[slot] = names
    out_names = []
    for slot, names in outputs.items():
        for n in names:
            block.create_var(name=n, shape=[-1], dtype="float32")
            out_names.append(n)
    block.append_op(type=op_type, inputs=in_map, outputs=dict(outputs),
                    attrs=attrs)

    exe = fluid.Executor(fluid.XLAPlace(0))
    scope = fluid.Scope()
    fetch = [out_names[0]] if out_names else []
    for _ in range(warmup):
        vals = exe.run(prog, feed=feed, fetch_list=fetch, scope=scope,
                       return_numpy=False)
    samples = []
    for _ in range(repeat):
        t0 = time.perf_counter_ns()
        vals = exe.run(prog, feed=feed, fetch_list=fetch, scope=scope,
                       return_numpy=False)
        for v in vals:
            jax.block_until_ready(v)
        samples.append((time.perf_counter_ns() - t0) / 1e3)
    samples.sort()
    return {
        "op": op_type,
        "repeat": repeat,
        "mean_us": float(np.mean(samples)),
        "p50_us": float(samples[len(samples) // 2]),
        "p99_us": float(samples[min(len(samples) - 1,
                                    int(len(samples) * 0.99))]),
        "min_us": float(samples[0]),
    }
