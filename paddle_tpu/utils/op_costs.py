"""Per-op device cost attribution — the analogue of the reference's
per-op device tracer (platform/device_tracer.cc, 788 LoC of CUPTI
bookkeeping). XLA executes one fused module, so per-op DEVICE TIME does
not exist post-fusion; what the compiler can attribute exactly is per-op
COST: each IR op's lowering is lowered standalone over abstract values
and XLA's HLO cost analysis reports its flops / bytes accessed. The
table names the top time sinks of a step (flops/peak ~ lower-bound
time), and merges into the chrome trace next to the host events.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import jax
import numpy as np

from ..framework.executor import is_host_op_type
from ..framework.registry import LowerCtx, get_op_spec

__all__ = ["program_cost_table", "print_cost_table", "merge_into_trace",
           "analytic_flops", "attention_flops", "ANALYTIC_FLOPS"]


# ---------------------------------------------------------------------------
# Hand-maintained analytic FLOPs table — the paper-napkin formulas per op
# type, cross-checked against XLA's cost_analysis() in
# tests/test_op_costs.py (entries that disagree with XLA by >2x on
# matmul/attention shapes are treated as table bugs and fixed here).
# XLA counts a MAC as 2 FLOPs (multiply + add), so a matmul is 2*M*N*K.
# ---------------------------------------------------------------------------

def _prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _matmul_flops(x_shape, y_shape, transpose_x=False, transpose_y=False):
    """2*M*N*K over the (possibly batched) contraction; leading batch dims
    follow fluid.layers.matmul broadcasting (batch from the higher-rank
    operand)."""
    xs, ys = list(x_shape), list(y_shape)
    if transpose_x:
        xs[-2:] = xs[-1:] + xs[-2:-1]
    if transpose_y:
        ys[-2:] = ys[-1:] + ys[-2:-1]
    m = xs[-2] if len(xs) >= 2 else 1
    k = xs[-1]
    n = ys[-1] if len(ys) >= 2 else 1
    batch = max(_prod(xs[:-2]), _prod(ys[:-2]))
    return 2.0 * batch * m * n * k


def _mul_flops(x_shape, y_shape, **_):
    """fluid's fc matmul (mul op): x [batch.., K] @ y [K, N], x flattened
    to 2-D at num_col_dims — flops depend only on total rows."""
    rows = _prod(x_shape[:-1])
    return 2.0 * rows * int(x_shape[-1]) * int(y_shape[-1])


def _conv2d_flops(out_shape, w_shape, **_):
    """2 * output elements * (Cin/groups * kh * kw); w is
    [Cout, Cin/g, kh, kw], so w[1:] already folds the group divide."""
    return 2.0 * _prod(out_shape) * _prod(w_shape[1:])


# op type -> flops formula over input/output shapes. Keys match the IR op
# names the lowerings register; shapes are the caller's responsibility
# (program_cost_table rows carry them implicitly via block vars).
ANALYTIC_FLOPS = {
    "mul": _mul_flops,
    "matmul": _matmul_flops,
    "matmul_v2": _matmul_flops,
    "conv2d": _conv2d_flops,
}


def analytic_flops(op_type: str, *shapes, **attrs) -> float:
    """Analytic FLOPs for one op from the hand-maintained table; raises
    KeyError for op types the table does not model (only ops whose cost is
    shape-derivable belong here)."""
    return float(ANALYTIC_FLOPS[op_type](*shapes, **attrs))


def attention_flops(batch: int, heads: int, seq: int, head_dim: int) -> float:
    """Analytic FLOPs of one scaled-dot-product attention forward:
    QK^T (2*B*H*T*T*Dh) + attn@V (2*B*H*T*T*Dh). The softmax between them
    is elementwise-dominated (~5 flops/element) and intentionally excluded
    — at T >= Dh it is <2% of the matmul cost, inside the 2x cross-check
    band."""
    return 2.0 * 2.0 * batch * heads * seq * seq * head_dim


def _var_aval(var):
    import jax.numpy as jnp

    from ..framework.core import dtype_to_jax

    shape = tuple(int(d) if d is not None and int(d) >= 0 else 1
                  for d in (var.shape or ()))
    return jax.ShapeDtypeStruct(shape, dtype_to_jax(var.dtype))


def program_cost_table(program, batch_size: int = 1,
                       feed_avals: Optional[Dict] = None) -> List[dict]:
    """Walk the main block once; for each device op, lower JUST that op over
    the current abstract values and read XLA's cost analysis. Returns rows
    {idx, type, outputs, flops, bytes, est_ms_at[peak]} in program order.

    ``feed_avals`` overrides data-var avals (name -> ShapeDtypeStruct or
    array); otherwise declared var shapes are used with dim -1 -> 1 (scale
    with ``batch_size``).
    """
    block = program.global_block()
    env: Dict[str, jax.ShapeDtypeStruct] = {}
    for name, var in block.vars.items():
        if var.persistable or var.is_data:
            a = _var_aval(var)
            if var.is_data and batch_size > 1 and a.shape \
                    and (var.shape[0] in (-1, None) or var.shape[0] == 1):
                a = jax.ShapeDtypeStruct((batch_size,) + a.shape[1:],
                                         a.dtype)
            env[name] = a
    for name, v in (feed_avals or {}).items():
        env[name] = (v if isinstance(v, jax.ShapeDtypeStruct)
                     else jax.ShapeDtypeStruct(np.shape(v),
                                               np.asarray(v).dtype))

    rows = []
    for idx, op in enumerate(block.ops):
        if is_host_op_type(op.type):
            rows.append({"idx": idx, "type": op.type, "host": True,
                         "flops": 0.0, "bytes": 0.0})
            continue
        try:
            spec = get_op_spec(op.type)
        except NotImplementedError:
            continue
        # flat name->aval environment: lowerings may read ctx.env by name
        # (vjp grad replay), not just the ins dict
        flat_names = list(dict.fromkeys(
            n for names in op.inputs.values() for n in names if n in env))
        flat_avals = [env[n] for n in flat_names]

        def fn(flat_vals, _op=op, _spec=spec, _names=tuple(flat_names)):
            e = dict(zip(_names, flat_vals))
            ctx = LowerCtx(program, block, e)
            ins = {slot: [e[n] for n in names if n in e]
                   for slot, names in _op.inputs.items()}
            ins = {s: v for s, v in ins.items() if v}
            outs = _spec.lower(ctx, _op, ins)
            return {k: v for k, v in outs.items() if v is not None}

        try:
            lowered = jax.jit(fn).lower(flat_avals)
            cost = lowered.cost_analysis() or {}
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            out_shapes = jax.eval_shape(fn, flat_avals)
        except Exception as e:  # un-lowerable standalone (env-coupled op)
            rows.append({"idx": idx, "type": op.type,
                         "error": type(e).__name__, "flops": 0.0,
                         "bytes": 0.0})
            continue
        # publish output avals for downstream ops
        for slot, vals in out_shapes.items():
            names = _op_out_names(op, slot)
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for n, v in zip(names, vals):
                if hasattr(v, "shape"):
                    env[n] = jax.ShapeDtypeStruct(v.shape, v.dtype)
        rows.append({
            "idx": idx, "type": op.type,
            "outputs": [n for ns in op.outputs.values() for n in ns][:2],
            "flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes": float(cost.get("bytes accessed", 0.0) or 0.0),
        })
    return rows


def _op_out_names(op, slot):
    return op.outputs.get(slot, [])


def print_cost_table(rows: List[dict], top: int = 10,
                     peak_flops: float = 197e12,  # v5e bf16 peak (394 is int8)
                     hbm_bw: float = 819e9) -> List[dict]:
    """Top-N ops by roofline-estimated time (max of flops/peak and
    bytes/bandwidth — defaults are TPU v5 lite)."""
    def est_us(r):
        return max(r.get("flops", 0.0) / peak_flops,
                   r.get("bytes", 0.0) / hbm_bw) * 1e6

    ranked = sorted((r for r in rows if not r.get("host")),
                    key=est_us, reverse=True)[:top]
    total_f = sum(r.get("flops", 0.0) for r in rows)
    print(f"{'#':>4} {'op':<32}{'GFLOPs':>10}{'MB':>10}{'est_us':>10}"
          f"{'%flops':>8}")
    for r in ranked:
        print(f"{r['idx']:>4} {r['type']:<32}"
              f"{r.get('flops', 0.0) / 1e9:>10.3f}"
              f"{r.get('bytes', 0.0) / 1e6:>10.2f}"
              f"{est_us(r):>10.2f}"
              f"{(100 * r.get('flops', 0.0) / total_f) if total_f else 0:>7.1f}%")
    return ranked


def merge_into_trace(rows: List[dict], trace_path: str,
                     peak_flops: float = 197e12,  # v5e bf16 peak (394 is int8)
                     hbm_bw: float = 819e9) -> None:
    """Append the cost rows to a chrome trace file as a synthetic
    'xla cost estimate' track (utils/timeline.py merge target)."""
    try:
        with open(trace_path) as f:
            trace = json.load(f)
    except (OSError, json.JSONDecodeError):
        trace = {"traceEvents": []}
    t = 0.0
    for r in rows:
        if r.get("host"):
            continue
        dur = max(r.get("flops", 0.0) / peak_flops,
                  r.get("bytes", 0.0) / hbm_bw) * 1e6
        trace["traceEvents"].append({
            "name": f"{r['idx']}:{r['type']}", "ph": "X", "ts": t,
            "dur": max(dur, 0.01), "pid": "xla-cost-estimate", "tid": 1,
            "args": {"flops": r.get("flops", 0.0),
                     "bytes": r.get("bytes", 0.0)},
        })
        t += max(dur, 0.01)
    with open(trace_path, "w") as f:
        json.dump(trace, f)
