"""NaN/Inf debugging — parity with FLAGS_check_nan_inf
(framework/details/nan_inf_utils_detail.cc per-op output scan).

Two levels, like the reference:
- FLAGS_check_nan_inf_level="fetch" (default): scan fetched values after
  the whole-block XLA run — cheap, catches that something went non-finite.
- FLAGS_check_nan_inf_level="op": the Executor interprets the block
  EAGERLY, one op lowering at a time, checking every floating output on
  the host and raising with the op type and output name — the reference's
  per-op localization (plus op attribution, op_call_stack.cc capability).
  Debug-only speed, exact blame.
"""
from __future__ import annotations

import numpy as np


def check_fetches(names, values):
    for name, v in zip(names, values):
        arr = np.asarray(v)
        if arr.dtype.kind != "f":
            if "float" in str(arr.dtype):  # ml_dtypes kinds report 'V'
                arr = arr.astype(np.float32)
            else:
                continue
        if np.isnan(arr).any():
            raise FloatingPointError(f"NaN detected in fetch var {name!r}")
        if np.isinf(arr).any():
            raise FloatingPointError(f"Inf detected in fetch var {name!r}")


def summarize_value(name, value):
    """Forensics summary of one fetched value: shape/dtype/element counts
    plus finite/nan/inf tallies and min/max/mean over the finite elements
    (anomaly dumps — observability/monitor.py). Never raises; a value that
    cannot even be converted reports its error instead."""
    try:
        arr = np.asarray(value)
    except Exception as e:
        return {"name": str(name), "error": f"{type(e).__name__}: {e}"}
    out = {"name": str(name), "shape": list(arr.shape),
           "dtype": str(arr.dtype), "size": int(arr.size)}
    if arr.size == 0:
        return out
    farr = arr
    if arr.dtype.kind != "f":
        if "float" in str(arr.dtype):  # ml_dtypes kinds report 'V'
            farr = arr.astype(np.float32)
        else:
            if arr.dtype.kind in "iub":
                out.update(min=int(arr.min()), max=int(arr.max()))
            return out
    finite = np.isfinite(farr)
    n_finite = int(finite.sum())
    out.update(
        finite_count=n_finite,
        nan_count=int(np.isnan(farr).sum()),
        inf_count=int(np.isinf(farr).sum()),
    )
    if n_finite:
        fin = farr[finite].astype(np.float64)
        out.update(min=float(fin.min()), max=float(fin.max()),
                   mean=float(fin.mean()))
    return out


def check_op_outputs(op, env):
    """Scan one op's outputs in an eager (op-level) run; raises with the
    op and var responsible (nan_inf_utils_detail.cc per-op behavior)."""
    for slot, names in op.outputs.items():
        for name in names:
            v = env.get(name)
            if v is None:
                continue
            arr = np.asarray(v)
            if arr.dtype.kind != "f":
                # ml_dtypes bfloat16/float8 report kind 'V'; they are
                # float-like and must be scanned too
                if "float" in str(arr.dtype):
                    arr = arr.astype(np.float32)
                else:
                    continue
            bad = None
            if np.isnan(arr).any():
                bad = "NaN"
            elif np.isinf(arr).any():
                bad = "Inf"
            if bad:
                raise FloatingPointError(
                    f"{bad} detected in output {name!r} (slot {slot}) of op "
                    f"{op.type!r} — inputs: "
                    + ", ".join(f"{s}={ns}" for s, ns in op.inputs.items()))
