"""NaN/Inf debugging — parity with FLAGS_check_nan_inf
(framework/details/nan_inf_utils_detail.cc per-op output scan).

With whole-program compilation the per-op scan happens on fetches; for
op-level attribution run the executor with FLAGS_check_nan_inf AND
FLAGS_check_nan_inf_level=op — the lowering then wraps every op output in a
jax.debug.check-style assertion via checkify (slower, debug only)."""
from __future__ import annotations

import numpy as np


def check_fetches(names, values):
    for name, v in zip(names, values):
        arr = np.asarray(v)
        if arr.dtype.kind == "f":
            if np.isnan(arr).any():
                raise FloatingPointError(f"NaN detected in fetch var {name!r}")
            if np.isinf(arr).any():
                raise FloatingPointError(f"Inf detected in fetch var {name!r}")
