"""Timeline tool — parity with tools/timeline.py (profiler records →
chrome://tracing JSON, with multi-trainer merge).

The reference converts profiler.proto dumps from N trainers into one
chrome-trace with a pid per trainer; here profiles are the chrome-trace JSON
files written by paddle_tpu.profiler.stop_profiler, merged the same way.
"""
from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

__all__ = ["Timeline"]


class Timeline:
    def __init__(self, profile_paths: Sequence[Tuple[str, str]]):
        """profile_paths: list of (trainer_name, path-to-chrome-trace.json)."""
        self.profile_paths = list(profile_paths)

    def _load(self):
        merged: List[dict] = []
        metadata: List[dict] = []
        for pid, (name, path) in enumerate(self.profile_paths):
            with open(path) as f:
                data = json.load(f)
            metadata.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": name},
            })
            for ev in data.get("traceEvents", []):
                ev = dict(ev)
                ev["pid"] = pid
                merged.append(ev)
        return metadata + merged

    def generate_chrome_trace(self, output_path: str):
        events = self._load()
        with open(output_path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return output_path
