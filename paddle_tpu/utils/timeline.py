"""Timeline tool — parity with tools/timeline.py (profiler records →
chrome://tracing JSON, with multi-trainer merge).

The reference converts profiler.proto dumps from N trainers into one
chrome-trace with a pid per trainer; here profiles are the chrome-trace JSON
files written by paddle_tpu.profiler.stop_profiler, merged the same way.

Each input file may itself carry several pids (the merged host+device
traces from observability/trace_merge.py put host and device spans on
distinct pids): the merge remaps each (file, original pid) pair to its own
output pid, so host/device tracks stay separate after the multi-trainer
merge instead of collapsing onto one row. Source process_name metadata is
preserved under a "trainer/" prefix.
"""
from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

__all__ = ["Timeline"]


class Timeline:
    def __init__(self, profile_paths: Sequence[Tuple[str, str]]):
        """profile_paths: list of (trainer_name, path-to-chrome-trace.json)."""
        self.profile_paths = list(profile_paths)

    def _load(self):
        merged: List[dict] = []
        metadata: List[dict] = []
        next_pid = 0
        for fi, (name, path) in enumerate(self.profile_paths):
            with open(path) as f:
                data = json.load(f)
            events = data.get("traceEvents", [])
            # source process names, keyed by original pid
            src_names: Dict[int, str] = {
                ev.get("pid", 0): ev.get("args", {}).get("name", "")
                for ev in events
                if ev.get("ph") == "M" and ev.get("name") == "process_name"
            }
            pid_map: Dict[int, int] = {}

            def out_pid(orig, name=name, src_names=src_names,
                        pid_map=pid_map):
                nonlocal next_pid
                if orig not in pid_map:
                    pid_map[orig] = next_pid
                    src = src_names.get(orig, "")
                    label = f"{name}/{src}" if src else name
                    metadata.append({
                        "name": "process_name", "ph": "M",
                        "pid": pid_map[orig], "args": {"name": label},
                    })
                    next_pid += 1
                return pid_map[orig]

            # single-pid files keep the old behavior (one pid per trainer)
            out_pid(min(src_names) if src_names
                    else min((ev.get("pid", 0) for ev in events
                              if ev.get("ph") != "M"), default=0))
            for ev in events:
                if ev.get("ph") == "M" and ev.get("name") == "process_name":
                    continue  # re-emitted above with the trainer prefix
                ev = dict(ev)
                ev["pid"] = out_pid(ev.get("pid", 0))
                merged.append(ev)
        return metadata + merged

    def generate_chrome_trace(self, output_path: str):
        events = self._load()
        with open(output_path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return output_path
