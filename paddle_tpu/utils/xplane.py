"""Minimal XSpace (xplane.pb) reader — a ``jax.profiler.ProfileData`` shim.

Newer jax exposes ``jax.profiler.ProfileData`` to walk a profiler capture
(planes -> lines -> events with stats); the jax pinned in this environment
(0.4.37) writes the capture but does not expose the reader, and no xplane
protobuf bindings ship with it — which left utils/device_trace.py (measured
per-op attribution) dead on arrival: 'cannot import name ProfileData'.

This module decodes the XSpace protobuf wire format directly (the schema is
tensorflow/core/profiler/protobuf/xplane.proto; only varint / fixed64 /
length-delimited wire types occur) and exposes the same surface
device_trace.py and observability/trace_merge.py consume:

    pd = ProfileData.from_file(path)      # or from_serialized_xspace(bytes)
    for plane in pd.planes:               # .name
        for line in plane.lines:          # .name
            for ev in line.events:        # .name, .start_ns, .duration_ns
                dict(ev.stats)            # {'hlo_op': ..., 'hlo_module': ...}

Times follow the jax reader's convention: ``start_ns`` is the line's
``timestamp_ns`` plus the event's ``offset_ps/1e3``; durations convert
ps -> ns. Stat values resolve the oneof (double/int/uint/str/bytes/ref —
ref values dereference the plane's stat_metadata names).
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple

__all__ = ["ProfileData"]


def _decode_varint(buf: bytes, i: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7
        if shift > 70:
            raise ValueError("varint too long (corrupt xplane.pb)")


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over one message's bytes.
    Length-delimited values come back as memoryview-backed bytes."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _decode_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:          # varint
            val, i = _decode_varint(buf, i)
        elif wt == 1:        # fixed64
            val = buf[i:i + 8]
            i += 8
        elif wt == 2:        # length-delimited
            ln, i = _decode_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wt == 5:        # fixed32
            val = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt} (group fields "
                             "do not occur in xplane.proto)")
        yield field, wt, val


def _signed64(v: int) -> int:
    """Two's-complement interpretation of a varint-decoded int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


class _Stat:
    """XStat: metadata_id + a value oneof."""

    __slots__ = ("metadata_id", "kind", "raw")

    def __init__(self, buf: bytes):
        self.metadata_id = 0
        self.kind = None
        self.raw = None
        for field, wt, val in _fields(buf):
            if field == 1:
                self.metadata_id = val
            elif field == 2:   # double_value (fixed64)
                self.kind, self.raw = "double", struct.unpack("<d", val)[0]
            elif field == 3:   # uint64_value
                self.kind, self.raw = "uint64", val
            elif field == 4:   # int64_value
                self.kind, self.raw = "int64", _signed64(val)
            elif field == 5:   # str_value
                self.kind, self.raw = "str", bytes(val).decode(
                    "utf-8", "replace")
            elif field == 6:   # bytes_value
                self.kind, self.raw = "bytes", bytes(val)
            elif field == 7:   # ref_value -> stat_metadata name
                self.kind, self.raw = "ref", val

    def resolve(self, stat_meta: Dict[int, str]):
        if self.kind == "ref":
            return stat_meta.get(self.raw, str(self.raw))
        return self.raw


class _Event:
    """XEvent with plane metadata resolved: name / start_ns / duration_ns /
    stats (iterable of (name, value), so ``dict(ev.stats)`` works)."""

    __slots__ = ("name", "start_ns", "duration_ns", "_stats")

    def __init__(self, buf: bytes, line_ts_ns: int,
                 event_meta: Dict[int, "_EventMeta"],
                 stat_meta: Dict[int, str]):
        metadata_id = 0
        offset_ps = 0
        duration_ps = 0
        raw_stats: List[_Stat] = []
        for field, wt, val in _fields(buf):
            if field == 1:
                metadata_id = val
            elif field == 2:   # offset_ps (oneof data)
                offset_ps = _signed64(val)
            elif field == 3:
                duration_ps = val
            elif field == 4:
                raw_stats.append(_Stat(val))
        meta = event_meta.get(metadata_id)
        self.name = (meta.display_name or meta.name) if meta else ""
        self.start_ns = line_ts_ns + offset_ps / 1e3
        self.duration_ns = duration_ps / 1e3
        stats: List[Tuple[str, object]] = []
        for s in raw_stats:
            stats.append((stat_meta.get(s.metadata_id, str(s.metadata_id)),
                          s.resolve(stat_meta)))
        # event-metadata-level stats apply to every occurrence (XLA Ops
        # lines carry hlo_op/hlo_module there on some runtimes)
        if meta is not None:
            for s in meta.stats:
                stats.append((stat_meta.get(s.metadata_id,
                                            str(s.metadata_id)),
                              s.resolve(stat_meta)))
        self._stats = stats

    @property
    def stats(self):
        return list(self._stats)


class _EventMeta:
    __slots__ = ("name", "display_name", "stats")

    def __init__(self, buf: bytes):
        self.name = ""
        self.display_name = ""
        self.stats: List[_Stat] = []
        for field, wt, val in _fields(buf):
            if field == 2:
                self.name = bytes(val).decode("utf-8", "replace")
            elif field == 4:
                self.display_name = bytes(val).decode("utf-8", "replace")
            elif field == 5:
                self.stats.append(_Stat(val))


class _Line:
    __slots__ = ("name", "timestamp_ns", "_event_bufs", "_event_meta",
                 "_stat_meta")

    def __init__(self, buf: bytes, event_meta, stat_meta):
        name = display_name = ""
        self.timestamp_ns = 0
        self._event_bufs: List[bytes] = []
        for field, wt, val in _fields(buf):
            if field == 2:
                name = bytes(val).decode("utf-8", "replace")
            elif field == 11:
                display_name = bytes(val).decode("utf-8", "replace")
            elif field == 3:
                self.timestamp_ns = _signed64(val)
            elif field == 4:
                self._event_bufs.append(val)
        self.name = display_name or name
        self._event_meta = event_meta
        self._stat_meta = stat_meta

    @property
    def events(self) -> Iterator[_Event]:
        for b in self._event_bufs:
            yield _Event(b, self.timestamp_ns, self._event_meta,
                         self._stat_meta)


def _parse_map_entry(buf: bytes) -> Tuple[int, bytes]:
    """proto map<int64, Msg> entry: key=field 1 varint, value=field 2."""
    key, val = 0, b""
    for field, wt, v in _fields(buf):
        if field == 1:
            key = v
        elif field == 2:
            val = v
    return key, val


class _Plane:
    __slots__ = ("name", "_line_bufs", "_event_meta", "_stat_meta")

    def __init__(self, buf: bytes):
        self.name = ""
        self._line_bufs: List[bytes] = []
        self._event_meta: Dict[int, _EventMeta] = {}
        self._stat_meta: Dict[int, str] = {}
        for field, wt, val in _fields(buf):
            if field == 2:
                self.name = bytes(val).decode("utf-8", "replace")
            elif field == 3:
                self._line_bufs.append(val)
            elif field == 4:
                k, v = _parse_map_entry(val)
                self._event_meta[k] = _EventMeta(v)
            elif field == 5:
                k, v = _parse_map_entry(val)
                meta_name = ""
                for f2, _, v2 in _fields(v):
                    if f2 == 2:
                        meta_name = bytes(v2).decode("utf-8", "replace")
                self._stat_meta[k] = meta_name

    @property
    def lines(self) -> Iterator[_Line]:
        for b in self._line_bufs:
            yield _Line(b, self._event_meta, self._stat_meta)


class ProfileData:
    """Drop-in for the subset of ``jax.profiler.ProfileData`` used here."""

    def __init__(self, plane_bufs: List[bytes]):
        self._plane_bufs = plane_bufs

    @classmethod
    def from_serialized_xspace(cls, data: bytes) -> "ProfileData":
        planes = [val for field, wt, val in _fields(data) if field == 1]
        return cls(planes)

    @classmethod
    def from_file(cls, path: str) -> "ProfileData":
        with open(path, "rb") as f:
            return cls.from_serialized_xspace(f.read())

    @property
    def planes(self) -> Iterator[_Plane]:
        for b in self._plane_bufs:
            yield _Plane(b)
