"""Measured per-op device attribution from jax.profiler xplane captures.

The reference measures per-kernel device time with CUPTI and correlates it
to ops by correlation id (platform/device_tracer.cc:1).  The TPU-native
pipeline here:

1. every IR-op lowering runs under ``jax.named_scope("ptop_<type>__<out>")``
   (framework/registry.py run_lowering), so XLA stamps the op identity into
   each HLO instruction's ``metadata.op_name``;
2. ``jax.profiler.trace`` captures the device execution timeline (XPlane);
   each executed HLO instruction/fusion appears as an event with an
   ``hlo_op`` stat and a measured ``duration_ns``;
3. the optimized HLO text of the executed program maps ``hlo_op`` back to
   ``op_name`` and hence to the IR op — fused computations attribute to the
   scope of their root instruction.

The result is MEASURED nanoseconds per IR op for the fused step, not a
cost-model estimate (utils/op_costs.py remains the static/modeled track).
"""
from __future__ import annotations

import glob
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

_METADATA_RX = re.compile(
    r"%?([\w.\-]+)\s*=\s[^\n]*?metadata=\{[^}]*?op_name=\"([^\"]+)\"")
_SCOPE_RX = re.compile(r"(ptop_[A-Za-z0-9_]+)")


_MODULE_RX = re.compile(r"HloModule\s+([\w.\-]+)")


def hlo_op_name_map(hlo_text: str) -> Dict[str, str]:
    """instruction name -> metadata op_name, from optimized HLO text."""
    return dict(_METADATA_RX.findall(hlo_text))


def hlo_module_name(hlo_text: str) -> str:
    m = _MODULE_RX.search(hlo_text)
    return m.group(1) if m else ""


def _latest_xplane(trace_dir: str) -> Optional[str]:
    files = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    return max(files, key=os.path.getmtime) if files else None


def device_events(trace_dir: str,
                  exclusive: bool = False) -> Iterable[Tuple[str, str, float]]:
    """Yield (hlo_module, hlo_op, duration_ns) for every device-executed
    HLO event in the newest capture under trace_dir.

    TPU device planes carry several lines: 'Steps' and 'XLA Modules' are
    whole-step envelopes, 'Async XLA Ops' are DMA streams overlapping
    compute, and 'XLA Ops' is the execution timeline — only the latter is
    yielded (summing every line triple-counts: each step appears as a Step
    event, a Module event, and its ops). 'XLA Ops' itself nests parent
    spans (%while, call ops) above their children on the same line; with
    ``exclusive=True`` each event's duration has its childrens' subtracted,
    so a sum over all events equals measured device-busy time.
    """
    from jax.profiler import ProfileData

    path = _latest_xplane(trace_dir)
    if path is None:
        return
    pd = ProfileData.from_file(path)
    for plane in pd.planes:
        device_plane = plane.name.startswith("/device:")
        lines = list(plane.lines)
        if device_plane:
            op_lines = [ln for ln in lines if str(ln.name) == "XLA Ops"]
            if op_lines:
                lines = op_lines
            else:
                # unknown runtime naming: at least drop the whole-step
                # envelope lines and the async DMA streams (which overlap
                # compute) so the sum stays ~1x, and say so
                import sys
                lines = [ln for ln in lines
                         if str(ln.name) not in ("Steps", "XLA Modules",
                                                 "Async XLA Ops")]
                print(f"[device_trace] warning: no 'XLA Ops' line on "
                      f"{plane.name}; summing {[str(l.name) for l in lines]}"
                      f" (attribution may overlap)", file=sys.stderr)
        for line in lines:
            # execution lines only: TPU device planes, or the PJRT CPU
            # client's runtime line — host python/trace-me lines may carry
            # hlo_op stats too and would double-count
            exec_line = device_plane or "XLAPjRtCpuClient" in str(line.name)
            if not exec_line:
                continue
            evs = []
            for ev in line.events:
                try:
                    stats = dict(ev.stats)
                except Exception:
                    stats = {}
                hlo_op = stats.get("hlo_op")
                if hlo_op is None:
                    if not device_plane:
                        continue
                    # TPU device planes name events by the HLO op directly
                    hlo_op = ev.name
                dur = float(getattr(ev, "duration_ns", 0.0) or 0.0)
                if dur <= 0:
                    continue
                start = float(getattr(ev, "start_ns", 0.0) or 0.0)
                evs.append([start, dur,
                            str(stats.get("hlo_module", plane.name)),
                            str(hlo_op)])
            if exclusive and evs:
                # properly nested spans: sweep by start, subtract each
                # event's duration from its innermost enclosing parent
                evs.sort(key=lambda r: (r[0], -r[1]))
                stack: List[list] = []
                for r in evs:
                    while stack and r[0] >= stack[-1][0] + stack[-1][1]:
                        stack.pop()
                    if stack:
                        stack[-1][4] -= r[1]
                    r.append(r[1])     # r[4] = exclusive dur
                    stack.append(r)
                for start, dur, module, hlo_op, excl in evs:
                    if excl > 0:
                        yield module, hlo_op, excl
            else:
                for start, dur, module, hlo_op in evs:
                    yield module, hlo_op, dur


def measured_op_rows(trace_dir: str, hlo_texts: List[str]) -> List[dict]:
    """Aggregate measured device ns per IR op (ptop_* scope).

    Events whose HLO instruction carries no ptop scope (infeed, copies,
    compiler-inserted glue) aggregate under their hlo op name so the table
    always sums to the measured total."""
    # per-module maps: generic instruction names (fusion.1, copy.3) repeat
    # across compiled blocks, so a flat map would misattribute block A's
    # events to block B's ops
    by_module: Dict[str, Dict[str, str]] = {}
    merged: Dict[str, str] = {}
    for txt in hlo_texts:
        m = hlo_op_name_map(txt)
        by_module.setdefault(hlo_module_name(txt), {}).update(m)
        merged.update(m)
    agg: Dict[str, List[float]] = {}
    for module, hlo_op, dur in device_events(trace_dir, exclusive=True):
        mod_map = by_module.get(module)
        if mod_map and hlo_op in mod_map:
            op_name = mod_map[hlo_op]
        else:
            op_name = merged.get(hlo_op, "")
        m = _SCOPE_RX.search(op_name)
        key = m.group(1) if m else f"[xla] {hlo_op.split('.')[0]}"
        a = agg.setdefault(key, [0.0, 0])
        a[0] += dur
        a[1] += 1
    rows = [{"op": k, "device_ns": int(v[0]), "events": v[1]}
            for k, v in agg.items()]
    rows.sort(key=lambda r: -r["device_ns"])
    return rows


def merge_into_trace(rows: List[dict], trace_path: str) -> None:
    """Append the measured rows as a synthetic 'measured device' track to
    the chrome trace (next to the host events and the modeled op_costs
    track)."""
    import json

    try:
        with open(trace_path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"traceEvents": []}
    ts = 0.0
    for r in rows:
        doc["traceEvents"].append({
            "name": r["op"], "ph": "X", "ts": ts,
            "dur": r["device_ns"] / 1000.0,
            "pid": 1, "tid": 999,
            "args": {"events": r["events"], "track": "measured-device"},
        })
        ts += r["device_ns"] / 1000.0
    with open(trace_path, "w") as f:
        json.dump(doc, f)


def print_rows(rows: List[dict], top: int = 5) -> None:
    total = sum(r["device_ns"] for r in rows) or 1
    print(f"{'Op (measured device time)':<48}{'ns':>12}{'%':>7}{'events':>8}")
    for r in rows[:top]:
        print(f"{r['op']:<48}{r['device_ns']:>12}"
              f"{100.0 * r['device_ns'] / total:>6.1f}%{r['events']:>8}")
