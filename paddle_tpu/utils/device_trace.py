"""Measured per-op device attribution from jax.profiler xplane captures.

The reference measures per-kernel device time with CUPTI and correlates it
to ops by correlation id (platform/device_tracer.cc:1).  The TPU-native
pipeline here:

1. every IR-op lowering runs under ``jax.named_scope("ptop_<type>__<out>")``
   (framework/registry.py run_lowering), so XLA stamps the op identity into
   each HLO instruction's ``metadata.op_name``;
2. ``jax.profiler.trace`` captures the device execution timeline (XPlane);
   each executed HLO instruction/fusion appears as an event with an
   ``hlo_op`` stat and a measured ``duration_ns``;
3. the optimized HLO text of the executed program maps ``hlo_op`` back to
   ``op_name`` and hence to the IR op — fused computations attribute to the
   scope of their root instruction.

The result is MEASURED nanoseconds per IR op for the fused step, not a
cost-model estimate (utils/op_costs.py remains the static/modeled track).
"""
from __future__ import annotations

import glob
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

_METADATA_RX = re.compile(
    r"%?([\w.\-]+)\s*=\s[^\n]*?metadata=\{[^}]*?op_name=\"([^\"]+)\"")
_SCOPE_RX = re.compile(r"(ptop_[A-Za-z0-9_]+)")


_MODULE_RX = re.compile(r"HloModule\s+([\w.\-]+)")


def hlo_op_name_map(hlo_text: str) -> Dict[str, str]:
    """instruction name -> metadata op_name, from optimized HLO text."""
    return dict(_METADATA_RX.findall(hlo_text))


def hlo_module_name(hlo_text: str) -> str:
    m = _MODULE_RX.search(hlo_text)
    return m.group(1) if m else ""


def _latest_xplane(trace_dir: str) -> Optional[str]:
    files = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    return max(files, key=os.path.getmtime) if files else None


def profile_data_cls():
    """The XSpace reader: jax's own ProfileData when the installed jax
    exposes it, else the in-repo wire-format shim (utils/xplane.py — the
    pinned jax 0.4.37 writes captures but ships no reader)."""
    try:
        from jax.profiler import ProfileData  # type: ignore[attr-defined]

        return ProfileData
    except ImportError:
        from .xplane import ProfileData

        return ProfileData


def _line_role(name: str, event_names: Iterable[str]) -> str:
    """Classify a device-plane trace line from OBSERVED names.

    Runtimes disagree on line naming ('XLA Ops' vs bare module lines), and
    trusting one runtime's labels is exactly what multi-counted
    PROFILE_STEP.json (round-5 advisor): whole-step envelopes ('jit_step',
    per-step events '0'..'7') and DMA streams ('copy-done') summed on top of
    the real op timeline. Roles:
      'ops'     — the execution timeline (the only line worth summing)
      'steps'   — whole-step envelopes
      'modules' — whole-executable envelopes
      'async'   — DMA/infeed streams that overlap compute
      'host'    — TraceMe/framework annotation lines
    Line names are tried first; unknown names fall back to what the line's
    events are called.
    """
    n = str(name).strip().lower()
    if "async" in n or "dma" in n:
        return "async"
    if n == "steps" or n.startswith("step"):
        return "steps"
    if "module" in n:
        return "modules"
    if "traceme" in n or "framework" in n or "scope" in n:
        return "host"
    if "op" in n:
        return "ops"
    names = [str(e) for e in event_names if str(e)]
    if names:
        total = len(names)
        if sum(t.isdigit() for t in names) / total > 0.5:
            return "steps"  # per-step envelopes named 0,1,2,...
        if sum(t.startswith(("jit_", "jit(")) or "module" in t.lower()
               for t in names) / total > 0.5:
            return "modules"
        if sum(t.lower().startswith(("copy", "send", "recv", "infeed",
                                     "outfeed"))
               for t in names) / total > 0.8:
            return "async"
    return "ops"


def _exclusive_sweep(evs: List[list]) -> Tuple[List[list], int]:
    """Subtract child spans from their innermost enclosing parent (properly
    nested spans assumed). Appends r[4] = exclusive duration to every row.

    Partially overlapping (non-nested) spans can drive a parent's exclusive
    duration negative; those are clamped to zero and COUNTED (returned as
    n_clamped) instead of silently dropped, so broken attribution is visible
    (round-5 advisor, device_trace.py:128).
    """
    evs.sort(key=lambda r: (r[0], -r[1]))
    stack: List[list] = []
    for r in evs:
        while stack and r[0] >= stack[-1][0] + stack[-1][1]:
            stack.pop()
        if stack:
            stack[-1][4] -= r[1]
        r.append(r[1])     # r[4] = exclusive dur
        stack.append(r)
    n_clamped = 0
    for r in evs:
        if r[4] < 0:
            r[4] = 0.0
            n_clamped += 1
    return evs, n_clamped


def _check_busy_le_wall(rows: List[list], where: str,
                        tolerance: float = 1.001) -> bool:
    """Device planes execute serially: sum(exclusive) must fit in the wall
    span. Returns False (and warns) when the rows are multi-counted."""
    import sys

    if not rows:
        return True
    wall = max(r[0] + r[1] for r in rows) - min(r[0] for r in rows)
    busy = sum(r[4] for r in rows)
    if busy > wall * tolerance:
        print(f"[device_trace] warning: exclusive sum {busy / 1e6:.1f} ms "
              f"exceeds wall {wall / 1e6:.1f} ms on {where} — events are "
              f"multi-counted; refusing exclusive attribution",
              file=sys.stderr)
        return False
    return True


def device_events(trace_dir: str,
                  exclusive: bool = False) -> Iterable[Tuple[str, str, float]]:
    """Yield (hlo_module, hlo_op, duration_ns) for every device-executed
    HLO event in the newest capture under trace_dir.

    TPU device planes carry several lines: 'Steps' and 'XLA Modules' are
    whole-step envelopes, 'Async XLA Ops' are DMA streams overlapping
    compute, and 'XLA Ops' is the execution timeline — only the latter is
    yielded (summing every line triple-counts: each step appears as a Step
    event, a Module event, and its ops). Line roles are detected from the
    OBSERVED line/event names (``_line_role``), not one runtime's labels.
    'XLA Ops' itself nests parent spans (%while, call ops) above their
    children on the same line; with ``exclusive=True`` each event's duration
    has its childrens' subtracted, so a sum over all events equals measured
    device-busy time — and that invariant is CHECKED: a line whose exclusive
    sum exceeds its wall-clock span is multi-counted, and exclusive
    attribution for it is refused (with a warning) rather than emitted
    corrupt (the round-5 PROFILE_STEP.json failure mode).
    """
    import sys

    path = _latest_xplane(trace_dir)
    if path is None:
        return
    pd = profile_data_cls().from_file(path)
    for plane in pd.planes:
        device_plane = plane.name.startswith("/device:")
        lines = list(plane.lines)
        if device_plane:
            classified = [
                (ln, _line_role(str(ln.name), (str(ev.name)
                                               for ev in ln.events)))
                for ln in lines
            ]
            op_lines = [ln for ln, role in classified if role == "ops"]
            if op_lines:
                lines = op_lines
            elif exclusive:
                print(f"[device_trace] warning: no op-role line detected on "
                      f"{plane.name} (lines: "
                      f"{[str(ln.name) for ln in lines]}); refusing "
                      f"exclusive attribution for this plane",
                      file=sys.stderr)
                continue
            else:
                # inclusive mode keeps a permissive fallback: drop the
                # recognized envelope/DMA lines, sum the rest, and say so
                lines = [ln for ln, role in classified
                         if role not in ("steps", "modules", "async")]
                print(f"[device_trace] warning: no op-role line on "
                      f"{plane.name}; summing "
                      f"{[str(ln.name) for ln in lines]}"
                      f" (attribution may overlap)", file=sys.stderr)
        plane_rows: List[list] = []   # device rows held for the plane check
        for line in lines:
            # execution lines only: TPU device planes, or the CPU client's
            # runtime line ('XLAPjRtCpuClient' / 'tf_XLATfrtCpuClient' —
            # the runtime renamed it across releases) — host python/
            # trace-me lines may carry hlo_op stats too and double-count
            exec_line = device_plane or "CpuClient" in str(line.name)
            if not exec_line:
                continue
            evs = []
            for ev in line.events:
                try:
                    stats = dict(ev.stats)
                except Exception:
                    stats = {}
                hlo_op = stats.get("hlo_op")
                if hlo_op is None:
                    if not device_plane:
                        continue
                    # TPU device planes name events by the HLO op directly
                    hlo_op = ev.name
                dur = float(getattr(ev, "duration_ns", 0.0) or 0.0)
                if dur <= 0:
                    continue
                start = float(getattr(ev, "start_ns", 0.0) or 0.0)
                evs.append([start, dur,
                            str(stats.get("hlo_module", plane.name)),
                            str(hlo_op)])
            if exclusive and evs:
                # properly nested spans: sweep by start, subtract each
                # event's duration from its innermost enclosing parent
                evs, n_clamped = _exclusive_sweep(evs)
                if n_clamped:
                    print(f"[device_trace] warning: {n_clamped} event(s) on "
                          f"'{line.name}' ({plane.name}) had negative "
                          f"exclusive duration (non-nested overlap); "
                          f"clamped to 0", file=sys.stderr)
                if device_plane:
                    plane_rows.extend(evs)
                else:
                    for start, dur, module, hlo_op, excl in evs:
                        yield module, hlo_op, excl
            else:
                for start, dur, module, hlo_op in evs:
                    yield module, hlo_op, dur
        if exclusive and plane_rows:
            # device-busy invariant: one device executes serially, so the
            # exclusive sum over everything about to be attributed must fit
            # in the plane's wall span. A violation means envelope/DMA lines
            # slipped past role detection (the PROFILE_STEP.json corruption:
            # busy 4.2x wall) — refuse rather than emit multi-counted rows.
            if _check_busy_le_wall(plane_rows, str(plane.name)):
                for start, dur, module, hlo_op, excl in plane_rows:
                    yield module, hlo_op, excl


def measured_op_rows(trace_dir: str, hlo_texts: List[str]) -> List[dict]:
    """Aggregate measured device ns per IR op (ptop_* scope).

    Events whose HLO instruction carries no ptop scope (infeed, copies,
    compiler-inserted glue) aggregate under their hlo op name so the table
    always sums to the measured total."""
    # per-module maps: generic instruction names (fusion.1, copy.3) repeat
    # across compiled blocks, so a flat map would misattribute block A's
    # events to block B's ops
    by_module: Dict[str, Dict[str, str]] = {}
    merged: Dict[str, str] = {}
    for txt in hlo_texts:
        m = hlo_op_name_map(txt)
        by_module.setdefault(hlo_module_name(txt), {}).update(m)
        merged.update(m)
    agg: Dict[str, List[float]] = {}
    for module, hlo_op, dur in device_events(trace_dir, exclusive=True):
        mod_map = by_module.get(module)
        if mod_map and hlo_op in mod_map:
            op_name = mod_map[hlo_op]
        else:
            op_name = merged.get(hlo_op, "")
        m = _SCOPE_RX.search(op_name)
        key = m.group(1) if m else f"[xla] {hlo_op.split('.')[0]}"
        a = agg.setdefault(key, [0.0, 0])
        a[0] += dur
        a[1] += 1
    rows = [{"op": k, "device_ns": int(v[0]), "events": v[1]}
            for k, v in agg.items()]
    rows.sort(key=lambda r: -r["device_ns"])
    return rows


def merge_into_trace(rows: List[dict], trace_path: str) -> None:
    """Append the measured rows as a synthetic 'measured device' track to
    the chrome trace (next to the host events and the modeled op_costs
    track)."""
    import json

    try:
        with open(trace_path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"traceEvents": []}
    ts = 0.0
    for r in rows:
        doc["traceEvents"].append({
            "name": r["op"], "ph": "X", "ts": ts,
            "dur": r["device_ns"] / 1000.0,
            "pid": 1, "tid": 999,
            "args": {"events": r["events"], "track": "measured-device"},
        })
        ts += r["device_ns"] / 1000.0
    with open(trace_path, "w") as f:
        json.dump(doc, f)


def print_rows(rows: List[dict], top: int = 5) -> None:
    total = sum(r["device_ns"] for r in rows) or 1
    print(f"{'Op (measured device time)':<48}{'ns':>12}{'%':>7}{'events':>8}")
    for r in rows[:top]:
        print(f"{r['op']:<48}{r['device_ns']:>12}"
              f"{100.0 * r['device_ns'] / total:>6.1f}%{r['events']:>8}")
