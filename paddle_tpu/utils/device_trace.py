"""Measured per-op device attribution from jax.profiler xplane captures.

The reference measures per-kernel device time with CUPTI and correlates it
to ops by correlation id (platform/device_tracer.cc:1).  The TPU-native
pipeline here:

1. every IR-op lowering runs under ``jax.named_scope("ptop_<type>__<out>")``
   (framework/registry.py run_lowering), so XLA stamps the op identity into
   each HLO instruction's ``metadata.op_name``;
2. ``jax.profiler.trace`` captures the device execution timeline (XPlane);
   each executed HLO instruction/fusion appears as an event with an
   ``hlo_op`` stat and a measured ``duration_ns``;
3. the optimized HLO text of the executed program maps ``hlo_op`` back to
   ``op_name`` and hence to the IR op — fused computations attribute to the
   scope of their root instruction.

The result is MEASURED nanoseconds per IR op for the fused step, not a
cost-model estimate (utils/op_costs.py remains the static/modeled track).
"""
from __future__ import annotations

import glob
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

_METADATA_RX = re.compile(
    r"%?([\w.\-]+)\s*=\s[^\n]*?metadata=\{[^}]*?op_name=\"([^\"]+)\"")
_SCOPE_RX = re.compile(r"(ptop_[A-Za-z0-9_]+)")


_MODULE_RX = re.compile(r"HloModule\s+([\w.\-]+)")


def hlo_op_name_map(hlo_text: str) -> Dict[str, str]:
    """instruction name -> metadata op_name, from optimized HLO text."""
    return dict(_METADATA_RX.findall(hlo_text))


def hlo_module_name(hlo_text: str) -> str:
    m = _MODULE_RX.search(hlo_text)
    return m.group(1) if m else ""


def _latest_xplane(trace_dir: str) -> Optional[str]:
    files = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    return max(files, key=os.path.getmtime) if files else None


def profile_data_cls():
    """The XSpace reader: jax's own ProfileData when the installed jax
    exposes it, else the in-repo wire-format shim (utils/xplane.py — the
    pinned jax 0.4.37 writes captures but ships no reader)."""
    try:
        from jax.profiler import ProfileData  # type: ignore[attr-defined]

        return ProfileData
    except ImportError:
        from .xplane import ProfileData

        return ProfileData


def _line_role(name: str, event_names: Iterable[str]) -> str:
    """Classify a device-plane trace line from OBSERVED names.

    Runtimes disagree on line naming ('XLA Ops' vs bare module lines), and
    trusting one runtime's labels is exactly what multi-counted
    PROFILE_STEP.json (round-5 advisor): whole-step envelopes ('jit_step',
    per-step events '0'..'7') and DMA streams ('copy-done') summed on top of
    the real op timeline. Roles:
      'ops'     — the execution timeline (the only line worth summing)
      'steps'   — whole-step envelopes
      'modules' — whole-executable envelopes
      'async'   — DMA/infeed streams that overlap compute
      'host'    — TraceMe/framework annotation lines
    Line names are tried first; unknown names fall back to what the line's
    events are called.
    """
    n = str(name).strip().lower()
    if "async" in n or "dma" in n:
        return "async"
    if n == "steps" or n.startswith("step"):
        return "steps"
    if "module" in n:
        return "modules"
    if "traceme" in n or "framework" in n or "scope" in n:
        return "host"
    if "op" in n:
        return "ops"
    names = [str(e) for e in event_names if str(e)]
    if names:
        total = len(names)
        if sum(t.isdigit() for t in names) / total > 0.5:
            return "steps"  # per-step envelopes named 0,1,2,...
        if sum(t.startswith(("jit_", "jit(")) or "module" in t.lower()
               for t in names) / total > 0.5:
            return "modules"
        if sum(t.lower().startswith(("copy", "send", "recv", "infeed",
                                     "outfeed"))
               for t in names) / total > 0.8:
            return "async"
    return "ops"


def _exclusive_segments(evs: List[list]) -> List[list]:
    """Per-line nested sweep that records each event's EXCLUSIVE time as
    explicit ``(start, end)`` segments (r[4] = segment list, r[5] = their
    summed ns).

    Within one trace line spans nest (parent %while/call envelopes above
    their children): a parent's open segment closes when a child starts and
    reopens when the child ends, so a parent's segments cover exactly the
    wall time no child occupies. A partially overlapping (non-nested) span
    simply eats the tail of its "parent"'s coverage — nothing goes
    negative, and the cross-line interval-union pass (:func:`_union_rows`)
    is what resolves genuinely parallel streams.
    """
    evs.sort(key=lambda r: (r[0], -r[1]))
    # frame: [end, seg_open, segments, row]
    stack: List[list] = []

    def _close_through(t: float) -> None:
        while stack and t >= stack[-1][0]:
            end, seg_open, segs, _row = stack.pop()
            if end > seg_open:
                segs.append((seg_open, end))
            if stack:   # the parent's coverage resumes where the child ended
                stack[-1][1] = max(stack[-1][1], end)

    for r in evs:
        start, dur = r[0], r[1]
        _close_through(start)
        if stack:
            top = stack[-1]
            if start > top[1]:
                top[2].append((top[1], start))
            top[1] = max(top[1], start + dur)
        segs: List[Tuple[float, float]] = []
        r.append(segs)
        stack.append([start + dur, start, segs, r])
    _close_through(float("inf"))
    for r in evs:
        r.append(sum(e - s for s, e in r[4]))   # r[5] = exclusive ns
    return evs


def _union_rows(rows: List[list]) -> List[list]:
    """Interval-union exclusive attribution across overlapping lines.

    ``rows`` carry per-line exclusive segments (r[4] from
    :func:`_exclusive_segments`).  Lines of one device plane can genuinely
    overlap (parallel streams: multiple op lines, compute vs DMA-adjacent
    work) — summing their per-line exclusive times then exceeds wall-clock
    and used to be *refused* outright (the pre-PR-14 behavior), which made
    every multi-stream trace unattributable.  Instead, sweep the elementary
    intervals of all segments and split each interval's wall time EQUALLY
    among the events active in it.  Appends r[6] = attributed ns:

      * serial traces: exactly one event active everywhere -> identical to
        the plain exclusive sum (r[6] == r[5]);
      * parallel streams: the attributed total equals the interval UNION,
        so sum(attributed) <= wall by construction.
    """
    bounds = sorted({t for r in rows for seg in r[4] for t in seg})
    idx = {t: i for i, t in enumerate(bounds)}
    starts: Dict[int, List[int]] = {}
    ends: Dict[int, List[int]] = {}
    for rid, r in enumerate(rows):
        r.append(0.0)                      # r[6] = union-attributed ns
        for s, e in r[4]:
            if e > s:
                starts.setdefault(idx[s], []).append(rid)
                ends.setdefault(idx[e], []).append(rid)
    active: Dict[int, list] = {}
    for i in range(len(bounds)):
        for rid in ends.get(i, ()):
            active.pop(rid, None)
        for rid in starts.get(i, ()):
            active[rid] = rows[rid]
        if i + 1 < len(bounds) and active:
            share = (bounds[i + 1] - bounds[i]) / len(active)
            for r in active.values():
                r[6] += share
    return rows


def _check_busy_le_wall(rows: List[list], where: str,
                        tolerance: float = 1.001) -> bool:
    """One serial device line keeps sum(exclusive) <= wall. Returns False
    (and says so) when lines overlap — the interval-union pass then owns
    the attribution instead of the plain per-line exclusive sums."""
    import sys

    if not rows:
        return True
    wall = max(r[0] + r[1] for r in rows) - min(r[0] for r in rows)
    busy = sum(r[5] for r in rows)
    if busy > wall * tolerance:
        print(f"[device_trace] note: exclusive sum {busy / 1e6:.1f} ms "
              f"exceeds wall {wall / 1e6:.1f} ms on {where} — overlapping "
              f"device lines; attributing by interval union",
              file=sys.stderr)
        return False
    return True


def device_events(trace_dir: str,
                  exclusive: bool = False) -> Iterable[Tuple[str, str, float]]:
    """Yield (hlo_module, hlo_op, duration_ns) for every device-executed
    HLO event in the newest capture under trace_dir.

    TPU device planes carry several lines: 'Steps' and 'XLA Modules' are
    whole-step envelopes, 'Async XLA Ops' are DMA streams overlapping
    compute, and 'XLA Ops' is the execution timeline — only the latter is
    yielded (summing every line triple-counts: each step appears as a Step
    event, a Module event, and its ops). Line roles are detected from the
    OBSERVED line/event names (``_line_role``), not one runtime's labels.
    'XLA Ops' itself nests parent spans (%while, call ops) above their
    children on the same line; with ``exclusive=True`` each event keeps
    only the wall time no child covers (per-line nested sweep). When the
    surviving lines OVERLAP — parallel streams: several op-role lines, or
    a runtime whose envelope detection is imperfect — per-line exclusive
    sums exceed the plane's wall span; that situation used to be refused
    outright (the round-5 PROFILE_STEP.json multi-count defense), which
    made every multi-stream trace unattributable. Now the plane falls back
    to INTERVAL-UNION attribution: elementary intervals are split equally
    among concurrently active events, so the attributed total equals the
    busy union (<= wall by construction) and serial traces are unchanged.
    """
    import sys

    path = _latest_xplane(trace_dir)
    if path is None:
        return
    pd = profile_data_cls().from_file(path)
    for plane in pd.planes:
        device_plane = plane.name.startswith("/device:")
        lines = list(plane.lines)
        if device_plane:
            classified = [
                (ln, _line_role(str(ln.name), (str(ev.name)
                                               for ev in ln.events)))
                for ln in lines
            ]
            op_lines = [ln for ln, role in classified if role == "ops"]
            if op_lines:
                lines = op_lines
            elif exclusive:
                print(f"[device_trace] warning: no op-role line detected on "
                      f"{plane.name} (lines: "
                      f"{[str(ln.name) for ln in lines]}); refusing "
                      f"exclusive attribution for this plane",
                      file=sys.stderr)
                continue
            else:
                # inclusive mode keeps a permissive fallback: drop the
                # recognized envelope/DMA lines, sum the rest, and say so
                lines = [ln for ln, role in classified
                         if role not in ("steps", "modules", "async")]
                print(f"[device_trace] warning: no op-role line on "
                      f"{plane.name}; summing "
                      f"{[str(ln.name) for ln in lines]}"
                      f" (attribution may overlap)", file=sys.stderr)
        plane_rows: List[list] = []   # device rows held for the plane check
        for line in lines:
            # execution lines only: TPU device planes, or the CPU
            # runtime's execution lines — the client thread
            # ('XLAPjRtCpuClient' / 'tf_XLATfrtCpuClient'; renamed across
            # releases) AND the Eigen intra-op pool ('tf_XLAEigen/...'),
            # where the thunk executor actually runs per-instruction work
            # when it parallelizes (those lines overlap — the
            # interval-union pass owns that). Host python/trace-me lines
            # may carry hlo_op stats too and double-count.
            exec_line = device_plane or "CpuClient" in str(line.name) \
                or "XLAEigen" in str(line.name)
            if not exec_line:
                continue
            evs = []
            for ev in line.events:
                try:
                    stats = dict(ev.stats)
                except Exception:
                    stats = {}
                hlo_op = stats.get("hlo_op")
                if hlo_op is None:
                    if not device_plane:
                        continue
                    # TPU device planes name events by the HLO op directly
                    hlo_op = ev.name
                dur = float(getattr(ev, "duration_ns", 0.0) or 0.0)
                if dur <= 0:
                    continue
                start = float(getattr(ev, "start_ns", 0.0) or 0.0)
                evs.append([start, dur,
                            str(stats.get("hlo_module", plane.name)),
                            str(hlo_op)])
            if exclusive and evs:
                # properly nested spans within the line: each event keeps
                # explicit exclusive (start, end) coverage segments
                plane_rows.extend(_exclusive_segments(evs))
            else:
                for start, dur, module, hlo_op in evs:
                    yield module, hlo_op, dur
        if exclusive and plane_rows:
            # device-busy invariant: one serial timeline keeps the
            # exclusive sum inside the plane's wall span, and the plain
            # per-line sums are exact. Overlapping lines (parallel
            # streams, or envelope lines past role detection) instead go
            # through interval-union attribution so the total can never
            # exceed wall (the round-5 PROFILE_STEP.json multi-count was
            # busy 4.2x wall, emitted as truth).
            if _check_busy_le_wall(plane_rows, str(plane.name)):
                for r in plane_rows:
                    yield r[2], r[3], r[5]
            else:
                for r in _union_rows(plane_rows):
                    yield r[2], r[3], r[6]


def measured_op_rows(trace_dir: str, hlo_texts: List[str]) -> List[dict]:
    """Aggregate measured device ns per IR op (ptop_* scope).

    Events whose HLO instruction carries no ptop scope (infeed, copies,
    compiler-inserted glue) aggregate under their hlo op name so the table
    always sums to the measured total."""
    # per-module maps: generic instruction names (fusion.1, copy.3) repeat
    # across compiled blocks, so a flat map would misattribute block A's
    # events to block B's ops
    by_module: Dict[str, Dict[str, str]] = {}
    merged: Dict[str, str] = {}
    for txt in hlo_texts:
        m = hlo_op_name_map(txt)
        by_module.setdefault(hlo_module_name(txt), {}).update(m)
        merged.update(m)
    agg: Dict[str, List[float]] = {}
    for module, hlo_op, dur in device_events(trace_dir, exclusive=True):
        mod_map = by_module.get(module)
        if mod_map and hlo_op in mod_map:
            op_name = mod_map[hlo_op]
        else:
            op_name = merged.get(hlo_op, "")
        m = _SCOPE_RX.search(op_name)
        key = m.group(1) if m else f"[xla] {hlo_op.split('.')[0]}"
        a = agg.setdefault(key, [0.0, 0])
        a[0] += dur
        a[1] += 1
    rows = [{"op": k, "device_ns": int(v[0]), "events": v[1]}
            for k, v in agg.items()]
    rows.sort(key=lambda r: -r["device_ns"])
    return rows


def merge_into_trace(rows: List[dict], trace_path: str) -> None:
    """Append the measured rows as a synthetic 'measured device' track to
    the chrome trace (next to the host events and the modeled op_costs
    track)."""
    import json

    try:
        with open(trace_path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"traceEvents": []}
    ts = 0.0
    for r in rows:
        doc["traceEvents"].append({
            "name": r["op"], "ph": "X", "ts": ts,
            "dur": r["device_ns"] / 1000.0,
            "pid": 1, "tid": 999,
            "args": {"events": r["events"], "track": "measured-device"},
        })
        ts += r["device_ns"] / 1000.0
    with open(trace_path, "w") as f:
        json.dump(doc, f)


def print_rows(rows: List[dict], top: int = 5) -> None:
    total = sum(r["device_ns"] for r in rows) or 1
    print(f"{'Op (measured device time)':<48}{'ns':>12}{'%':>7}{'events':>8}")
    for r in rows[:top]:
        print(f"{r['op']:<48}{r['device_ns']:>12}"
              f"{100.0 * r['device_ns'] / total:>6.1f}%{r['events']:>8}")
