"""Preallocated slot-major KV cache for the serving engine.

One slab per projection: ``[num_layers, max_slots, max_seq, nh, hd]``,
allocated ONCE at engine startup and threaded through every prefill/decode
executable with buffer donation — steady-state serving never allocates,
never frees, and never changes a shape (the zero-recompile contract,
docs/serving.md).

The device arrays are pure values (jax); what this class owns is the HOST
truth the scheduler plans against: which slots are live, how long each
slot's valid prefix is, and a per-slot generation counter so tests can
prove a freed slot's storage really is reused. Slot state never reaches
the compiled functions — they see only ``positions``/``lengths`` vectors,
so join/evict at token boundaries is a host-side bookkeeping edit, not a
recompile.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["KVCache", "CacheFullError", "TRANSFER_ROW_BUCKET"]

# Row-window width bucket for the transfer path (read_rows/write_rows).
# Windows are widened to a multiple of this (clamped to max_seq) so a
# KV handoff compiles ONE slice/update shape instead of one per chunk
# remainder; kv_transfer chunks at this same width.
TRANSFER_ROW_BUCKET = 64


# Transfer-path row I/O compiled ONCE per row-window size: slot and
# start are traced scalars, so a KV handoff touching every slot at many
# offsets reuses a single executable instead of compiling a fresh
# gather/scatter for each (slot, start) pair (~100ms apiece). Callers
# guarantee start + n <= max_seq — dynamic_slice would silently clamp
# (and shift) an out-of-range window, so the host wrappers assert it.
@functools.partial(jax.jit, static_argnames=("n",))
def _read_rows_exec(k, v, slot, start, *, n):
    sizes = (k.shape[0], 1, n, k.shape[3], k.shape[4])
    zero = jnp.int32(0)
    starts = (zero, slot, start, zero, zero)
    return (jax.lax.dynamic_slice(k, starts, sizes)[:, 0],
            jax.lax.dynamic_slice(v, starts, sizes)[:, 0])


@jax.jit
def _write_rows_exec(k, v, slot, start, k_rows, v_rows):
    zero = jnp.int32(0)
    starts = (zero, slot, start, zero, zero)
    return (jax.lax.dynamic_update_slice(k, k_rows[:, None], starts),
            jax.lax.dynamic_update_slice(v, v_rows[:, None], starts))


class CacheFullError(RuntimeError):
    """All slots are occupied (the scheduler should queue, not crash)."""


@dataclasses.dataclass
class _SlotState:
    live: bool = False
    length: int = 0          # valid prefix length (tokens written)
    generation: int = 0      # bumped on every alloc — reuse visible to tests


class KVCache:
    """Slot allocator + the two cache slabs.

    ``k``/``v`` are replaced wholesale by the engine after every
    prefill/decode call (donated in, fresh handle out). ``max_seq`` bounds
    prompt+generation per slot; ``max_slots`` is the static decode batch.
    """

    def __init__(self, num_layers: int, max_slots: int, max_seq: int,
                 num_heads: int, head_dim: int, dtype: Any = jnp.float32):
        if max_slots < 1 or max_seq < 1:
            raise ValueError("max_slots and max_seq must be >= 1")
        self.num_layers = int(num_layers)
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        shape = (num_layers, max_slots, max_seq, num_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self._slots = [_SlotState() for _ in range(max_slots)]
        self._free: List[int] = list(range(max_slots))

    # -- geometry ----------------------------------------------------------
    @property
    def nbytes(self) -> int:
        return int(self.k.size + self.v.size) * jnp.dtype(self.dtype).itemsize

    # -- slot bookkeeping --------------------------------------------------
    def alloc(self, length: int = 0) -> int:
        """Claim a free slot (lowest index first — deterministic tests);
        raises :class:`CacheFullError` when none is free."""
        if not self._free:
            raise CacheFullError(
                f"all {self.max_slots} KV-cache slots are live")
        if length > self.max_seq:
            raise ValueError(
                f"sequence length {length} exceeds max_seq {self.max_seq}")
        slot = self._free.pop(0)
        st = self._slots[slot]
        st.live = True
        st.length = int(length)
        st.generation += 1
        return slot

    def free(self, slot: int) -> None:
        st = self._slots[slot]
        if not st.live:
            raise ValueError(f"slot {slot} is not live")
        st.live = False
        st.length = 0
        self._free.append(slot)
        self._free.sort()

    def set_length(self, slot: int, length: int) -> None:
        if length > self.max_seq:
            raise ValueError(
                f"slot {slot}: length {length} exceeds max_seq "
                f"{self.max_seq}")
        self._slots[slot].length = int(length)

    def length(self, slot: int) -> int:
        return self._slots[slot].length

    def generation(self, slot: int) -> int:
        return self._slots[slot].generation

    def is_live(self, slot: int) -> bool:
        return self._slots[slot].live

    def live_slots(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s.live]

    def free_slot_count(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return (self.max_slots - len(self._free)) / self.max_slots

    def lengths_vector(self) -> np.ndarray:
        """[max_slots] int32 of valid prefix lengths (0 for dead slots) —
        the host-side source of the decode step's positions feed."""
        return np.array([s.length if s.live else 0 for s in self._slots],
                        np.int32)

    # -- row content I/O (serving/kv_transfer.py handoff) ------------------
    def read_rows(self, slot: int, start: int, n: int):
        """Host copies of ``n`` cache rows of ``slot`` beginning at
        position ``start``: ``([L, n, nh, hd] k, same v)`` — the slab
        analogue of :meth:`PagedKVCache.read_pages`, chunk-sized so a KV
        handoff never materializes a whole slot at once."""
        if start + n > self.max_seq:
            raise ValueError(
                f"read_rows window [{start}, {start + n}) exceeds "
                f"max_seq {self.max_seq}")
        s2, bn, off = self._row_window(start, n)
        k, v = _read_rows_exec(self.k, self.v, jnp.int32(slot),
                               jnp.int32(s2), n=bn)
        k = np.asarray(k)
        v = np.asarray(v)
        return k[:, off:off + n], v[:, off:off + n]

    def _row_window(self, start: int, n: int):
        """Widen [start, start+n) to a bucket-multiple window inside
        [0, max_seq): returns (window_start, window_len, offset of the
        requested rows within the window)."""
        bucket = min(TRANSFER_ROW_BUCKET, self.max_seq)
        bn = min(-(-int(n) // bucket) * bucket, self.max_seq)
        s2 = min(int(start), self.max_seq - bn)
        return s2, bn, int(start) - s2

    def write_rows(self, slot: int, start: int, k_rows: np.ndarray,
                   v_rows: np.ndarray) -> None:
        """Write transferred K/V rows into ``slot`` at ``start`` (host
        path between executable calls — the arrays are replaced
        wholesale, same as the engine does after every step)."""
        k_rows = np.asarray(k_rows)
        v_rows = np.asarray(v_rows)
        n = int(k_rows.shape[1])
        if start + n > self.max_seq:
            raise ValueError(
                f"write_rows window [{start}, {start + n}) exceeds "
                f"max_seq {self.max_seq}")
        s2, bn, off = self._row_window(start, n)
        if bn != n or off:
            # read-modify-write the widened window so the update keeps
            # one compiled shape without clobbering neighbor rows
            cur_k, cur_v = _read_rows_exec(
                self.k, self.v, jnp.int32(slot), jnp.int32(s2), n=bn)
            cur_k = np.array(cur_k)
            cur_v = np.array(cur_v)
            cur_k[:, off:off + n] = k_rows
            cur_v[:, off:off + n] = v_rows
            k_rows, v_rows = cur_k, cur_v
        self.k, self.v = _write_rows_exec(
            self.k, self.v, jnp.int32(slot), jnp.int32(s2),
            jnp.asarray(k_rows, self.dtype),
            jnp.asarray(v_rows, self.dtype))

    def headroom(self, slot: int) -> int:
        """Tokens this slot can still grow by before hitting max_seq."""
        return self.max_seq - self._slots[slot].length
