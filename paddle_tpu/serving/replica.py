"""Serving replica worker: one engine incarnation under the gang
supervisor (ISSUE 15, docs/serving.md "Resilience").

Run as a SCRIPT (``python paddle_tpu/serving/replica.py --config X``) by
:class:`~paddle_tpu.serving.gang.ReplicaGang` — one subprocess per
replica slot. The worker:

- builds the model + :class:`DecodeEngine` from the JSON config
  (deterministic ``init_params(PRNGKey(seed))`` — every replica serves
  identical weights, so a failed-over greedy request returns the same
  tokens its first replica would have),
- restores the persistent prefix store (``prefix_store_dir``) BEFORE
  warmup, so a recycled replica serves the shared-system-prompt workload
  prefill-once from its very first request,
- serves through the standard :class:`FrontDoor` on an ephemeral port,
  reported back through ``ready.json`` (port, pid, restored record
  count),
- arms the hang watchdog from the ``PADDLE_HEALTH_*`` env contract the
  gang exports (the engine loop stamps ``serve/tick`` progress; a wedged
  loop exits :data:`~paddle_tpu.parallel.health.HANG_EXIT_CODE` = 43),
  and writes a liveness heartbeat file the supervisor probes,
- maps a POISONED engine to a fail-fast exit with
  :data:`POISONED_EXIT_CODE` = 44 (the gang recycles with
  ``cause=poisoned``) instead of 500ing every request forever,
- drains gracefully on SIGTERM and exits 0.

``{"stub": {...}}`` configs run a stdlib-only protocol stub (no jax
import — sub-second startup) implementing the same HTTP surface
(``/generate``, ``/health``, ``/metrics``) with deterministic fake
tokens; gang unit tests use it to exercise failover/recycle mechanics
without paying engine warmup per test.

Top-level imports here are stdlib-only on purpose: the gang imports
this module for the exit-code contract, and the stub path must not drag
jax in.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

#: Exit code for a poisoned engine (donation invalidated the KV slabs —
#: engine.py). Distinct from health.HANG_EXIT_CODE (43): the gang maps
#: 44 -> ``paddle_serve_replica_restarts_total{cause="poisoned"}``.
POISONED_EXIT_CODE = 44

READY_NAME = "ready.json"
HEARTBEAT_NAME = "heartbeat.json"


class ReplicaRole:
    """Phase role of a replica in a disaggregated gang (ISSUE 17,
    docs/serving.md "Disaggregation"). Plain string constants — this
    module must stay stdlib-only (no enum import cost matters, but the
    gang JSON-serializes roles into replica configs, so str is the
    native type)."""

    PREFILL = "prefill"      # serves /prefill, ships KV handoffs out
    DECODE = "decode"        # serves /resume, adopts KV handoffs
    COLOCATED = "colocated"  # serves /generate end to end (default)
    ALL = (PREFILL, DECODE, COLOCATED)


def _atomic_json(path: str, obj: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except OSError:
        pass  # liveness files are advisory, never fatal


def _heartbeat_loop(run_dir: str, status_fn, stop: threading.Event,
                    interval_s: float = 0.5) -> None:
    path = os.path.join(run_dir, HEARTBEAT_NAME)
    # first beat lands IMMEDIATELY: staleness detection needs a baseline
    # even when the worker wedges right after coming up
    _atomic_json(path, {"ts": time.time(), "pid": os.getpid(),
                        "status": "starting"})
    while not stop.wait(interval_s):
        try:
            status = status_fn()
        except Exception as e:
            status = f"error: {e}"
        _atomic_json(path, {"ts": time.time(), "pid": os.getpid(),
                            "status": status})


# ---------------------------------------------------------------------------
# Stub worker: protocol-faithful, engine-free (gang unit tests)
# ---------------------------------------------------------------------------

def _stub_tokens(prompt, n):
    return [(sum(prompt) * 31 + i * 7) % 97 for i in range(n)]


_stub_span_lock = threading.Lock()
_stub_span_n = [0]


def _stub_span_append(path: str, name: str, start_ns: int, dur_ns: int,
                      trace: int, parent, attrs: dict) -> None:
    """Append ONE span record (same JSONL shape observability/spans.py
    writes — tools/trace_assemble.py stitches both) with write+flush per
    record, so a SIGKILLed stub's completed spans survive. Stdlib-only
    on purpose: the stub path must not import the observability
    package."""
    with _stub_span_lock:
        _stub_span_n[0] += 1
        span_id = ((os.getpid() & 0xFFFF) << 40) | _stub_span_n[0]
        rec = {"name": name, "trace": int(trace), "span": span_id,
               "parent": None if parent is None else int(parent),
               "start_ns": int(start_ns), "dur_ns": int(dur_ns),
               "tid": threading.get_ident(),
               "thread": threading.current_thread().name,
               "attrs": attrs}
        try:
            with open(path, "a") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()
        except OSError:
            pass


def run_stub(cfg: dict) -> int:
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    stub = cfg.get("stub") or {}
    run_dir = cfg["run_dir"]
    os.makedirs(run_dir, exist_ok=True)
    role = cfg.get("role", ReplicaRole.COLOCATED)
    state = {"served": 0, "hung": False}
    hb_frozen = threading.Event()
    span_path = None
    if cfg.get("trace_dir"):
        # same per-process sink naming as spans.process_sink_path —
        # assembled together with the supervisor's and siblings' files
        os.makedirs(cfg["trace_dir"], exist_ok=True)
        span_path = os.path.join(
            cfg["trace_dir"], f"spans-{role}-{os.getpid()}.jsonl")

    def status():
        if stub.get("poison_after") and \
                state["served"] >= stub["poison_after"]:
            return "poisoned"
        return "ok"

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def _json(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_GET(self):
            if self.path == "/health":
                return self._json(200, {
                    "status": status(), "loop_alive": not state["hung"],
                    "stub": True, "served": state["served"],
                    "role": role})
            if self.path == "/metrics":
                text = (f"paddle_serve_prefill_tokens_total "
                        f"{state['served']}\n").encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
                return
            self._json(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path not in ("/generate", "/prefill", "/resume"):
                return self._json(404, {"error": "unknown path"})
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n).decode() or "{}")
            # trace participation (ISSUE 18): adopt the router's wire
            # context and append one span per handled request — flushed
            # at record, so a killed stub's completed spans survive
            wire = body.get("trace")
            t0 = time.perf_counter_ns()
            try:
                self._post_inner(body)
            finally:
                if span_path and isinstance(wire, dict) \
                        and "trace_id" in wire:
                    _stub_span_append(
                        span_path, "stub" + self.path, t0,
                        time.perf_counter_ns() - t0,
                        trace=wire["trace_id"],
                        parent=wire.get("parent_span"),
                        attrs={"pid": os.getpid(), "role": role})

        def _post_inner(self, body):
            if self.path == "/resume" and stub.get("die_on_resume"):
                # mid-transfer kill: the decode replica dies while the
                # migrated request is in its hands (gang failover test)
                os._exit(int(stub.get("die_code", 1)))
            if stub.get("hang_after") is not None and \
                    state["served"] >= stub["hang_after"]:
                state["hung"] = True
                hb_frozen.set()           # heartbeat goes stale too
                time.sleep(600)
            if stub.get("die_after") is not None and \
                    state["served"] >= stub["die_after"]:
                os._exit(int(stub.get("die_code", 1)))
            delay = float(body.get("stub_delay_s",
                                   stub.get("delay_s", 0.0)))
            if delay:
                time.sleep(delay)
            if status() == "poisoned":
                return self._json(503, {"error": "engine poisoned (stub)"})
            if self.path == "/prefill":
                prompt = body.get("prompt") or []
                if not prompt:
                    return self._json(400, {"error": "empty prompt"})
                state["served"] += 1
                # inline fake handoff: checksum lets /resume verify the
                # blob actually travelled router -> decode intact
                return self._json(200, {
                    "first_token": _stub_tokens(prompt, 1)[0],
                    "ttft_ms": delay * 1e3,
                    "transfer_id": body.get("transfer_id") or "stub",
                    "kv": {"stub": True, "checksum": sum(prompt),
                           "prompt_len": len(prompt),
                           "tokens": list(prompt)},
                    "pid": os.getpid()})
            if self.path == "/resume":
                kv = body.get("kv") or {}
                prompt = kv.get("tokens") or body.get("prompt") or []
                if not prompt or kv.get("checksum") != sum(prompt):
                    return self._json(400, {
                        "error": "stub handoff checksum mismatch"})
                toks = _stub_tokens(prompt,
                                    int(body.get("max_new_tokens", 4)))
                if int(body.get("first_token", toks[0])) != toks[0]:
                    return self._json(400, {
                        "error": "stub first-token mismatch"})
                state["served"] += 1
                return self._json(200, {
                    "tokens": toks, "num_tokens": len(toks),
                    "tpot_ms": 0.0, "pid": os.getpid()})
            prompt = body.get("prompt") or []
            toks = _stub_tokens(prompt,
                                int(body.get("max_new_tokens", 4)))
            state["served"] += 1
            self._json(200, {"tokens": toks, "num_tokens": len(toks),
                             "ttft_ms": delay * 1e3, "tpot_ms": 0.0,
                             "pid": os.getpid()})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    stop_hb = threading.Event()

    def hb_status():
        if hb_frozen.is_set():
            time.sleep(600)               # freeze: supervisor sees stale
        return status()

    threading.Thread(target=_heartbeat_loop,
                     args=(run_dir, hb_status, stop_hb, 0.2),
                     daemon=True).start()
    _atomic_json(os.path.join(run_dir, READY_NAME),
                 {"port": httpd.server_address[1], "pid": os.getpid(),
                  "stub": True, "role": role,
                  "restored_prefix_records": 0})
    import signal

    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    done.wait()
    httpd.shutdown()
    return 0


# ---------------------------------------------------------------------------
# Real worker: DecodeEngine + FrontDoor + prefix-store warm restart
# ---------------------------------------------------------------------------

def run_engine(cfg: dict) -> int:
    import signal

    import jax

    from paddle_tpu import serving
    from paddle_tpu.models import gpt
    from paddle_tpu.parallel import health

    run_dir = cfg["run_dir"]
    os.makedirs(run_dir, exist_ok=True)
    if cfg.get("trace_dir"):
        # per-process span sink under the gang's shared trace dir: every
        # span this replica records (serve/request, serve/prefill,
        # serve/kv_send, ...) appends to spans-<role>-<pid>.jsonl,
        # flushed per record so a SIGKILL loses at most the in-flight
        # span; tools/trace_assemble.py stitches the fleet's files
        from paddle_tpu.observability import spans as ospans

        ospans.attach_process_sink(cfg["trace_dir"],
                                   cfg.get("role", "engine"))
    m = cfg["model"]
    mcfg = gpt.GPTConfig(
        vocab_size=int(m["vocab_size"]),
        max_seq_len=int(m.get("max_seq_len", 64)),
        num_layers=int(m["num_layers"]), num_heads=int(m["num_heads"]),
        d_model=int(m["d_model"]), d_ff=int(m["d_ff"]), remat=False)
    params = gpt.init_params(jax.random.PRNGKey(int(m.get("seed", 0))),
                             mcfg)
    ekw = dict(cfg.get("engine") or {})
    if "prefill_buckets" in ekw:
        ekw["prefill_buckets"] = tuple(int(b)
                                       for b in ekw["prefill_buckets"])
    engine = serving.DecodeEngine(params, mcfg,
                                  serving.EngineConfig(**ekw))
    restored = 0
    store = None
    if cfg.get("prefix_store_dir"):
        from paddle_tpu.serving.kv_transfer import CacheConfigMismatch
        from paddle_tpu.serving.prefix_store import PrefixStore

        store = PrefixStore(cfg["prefix_store_dir"])
        try:
            restored = engine.attach_prefix_store(store)
        except CacheConfigMismatch as e:
            # a mismatched store must not crash-loop the replica under
            # the gang supervisor: log loudly, serve with a cold cache,
            # and DETACH the store so this incarnation neither trusts
            # nor overwrites records shaped for another config
            sys.stderr.write(f"[replica] prefix store rejected — "
                             f"serving cold: {e}\n")
            sys.stderr.flush()
            engine.prefix_store = None
            try:
                store.close()
            except Exception:
                pass
            store = None
    engine.warmup()
    kv_server = None
    if cfg.get("kv_server"):
        from paddle_tpu.serving.kv_transfer import KVTransferServer

        kv_server = KVTransferServer().start()
    skw = dict(cfg.get("scheduler") or {})
    sched = serving.Scheduler(engine, serving.SchedulerConfig(**skw))

    inject = cfg.get("inject") or {}
    if inject:
        orig_step = sched.step

        def step():
            done = sched.completed
            if inject.get("hang_after") is not None \
                    and done >= inject["hang_after"]:
                # wedge the loop: progress stamps stop, the watchdog
                # (armed from the gang's PADDLE_HEALTH_* env) exits 43
                sys.stderr.write("[replica] injected hang\n")
                sys.stderr.flush()
                time.sleep(3600)
            if inject.get("poison_after") is not None and \
                    done >= inject["poison_after"] and \
                    engine.poisoned is None:
                # stand-in for an executable dying after cache donation
                engine.poisoned = ("injected poison "
                                   "(serve_fault_bench)")
            if inject.get("die_after") is not None \
                    and done >= inject["die_after"]:
                os._exit(int(inject.get("die_code", 1)))
            return orig_step()

        sched.step = step

    def on_poison(reason):
        sys.stderr.write(f"[replica] engine poisoned ({reason}) — "
                         f"exiting {POISONED_EXIT_CODE} for the gang\n")
        sys.stderr.flush()
        os._exit(POISONED_EXIT_CODE)

    front = serving.FrontDoor(
        scheduler=sched, port=int(cfg.get("port", 0)),
        max_queue=int(cfg.get("max_queue", 64)),
        request_timeout_s=float(cfg.get("request_timeout_s", 30.0)),
        on_poison=on_poison, kv_server=kv_server).start()
    # the gang's env contract arms the hang watchdog AFTER warmup (the
    # engine's own compiles ran under health.suspend regardless)
    health.maybe_install_from_env()
    front.install_signal_handlers(
        drain_timeout_s=float(cfg.get("drain_timeout_s", 30.0)))

    stop_hb = threading.Event()
    threading.Thread(
        target=_heartbeat_loop,
        args=(run_dir, lambda: front.health()["status"], stop_hb),
        daemon=True).start()
    _atomic_json(os.path.join(run_dir, READY_NAME),
                 {"port": front.port, "pid": os.getpid(),
                  "role": engine.role,
                  "kv_port": (kv_server.port if kv_server is not None
                              else None),
                  "restored_prefix_records": int(restored)})
    sys.stderr.write(f"[replica] ready on port {front.port} "
                     f"role={engine.role} "
                     f"(restored {restored} prefix records)\n")
    sys.stderr.flush()
    try:
        while front._thread is not None and front._thread.is_alive():
            time.sleep(0.2)
    finally:
        stop_hb.set()
        if kv_server is not None:
            try:
                kv_server.close()
            except Exception:
                pass
        if store is not None:
            try:
                store.close()
            except Exception:
                pass
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True,
                    help="path to the replica's JSON config")
    args = ap.parse_args(argv)
    with open(args.config) as f:
        cfg = json.load(f)
    if cfg.get("stub") is not None:
        return run_stub(cfg)
    return run_engine(cfg)


if __name__ == "__main__":
    if __package__ in (None, ""):
        # executed as a file by the gang supervisor: make the package
        # importable without requiring an installed paddle_tpu
        sys.path.insert(0, os.path.abspath(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "..")))
    sys.exit(main())
