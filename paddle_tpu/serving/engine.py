"""TPU-native decode engine: AOT prefill/decode executables over a
preallocated KV cache (docs/serving.md).

The training side already proved the ingredients — PR 1's cached dispatch,
PR 4's explicit ``lower()+compile()`` AOT executables and recompile
explainer, PR 5's chunk-scaled quantizer, PR 12's sharding plans. This
module assembles them into the serving shape:

- **Compiled once, shapes static forever.** Prefill is shape-bucketed
  (one executable per ladder rung, prompts padded up); decode is ONE
  executable over the static ``[max_batch]`` slot layout; the optional
  speculative-verify window is one more static ``[max_batch, W]``
  executable. Requests join and leave the batch by editing host-side slot
  state, never a shape. After :meth:`DecodeEngine.warmup`, steady-state
  serving performs zero compiles; ``paddle_recompiles_total`` is the
  guardrail.
- **KV cache as carried state.** Slab layout
  (``[L, max_batch, max_seq, nh, hd]``, PR 9) or the paged layout
  (``serving/paged_kv.py``: a ``[L, num_pages, page_size, nh, hd]`` pool
  + per-slot page tables fed as device arrays, prefix-cache capable).
  Both are threaded through every executable with buffer donation on TPU.
- **Sampling inside the executables** (``serving/sampling.py``):
  per-slot temperature/top-k/top-p/seed ride as batch inputs — changing
  them never changes a shape. ``temperature=0`` is bit-exact greedy.
- **Tensor-parallel lowering** (``EngineConfig(sharding="tp", tp=N)``):
  attention/MLP weights and the KV head axis shard over an N-chip mesh
  through PR 12's plan machinery (``sharding/plan.py`` suffix
  inheritance) + ``jax.jit`` ``in_shardings``/``out_shardings`` — the
  AOT warmup ladder, cache donation, and the zero-recompile gate all
  survive ``NamedSharding``.
- **Weights in serving precision.** ``weight_dtype="int8"|"bf16"``
  through serving/quant.py; dequantization happens inside the compiled
  functions. (int8's flat chunk layout cannot head-shard — ``tp`` engines
  take f32/bf16.)

The engine is single-threaded by contract: exactly one scheduler loop
calls it (serving/scheduler.py). It is GPT-first (models/gpt.py param
tree); other decoder families plug in by matching the param-tree layout.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models import gpt as gpt_mod
from ..models.gpt import GPTConfig
from ..observability import program_report as _prep
from ..observability import spans as _spans
from ..ops.decode_attention import (cache_update, decode_attention,
                                    paged_cache_update, paged_gather,
                                    paged_page_write,
                                    paged_prefill_attention,
                                    prefill_attention, window_attention,
                                    window_cache_update)
from . import metrics as smetrics
from . import sampling as samp
from .kv_cache import KVCache
from .paged_kv import PagedKVCache, PagePoolFullError, PrefixCache
from .quant import dequantize_params, quantize_params, quantized_nbytes
from .sampling import GREEDY, SamplingParams

__all__ = ["EngineConfig", "DecodeEngine", "PromptTooLongError",
           "default_bucket_ladder"]


class PromptTooLongError(ValueError):
    """Prompt exceeds the largest prefill bucket."""


def default_bucket_ladder(max_seq: int, smallest: int = 16) -> Tuple[int, ...]:
    """Powers of two from ``smallest`` up to ``max_seq`` (inclusive as the
    last rung). Each rung is one AOT-compiled prefill executable — the
    ladder trades warmup compiles against padding waste."""
    out: List[int] = []
    b = smallest
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(sorted(set(out)))


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static serving geometry — every field here is baked into executable
    shapes, so changing one means a new engine (and new compiles)."""
    max_batch: int = 8               # decode slots (the static batch)
    max_seq: int = 256               # per-slot prompt+generation bound
    prefill_buckets: Tuple[int, ...] = ()   # () -> default_bucket_ladder
    weight_dtype: str = "f32"        # "f32" | "bf16" | "int8"
    quant_chunk: int = 256           # int8 scale granularity
    cache_dtype: Any = None          # None -> the model's compute dtype
    eos_id: Optional[int] = None     # greedy decode stops on this token
    # -- KV layout (docs/serving.md "Paged KV") -------------------------
    kv_layout: str = "slab"          # "slab" | "paged"
    page_size: int = 16              # tokens per page (divides buckets)
    num_pages: int = 0               # 0 -> slab-parity pool (+1 scratch)
    prefix_cache: bool = True        # token-hash prefix cache (paged only)
    prefix_cache_pages: int = 0      # 0 -> bounded by the pool itself
    # -- tensor parallelism over PR 12's sharding layer -----------------
    sharding: Optional[str] = None   # None | "tp"
    tp: int = 1                      # mesh size for sharding="tp"
    # -- phase disaggregation (ISSUE 17, docs/serving.md) ----------------
    # "prefill" | "decode" | "colocated": stamps the TTFT/TPOT metric
    # labels and tells the disagg router which fleet this engine serves
    role: str = "colocated"
    # -- speculative decoding (serving/spec_decode.py) ------------------
    verify_window: int = 0           # W>0 compiles the verify executable
    # -- fused decode step (ops/pallas_kernels.py, docs/kernels.md) -----
    # one Pallas launch per layer for cache-row write + masked one-token
    # attention (the paged variant subsumes the page-table gather) plus
    # one launch for the final layernorm + LM-head projection — replaces
    # the decode tick's scatter/gather/attention small-fusion residue
    # ranked by ATTRIBUTION_DECODE.json. Opt-in: interpret-mode Pallas
    # is slower than XLA off-TPU. Masked-lane / scratch-page write-guard
    # semantics are preserved (tests/test_pallas_fused.py).
    fused_decode: bool = False

    def resolved_buckets(self) -> Tuple[int, ...]:
        buckets = tuple(sorted(set(
            int(b) for b in (self.prefill_buckets
                             or default_bucket_ladder(self.max_seq)))))
        if not buckets:
            raise ValueError("prefill_buckets must not be empty")
        if buckets[-1] > self.max_seq:
            raise ValueError(
                f"largest prefill bucket {buckets[-1]} exceeds max_seq "
                f"{self.max_seq}")
        return buckets


class DecodeEngine:
    def __init__(self, params, cfg: GPTConfig, ecfg: EngineConfig):
        if ecfg.max_seq > cfg.max_seq_len:
            raise ValueError(
                f"EngineConfig.max_seq {ecfg.max_seq} exceeds the model's "
                f"positional table {cfg.max_seq_len}")
        self.cfg = cfg
        self.ecfg = ecfg
        self.buckets = ecfg.resolved_buckets()
        self.paged = ecfg.kv_layout == "paged"
        if ecfg.kv_layout not in ("slab", "paged"):
            raise ValueError(f"kv_layout {ecfg.kv_layout!r}: "
                             "expected 'slab' or 'paged'")
        if self.paged:
            bad = [b for b in self.buckets if b % ecfg.page_size]
            if bad:
                raise ValueError(
                    f"paged engine: prefill buckets {bad} are not "
                    f"multiples of page_size {ecfg.page_size}")
        if ecfg.role not in ("prefill", "decode", "colocated"):
            raise ValueError(f"role {ecfg.role!r}: expected 'prefill', "
                             "'decode' or 'colocated'")
        self.role = ecfg.role
        self._donate = jax.default_backend() != "cpu"
        self._ref_params = params                  # f32 truth for parity
        # -- tensor-parallel mesh + shardings (PR 12 plan machinery) ----
        self._mesh = None
        self._param_sh = None
        self._cache_sh = None
        self._repl_sh = None
        if ecfg.sharding not in (None, "tp"):
            raise ValueError(f"sharding {ecfg.sharding!r}: expected None "
                             "or 'tp'")
        qparams = quantize_params(params, ecfg.weight_dtype,
                                  ecfg.quant_chunk)
        if ecfg.sharding == "tp":
            self._init_tp(qparams)
        self.qparams = jax.device_put(qparams, self._param_sh)
        self.weight_nbytes = quantized_nbytes(self.qparams)
        cache_dtype = ecfg.cache_dtype or cfg.dtype
        if self.paged:
            self.cache = PagedKVCache(
                cfg.num_layers, ecfg.max_batch, ecfg.max_seq,
                cfg.num_heads, cfg.head_dim, dtype=cache_dtype,
                page_size=ecfg.page_size, num_pages=ecfg.num_pages)
            self.prefix = (PrefixCache(self.cache,
                                       ecfg.prefix_cache_pages)
                           if ecfg.prefix_cache else None)
            if self.prefix is not None:
                self.cache.reclaimer = self.prefix.reclaim
        else:
            self.cache = KVCache(cfg.num_layers, ecfg.max_batch,
                                 ecfg.max_seq, cfg.num_heads, cfg.head_dim,
                                 dtype=cache_dtype)
            self.prefix = None
        if self._cache_sh is not None:
            self.cache.k = jax.device_put(self.cache.k, self._cache_sh)
            self.cache.v = jax.device_put(self.cache.v, self._cache_sh)
        self._exec: Dict[str, Any] = {}
        self._sig_history: Dict[str, List[dict]] = {}
        self.compiles = 0
        self.steady_state_recompiles = 0
        self._warm = False
        # how much slot headroom a generation step needs (the spec-decode
        # wrapper raises this to its window size)
        self.min_headroom = 1
        # draft engines under spec_decode turn this off: their tokens
        # are proposals, not served output
        self.meter_tokens = True
        # set when an executable fails AFTER its cache buffers were donated
        # (the slabs are invalidated by donation, so no later call can be
        # trusted) — every serving entrypoint refuses from then on
        self.poisoned: Optional[str] = None
        # optional persistent prefix store (serving/prefix_store.py):
        # published pages survive restarts — attach_prefix_store()
        self.prefix_store = None
        self._tokens_window: List[Tuple[float, int]] = []  # (t, n) samples

    def attach_prefix_store(self, store) -> int:
        """Arm warm restart (docs/serving.md "Resilience"): restore the
        store's committed prefix records into the pool + prefix cache
        NOW (call before :meth:`warmup`), and persist every later
        publish through it. Returns how many records were restored."""
        if not self.paged or self.prefix is None:
            raise ValueError("prefix store needs kv_layout='paged' with "
                             "prefix_cache enabled")
        self.prefix_store = store
        return store.restore_into(self)

    # -- KV handoff surface (serving/kv_transfer.py, ISSUE 17) ----------
    def cache_fingerprint(self):
        """Geometry fingerprint of this engine's KV cache — the
        compatibility check on every handoff / prefix-store restore."""
        from .kv_transfer import cache_fingerprint

        return cache_fingerprint(self.cache)

    def export_request_kv(self, slot: int, tokens=None) -> dict:
        """Serialize a live slot's KV state for migration to a decode
        replica (chunked, CRC-stamped, fingerprinted). The slot stays
        live until the caller frees it."""
        from .kv_transfer import export_slot

        return export_slot(self, slot, tokens=tokens)

    def adopt_request_kv(self, handoff: dict) -> int:
        """Materialize a migrated request's KV state into a fresh slot
        (the decode half of a handoff). Raises CacheConfigMismatch on
        geometry drift. Must run on the serving loop thread — it writes
        the cache arrays between executable calls."""
        from .kv_transfer import adopt_into_engine

        return adopt_into_engine(self, handoff)

    def _init_tp(self, qparams) -> None:
        """Mesh + NamedShardings for the tp engine: KV heads and the
        attention/MLP weight split derive from the PR 12 GPT annotation
        set through plan-level suffix inheritance."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import build_mesh
        from ..sharding.plan import (complete_pytree_specs,
                                     gpt_annotations, named_sharding_tree)

        tp = int(self.ecfg.tp)
        if tp < 2:
            raise ValueError("sharding='tp' needs tp >= 2")
        if self.ecfg.weight_dtype == "int8":
            raise ValueError(
                "sharding='tp' cannot head-shard int8's flat chunk "
                "layout — use weight_dtype 'f32' or 'bf16'")
        if len(jax.devices()) < tp:
            raise ValueError(
                f"tp={tp} needs {tp} devices, have {len(jax.devices())}")
        if self.cfg.num_heads % tp or self.cfg.d_ff % tp:
            raise ValueError(
                f"tp={tp} must divide num_heads {self.cfg.num_heads} and "
                f"d_ff {self.cfg.d_ff}")
        self._mesh = build_mesh([("tp", tp)], jax.devices()[:tp])
        ann = gpt_annotations("tp", tp_axis="tp")
        specs, self.tp_derived = complete_pytree_specs(
            qparams, ann, {"tp": tp})
        self._param_sh = named_sharding_tree(specs, self._mesh)
        # slab [L, B, S, nh, hd] and pool [L, P, page, nh, hd] both carry
        # the KV head axis at dim 3 — one spec serves either layout
        self._cache_sh = NamedSharding(
            self._mesh, P(None, None, None, "tp", None))
        self._repl_sh = NamedSharding(self._mesh, P())

    # ------------------------------------------------------------------
    # pure functions (traced once per executable)
    # ------------------------------------------------------------------
    def _dequant(self, qparams):
        return dequantize_params(qparams)

    def _decode_ln(self):
        """The decode tick's layernorm: the fused Pallas block kernel
        under ``EngineConfig.fused_decode``, else the XLA reference."""
        if self.ecfg.fused_decode:
            from ..ops.pallas_kernels import fused_ln as _fln

            return lambda x, scale, bias: _fln(x, scale, bias, eps=1e-5)
        return gpt_mod._layer_norm

    def _block_tail(self, h, a, layer_p, dt, ln, bt: str):
        """Shared post-attention half of a transformer block: projection,
        residual, MLP. ``bt`` is the einsum batch prefix ("b" for decode
        rows, "bt"/"bw" for prefill/verify)."""
        o = jnp.einsum(f"{bt}nh,nhd->{bt}d", a,
                       layer_p["w_proj"].astype(dt))
        h = h + o + layer_p["b_proj"].astype(dt)
        h2 = ln(h, layer_p["ln2_scale"], layer_p["ln2_bias"])
        f = jnp.einsum(f"{bt}d,df->{bt}f", h2, layer_p["w_fc"].astype(dt))
        f = jax.nn.gelu(f + layer_p["b_fc"].astype(dt), approximate=True)
        o2 = jnp.einsum(f"{bt}f,fd->{bt}d", f, layer_p["w_out"].astype(dt))
        return h + o2 + layer_p["b_out"].astype(dt)

    def _prefill_fn(self, qparams, ck, cv, tokens, length, slot,
                    temp, top_k, top_p, seed):
        """tokens [1, T] int32, length/slot + sampling scalars ->
        (ck, cv, logits[V], token).

        Runs the full causal forward over the padded bucket, writes the
        per-layer K/V for positions [0, T) into the cache at ``slot``
        (padding rows land too, but the length mask keeps decode from ever
        reading them), and returns the logits of the LAST VALID position
        plus the token sampled from them — the first generated token comes
        straight out of prefill."""
        cfg = self.cfg
        params = self._dequant(qparams)
        dt = cfg.dtype
        ln = gpt_mod._layer_norm
        x = gpt_mod.embed(params, tokens, cfg)          # [1, T, D]

        def body(h, layer_p):
            h1 = ln(h, layer_p["ln1_scale"], layer_p["ln1_bias"])
            qkv = jnp.einsum("btd,dcnh->btcnh", h1,
                             layer_p["w_qkv"].astype(dt))
            qkv = qkv + layer_p["b_qkv"].astype(dt)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            a = prefill_attention(q, k, v)
            h = self._block_tail(h, a, layer_p, dt, ln, "bt")
            return h, (k, v)

        x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
        # ks: [L, 1, T, nh, hd] -> cache slab write at (slot, 0..T)
        ck = jax.lax.dynamic_update_slice(
            ck, ks.astype(ck.dtype), (0, slot, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cv, vs.astype(cv.dtype), (0, slot, 0, 0, 0))
        h_last = jax.lax.dynamic_index_in_dim(x[0], length - 1, axis=0,
                                              keepdims=False)      # [D]
        h_last = ln(h_last, params["ln_f_scale"], params["ln_f_bias"])
        logits = jnp.einsum("d,dv->v", h_last,
                            params["lm_head"].astype(dt))
        logits = logits.astype(jnp.float32)
        tok = samp.sample_token(logits, temp, top_k, top_p, seed,
                                length - 1)
        return ck, cv, logits, tok

    def _prefill_fn_paged(self, qparams, kp, vp, tokens, length,
                          prefix_len, table_row, temp, top_k, top_p,
                          seed):
        """Paged (prefix-cache capable) prefill: tokens [1, T] is the
        SUFFIX after ``prefix_len`` cached tokens; suffix K/V scatter
        into the slot's own pages, attention runs over the gathered full
        view (cached prefix + suffix). prefix_len == 0 is a plain paged
        prefill."""
        cfg = self.cfg
        params = self._dequant(qparams)
        dt = cfg.dtype
        ln = gpt_mod._layer_norm
        ps = self.ecfg.page_size
        T = tokens.shape[1]
        n_pages = T // ps
        positions = prefix_len + jnp.arange(T)
        x = (params["wte"][tokens]
             + params["wpe"][positions][None]).astype(dt)    # [1, T, D]
        suffix_pages = jax.lax.dynamic_slice(
            table_row, (prefix_len // ps,), (n_pages,))

        def body(h, xs):
            layer_p, kp_l, vp_l = xs
            h1 = ln(h, layer_p["ln1_scale"], layer_p["ln1_bias"])
            qkv = jnp.einsum("btd,dcnh->btcnh", h1,
                             layer_p["w_qkv"].astype(dt))
            qkv = qkv + layer_p["b_qkv"].astype(dt)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            nh, hd = k.shape[2], k.shape[3]
            kp_l = paged_page_write(
                kp_l, k[0].reshape(n_pages, ps, nh, hd), suffix_pages)
            vp_l = paged_page_write(
                vp_l, v[0].reshape(n_pages, ps, nh, hd), suffix_pages)
            k_all = paged_gather(kp_l, table_row[None])  # [1, S, nh, hd]
            v_all = paged_gather(vp_l, table_row[None])
            a = paged_prefill_attention(q, k_all, v_all, prefix_len)
            h = self._block_tail(h, a, layer_p, dt, ln, "bt")
            return h, (kp_l, vp_l)

        x, (kp, vp) = jax.lax.scan(body, x, (params["blocks"], kp, vp))
        h_last = jax.lax.dynamic_index_in_dim(x[0], length - 1, axis=0,
                                              keepdims=False)
        h_last = ln(h_last, params["ln_f_scale"], params["ln_f_bias"])
        logits = jnp.einsum("d,dv->v", h_last,
                            params["lm_head"].astype(dt))
        logits = logits.astype(jnp.float32)
        tok = samp.sample_token(logits, temp, top_k, top_p, seed,
                                prefix_len + length - 1)
        return kp, vp, logits, tok

    def _decode_fn(self, qparams, ck, cv, tokens, positions, actives,
                   temps, top_ks, top_ps, seeds):
        """tokens/positions/actives/sampling [max_batch] -> (ck, cv,
        logits[B, V], tokens[B]).

        One token per slot: write this step's K/V at ``positions``, attend
        over each slot's valid prefix (positions+1), emit next-token
        logits plus the per-slot sampled (or argmax) next token. Lanes
        with ``actives == 0`` ride along shape-stable but write NOTHING —
        a live slot excluded from a partial feed (the spec draft's
        catch-up rounds) keeps every cached row intact."""
        cfg = self.cfg
        params = self._dequant(qparams)
        dt = cfg.dtype
        fused = self.ecfg.fused_decode
        ln = self._decode_ln()
        if fused:
            from ..ops.pallas_kernels import (fused_decode_attention,
                                              fused_logits_head)
        x = (params["wte"][tokens] + params["wpe"][positions]).astype(dt)

        def body(h, xs):
            layer_p, ck_l, cv_l = xs
            h1 = ln(h, layer_p["ln1_scale"], layer_p["ln1_bias"])
            qkv = jnp.einsum("bd,dcnh->bcnh", h1,
                             layer_p["w_qkv"].astype(dt))
            qkv = qkv + layer_p["b_qkv"].astype(dt)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]      # [B, nh, hd]
            if fused:
                # one launch: write-guarded row update + masked attention
                a, ck_l, cv_l = fused_decode_attention(
                    q, ck_l, cv_l, k, v, positions, active=actives)
            else:
                ck_l = cache_update(ck_l, k, positions, active=actives)
                cv_l = cache_update(cv_l, v, positions, active=actives)
                a = decode_attention(q, ck_l, cv_l, positions + 1)
            h = self._block_tail(h, a, layer_p, dt, ln, "b")
            return h, (ck_l, cv_l)

        x, (ck, cv) = jax.lax.scan(body, x,
                                   (params["blocks"], ck, cv))
        if fused:
            logits = fused_logits_head(
                x, params["ln_f_scale"], params["ln_f_bias"],
                params["lm_head"].astype(dt))
        else:
            x = ln(x, params["ln_f_scale"], params["ln_f_bias"])
            logits = jnp.einsum("bd,dv->bv", x,
                                params["lm_head"].astype(dt))
        logits = logits.astype(jnp.float32)
        toks = samp.sample_batch(logits, temps, top_ks, top_ps, seeds,
                                 positions)
        return ck, cv, logits, toks

    def _decode_fn_paged(self, qparams, kp, vp, tokens, positions,
                         tables, temps, top_ks, top_ps, seeds):
        """Paged twin of :meth:`_decode_fn`: per-slot page tables
        [B, max_pages] route the one-row write (scatter) and the
        attention read (gather) through the shared pool. Lanes whose
        table row is all-zero write into the scratch page."""
        cfg = self.cfg
        params = self._dequant(qparams)
        dt = cfg.dtype
        fused = self.ecfg.fused_decode
        ln = self._decode_ln()
        if fused:
            from ..ops.pallas_kernels import (fused_logits_head,
                                              fused_paged_decode_attention)
        ps = self.ecfg.page_size
        x = (params["wte"][tokens] + params["wpe"][positions]).astype(dt)
        phys = jnp.take_along_axis(
            tables, (positions // ps)[:, None], axis=1)[:, 0]
        rows = positions % ps

        def body(h, xs):
            layer_p, kp_l, vp_l = xs
            h1 = ln(h, layer_p["ln1_scale"], layer_p["ln1_bias"])
            qkv = jnp.einsum("bd,dcnh->bcnh", h1,
                             layer_p["w_qkv"].astype(dt))
            qkv = qkv + layer_p["b_qkv"].astype(dt)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            if fused:
                # one launch: row scatter + page gather + masked attention
                # (dead lanes' all-zero tables land the write on the
                # scratch page, same as paged_cache_update)
                a, kp_l, vp_l = fused_paged_decode_attention(
                    q, kp_l, vp_l, k, v, tables, positions)
            else:
                kp_l = paged_cache_update(kp_l, k, phys, rows)
                vp_l = paged_cache_update(vp_l, v, phys, rows)
                k_all = paged_gather(kp_l, tables)      # [B, S, nh, hd]
                v_all = paged_gather(vp_l, tables)
                a = decode_attention(q, k_all, v_all, positions + 1)
            h = self._block_tail(h, a, layer_p, dt, ln, "b")
            return h, (kp_l, vp_l)

        x, (kp, vp) = jax.lax.scan(body, x, (params["blocks"], kp, vp))
        if fused:
            logits = fused_logits_head(
                x, params["ln_f_scale"], params["ln_f_bias"],
                params["lm_head"].astype(dt))
        else:
            x = ln(x, params["ln_f_scale"], params["ln_f_bias"])
            logits = jnp.einsum("bd,dv->bv", x,
                                params["lm_head"].astype(dt))
        logits = logits.astype(jnp.float32)
        toks = samp.sample_batch(logits, temps, top_ks, top_ps, seeds,
                                 positions)
        return kp, vp, logits, toks

    def _verify_fn(self, qparams, ck, cv, tokens, starts, actives,
                   temps, top_ks, top_ps, seeds):
        """Speculative-verify window: tokens [B, W] written at positions
        ``starts + w``, causal window attention over the cache, logits
        AND per-position sampled target tokens for every window slot in
        one batched call (docs/serving.md "Speculative decoding")."""
        cfg = self.cfg
        params = self._dequant(qparams)
        dt = cfg.dtype
        ln = gpt_mod._layer_norm
        W = tokens.shape[1]
        positions = starts[:, None] + jnp.arange(W)      # [B, W]
        x = (params["wte"][tokens] + params["wpe"][positions]).astype(dt)

        def body(h, xs):
            layer_p, ck_l, cv_l = xs
            h1 = ln(h, layer_p["ln1_scale"], layer_p["ln1_bias"])
            qkv = jnp.einsum("bwd,dcnh->bwcnh", h1,
                             layer_p["w_qkv"].astype(dt))
            qkv = qkv + layer_p["b_qkv"].astype(dt)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            ck_l = window_cache_update(ck_l, k, starts,
                                       active=actives)
            cv_l = window_cache_update(cv_l, v, starts,
                                       active=actives)
            a = window_attention(q, ck_l, cv_l, starts)
            h = self._block_tail(h, a, layer_p, dt, ln, "bw")
            return h, (ck_l, cv_l)

        x, (ck, cv) = jax.lax.scan(body, x, (params["blocks"], ck, cv))
        x = ln(x, params["ln_f_scale"], params["ln_f_bias"])
        logits = jnp.einsum("bwd,dv->bwv", x,
                            params["lm_head"].astype(dt))
        logits = logits.astype(jnp.float32)
        toks = samp.sample_window(logits, temps, top_ks, top_ps, seeds,
                                  positions)
        return ck, cv, logits, toks

    def _verify_fn_paged(self, qparams, kp, vp, tokens, starts, tables,
                         temps, top_ks, top_ps, seeds):
        """Paged verify window: B*W rows scatter through the page tables,
        attention reads the gathered per-slot views."""
        cfg = self.cfg
        params = self._dequant(qparams)
        dt = cfg.dtype
        ln = gpt_mod._layer_norm
        ps = self.ecfg.page_size
        B, W = tokens.shape
        positions = starts[:, None] + jnp.arange(W)      # [B, W]
        x = (params["wte"][tokens] + params["wpe"][positions]).astype(dt)
        phys = jnp.take_along_axis(tables, positions // ps, axis=1)
        rows = positions % ps

        def body(h, xs):
            layer_p, kp_l, vp_l = xs
            h1 = ln(h, layer_p["ln1_scale"], layer_p["ln1_bias"])
            qkv = jnp.einsum("bwd,dcnh->bwcnh", h1,
                             layer_p["w_qkv"].astype(dt))
            qkv = qkv + layer_p["b_qkv"].astype(dt)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            nh, hd = k.shape[2], k.shape[3]
            kp_l = paged_cache_update(
                kp_l, k.reshape(B * W, nh, hd),
                phys.reshape(-1), rows.reshape(-1))
            vp_l = paged_cache_update(
                vp_l, v.reshape(B * W, nh, hd),
                phys.reshape(-1), rows.reshape(-1))
            k_all = paged_gather(kp_l, tables)
            v_all = paged_gather(vp_l, tables)
            a = window_attention(q, k_all, v_all, starts)
            h = self._block_tail(h, a, layer_p, dt, ln, "bw")
            return h, (kp_l, vp_l)

        x, (kp, vp) = jax.lax.scan(body, x, (params["blocks"], kp, vp))
        x = ln(x, params["ln_f_scale"], params["ln_f_bias"])
        logits = jnp.einsum("bwd,dv->bwv", x,
                            params["lm_head"].astype(dt))
        logits = logits.astype(jnp.float32)
        toks = samp.sample_window(logits, temps, top_ks, top_ps, seeds,
                                  positions)
        return kp, vp, logits, toks

    # ------------------------------------------------------------------
    # AOT compilation (PR 4 discipline: explicit lower+compile, program
    # report, recompile-explainer integration)
    # ------------------------------------------------------------------
    def _make_sig(self, example_args) -> dict:
        leaves = jax.tree_util.tree_leaves(example_args)
        feed_sig = [(f"arg{i}", tuple(np.shape(a)),
                     str(jnp.result_type(a))) for i, a in enumerate(leaves)]
        return _prep.make_sig(feed_sig, fetch_names=())

    def _shardings_for(self, example_args, n_outputs: int):
        """(in_shardings, out_shardings) pytrees for the tp mesh: params
        take the plan shardings, cache slabs the KV-head split, every
        other input/output replicates. None/None off-mesh."""
        if self._mesh is None:
            return None, None
        ins = [self._param_sh, self._cache_sh, self._cache_sh]
        ins += [self._repl_sh] * (len(example_args) - 3)
        outs = [self._cache_sh, self._cache_sh]
        outs += [self._repl_sh] * (n_outputs - 2)
        return tuple(ins), tuple(outs)

    def _compile(self, name: str, fn, example_args,
                 donate_argnums: Tuple[int, ...],
                 n_outputs: int = 4) -> Any:
        from ..parallel import health as _health

        sig = self._make_sig(example_args)
        hist = self._sig_history.setdefault(name, [])
        if hist:
            # a same-name rebuild is exactly what steady state must never
            # do: explain it through the PR 4 taxonomy and count it
            cause, detail = _prep.explain_recompile(sig, hist)
            _prep.note_recompile(f"serve/{name}", cause, detail)
            if self._warm:
                self.steady_state_recompiles += 1
        hist.append(sig)
        del hist[:-8]
        in_sh, out_sh = self._shardings_for(example_args, n_outputs)
        jit_kw: Dict[str, Any] = dict(
            donate_argnums=donate_argnums if self._donate else ())
        if in_sh is not None:
            jit_kw.update(in_shardings=in_sh, out_shardings=out_sh)
        jitted = jax.jit(fn, **jit_kw)
        t0 = time.perf_counter_ns()
        with _health.suspend():
            lowered = jitted.lower(*example_args)
            compiled = lowered.compile()
        compile_ms = (time.perf_counter_ns() - t0) / 1e6
        self.compiles += 1
        donated = [f"arg{i}" for i in donate_argnums] if self._donate else []
        _prep.capture(
            f"serve/{name}", compiled=compiled, compile_ms=compile_ms,
            donated=donated, inputs=example_args,
            extra={"engine": {
                "max_batch": self.ecfg.max_batch,
                "max_seq": self.ecfg.max_seq,
                "weight_dtype": self.ecfg.weight_dtype,
                "cache_dtype": str(jnp.dtype(self.cache.dtype).name),
                "kv_layout": self.ecfg.kv_layout,
                "sharding": self.ecfg.sharding or "none",
                "tp": self.ecfg.tp,
                "buckets": list(self.buckets),
            }})
        return compiled

    def _samp_scalar_examples(self):
        return (np.float32(0.0), np.int32(0), np.float32(1.0),
                np.int32(0))

    def _samp_batch_examples(self):
        B = self.ecfg.max_batch
        return (np.zeros((B,), np.float32), np.zeros((B,), np.int32),
                np.ones((B,), np.float32), np.zeros((B,), np.int32))

    def _prefill_exec(self, bucket: int):
        name = f"prefill_b{bucket}"
        exe = self._exec.get(name)
        if exe is None:
            if self.paged:
                M = self.cache.max_pages_per_slot
                example = (self.qparams, self.cache.k, self.cache.v,
                           np.zeros((1, bucket), np.int32), np.int32(1),
                           np.int32(0), np.zeros((M,), np.int32),
                           *self._samp_scalar_examples())
                exe = self._compile(name, self._prefill_fn_paged, example,
                                    donate_argnums=(1, 2))
            else:
                example = (self.qparams, self.cache.k, self.cache.v,
                           np.zeros((1, bucket), np.int32), np.int32(1),
                           np.int32(0), *self._samp_scalar_examples())
                exe = self._compile(name, self._prefill_fn, example,
                                    donate_argnums=(1, 2))
            self._exec[name] = exe
        return exe

    def _decode_exec(self):
        exe = self._exec.get("decode")
        if exe is None:
            B = self.ecfg.max_batch
            if self.paged:
                M = self.cache.max_pages_per_slot
                example = (self.qparams, self.cache.k, self.cache.v,
                           np.zeros((B,), np.int32),
                           np.zeros((B,), np.int32),
                           np.zeros((B, M), np.int32),
                           *self._samp_batch_examples())
                exe = self._compile("decode", self._decode_fn_paged,
                                    example, donate_argnums=(1, 2))
            else:
                example = (self.qparams, self.cache.k, self.cache.v,
                           np.zeros((B,), np.int32),
                           np.zeros((B,), np.int32),
                           np.zeros((B,), np.int32),
                           *self._samp_batch_examples())
                exe = self._compile("decode", self._decode_fn, example,
                                    donate_argnums=(1, 2))
            self._exec["decode"] = exe
        return exe

    def _verify_exec(self):
        W = self.ecfg.verify_window
        if W < 2:
            raise ValueError("verify executable needs verify_window >= 2")
        name = f"verify_w{W}"
        exe = self._exec.get(name)
        if exe is None:
            B = self.ecfg.max_batch
            if self.paged:
                M = self.cache.max_pages_per_slot
                example = (self.qparams, self.cache.k, self.cache.v,
                           np.zeros((B, W), np.int32),
                           np.zeros((B,), np.int32),
                           np.zeros((B, M), np.int32),
                           *self._samp_batch_examples())
                exe = self._compile(name, self._verify_fn_paged, example,
                                    donate_argnums=(1, 2))
            else:
                example = (self.qparams, self.cache.k, self.cache.v,
                           np.zeros((B, W), np.int32),
                           np.zeros((B,), np.int32),
                           np.zeros((B,), np.int32),
                           *self._samp_batch_examples())
                exe = self._compile(name, self._verify_fn, example,
                                    donate_argnums=(1, 2))
            self._exec[name] = exe
        return exe

    def warmup(self) -> Dict[str, float]:
        """Compile every executable the steady state will ever need (the
        decode program + one prefill per bucket + the verify window when
        configured) and run each once so the first real request pays no
        compile and no first-dispatch cost. Returns {executable_name:
        wall ms per warm call}."""
        timings: Dict[str, float] = {}
        B = self.ecfg.max_batch
        zeros_b = np.zeros((B,), np.int32)

        def _warm_call(label, exe, *args):
            t0 = time.perf_counter()
            out = exe(self.qparams, self.cache.k, self.cache.v, *args)
            jax.block_until_ready(out[2])
            self.cache.k, self.cache.v = out[0], out[1]
            timings[label] = (time.perf_counter() - t0) * 1e3

        dec = self._decode_exec()
        if self.paged:
            M = self.cache.max_pages_per_slot
            _warm_call("decode", dec, zeros_b, zeros_b,
                       np.zeros((B, M), np.int32),
                       *self._samp_batch_examples())
        else:
            _warm_call("decode", dec, zeros_b, zeros_b, zeros_b,
                       *self._samp_batch_examples())
        for bucket in self.buckets:
            exe = self._prefill_exec(bucket)
            if self.paged:
                M = self.cache.max_pages_per_slot
                _warm_call(f"prefill_b{bucket}", exe,
                           np.zeros((1, bucket), np.int32), np.int32(1),
                           np.int32(0), np.zeros((M,), np.int32),
                           *self._samp_scalar_examples())
            else:
                _warm_call(f"prefill_b{bucket}", exe,
                           np.zeros((1, bucket), np.int32), np.int32(1),
                           np.int32(0), *self._samp_scalar_examples())
        if self.ecfg.verify_window >= 2:
            W = self.ecfg.verify_window
            ver = self._verify_exec()
            if self.paged:
                M = self.cache.max_pages_per_slot
                _warm_call(f"verify_w{W}", ver,
                           np.zeros((B, W), np.int32), zeros_b,
                           np.zeros((B, M), np.int32),
                           *self._samp_batch_examples())
            else:
                _warm_call(f"verify_w{W}", ver,
                           np.zeros((B, W), np.int32), zeros_b, zeros_b,
                           *self._samp_batch_examples())
        # transfer-path gather/scatter (KV handoff + prefix store): one
        # compiled shape each — warmed here so a disagg handoff's first
        # export/adopt never pays a mid-request compile (~100ms)
        t0 = time.perf_counter()
        if self.paged:
            k0, v0 = self.cache.read_pages([0])
            self.cache.write_pages([0], k0, v0)
        else:
            from .kv_transfer import DEFAULT_CHUNK_ROWS
            n = min(DEFAULT_CHUNK_ROWS, self.ecfg.max_seq)
            k0, v0 = self.cache.read_rows(0, 0, n)
            self.cache.write_rows(0, 0, k0, v0)
        timings["kv_transfer"] = (time.perf_counter() - t0) * 1e3
        self._warm = True
        return timings

    # ------------------------------------------------------------------
    # host-side serving API (one scheduler thread)
    # ------------------------------------------------------------------
    def _check_poisoned(self) -> None:
        if self.poisoned is not None:
            raise RuntimeError(f"engine poisoned: {self.poisoned}")

    def _poison_on_donation_failure(self, name: str, exc: Exception) -> None:
        """An executable compiled with donate_argnums died mid-call: the
        cache slabs it was handed are donation-invalidated, so cache.k/v
        can no longer be trusted. Mark the engine fatally poisoned rather
        than let later calls read freed buffers. (Without donation — CPU —
        the slabs are untouched and the engine stays usable.)"""
        if self._donate and self.poisoned is None:
            self.poisoned = (
                f"{name} failed after cache-buffer donation "
                f"({type(exc).__name__}: {exc}); KV slabs invalidated — "
                f"rebuild the engine")

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise PromptTooLongError(
            f"prompt length {n} exceeds the largest prefill bucket "
            f"{self.buckets[-1]}")

    def can_admit(self, prompt_len: int) -> bool:
        """Would a prompt admit RIGHT NOW (slot + page budget)? The
        scheduler's head-of-line check — never raises."""
        if self.paged:
            # conservative: require the full prompt's pages (a prefix hit
            # only makes admission cheaper; reclaimable cache pages count)
            return self.cache.can_admit(prompt_len)
        return self.cache.free_slot_count() > 0

    def _trim_prefix(self, n: int, prefix_len: int,
                     prefix_pages: Tuple[int, ...]):
        """Shrink a prefix hit until the suffix bucket fits behind it
        (prefix_len + bucket(suffix) <= max_seq keeps the page-write
        slice in range)."""
        while prefix_len > 0:
            try:
                bucket = self.bucket_for(n - prefix_len)
            except PromptTooLongError:
                bucket = None
            if bucket is not None and prefix_len + bucket <= self.ecfg.max_seq:
                return prefix_len, prefix_pages
            prefix_len -= self.cache.page_size
            prefix_pages = prefix_pages[:-1]
        return 0, ()

    def start_sequence(self, tokens: Sequence[int]) -> Tuple[int, np.ndarray]:
        """Claim a slot, prefill the prompt, return (slot, logits[V]) of
        the last prompt position — argmax of it is the first generated
        token. Raises CacheFullError when no slot is free,
        PagePoolFullError when the paged pool is dry, and
        PromptTooLongError above the ladder."""
        slot, logits, _tok = self.start_sequence_sampled(tokens, GREEDY)
        return slot, logits

    def start_sequence_sampled(
            self, tokens: Sequence[int], params: SamplingParams
    ) -> Tuple[int, np.ndarray, int]:
        """:meth:`start_sequence` plus in-executable sampling: returns
        (slot, last-position logits[V], first generated token)."""
        self._check_poisoned()
        n = len(tokens)
        if n < 1:
            raise ValueError("empty prompt")
        sp_scalars = (np.float32(params.temperature),
                      np.int32(params.top_k), np.float32(params.top_p),
                      np.int32(np.uint32(params.seed)))
        if self.paged:
            return self._start_paged(tokens, n, sp_scalars)
        bucket = self.bucket_for(n)
        exe = self._prefill_exec(bucket)
        slot = self.cache.alloc(length=n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = np.asarray(tokens, np.int32)
        t0 = time.perf_counter_ns()
        try:
            ck, cv, logits, tok = exe(
                self.qparams, self.cache.k, self.cache.v, padded,
                np.int32(n), np.int32(slot), *sp_scalars)
            logits = np.asarray(logits)
            tok = int(tok)
        except Exception as e:
            self._poison_on_donation_failure(f"prefill_b{bucket}", e)
            self.cache.free(slot)
            raise
        t1 = time.perf_counter_ns()
        smetrics.m_prefill_ms.observe((t1 - t0) / 1e6)
        smetrics.m_prefill_tokens.inc(n)
        # inherits the scheduler's per-request span context (the admit
        # path wraps this call in the request's trace)
        _spans.record("serve/prefill", t0, t1 - t0,
                      attrs={"bucket": bucket, "prompt_len": n,
                             "slot": slot})
        self.cache.k, self.cache.v = ck, cv
        return slot, logits, tok

    def _start_paged(self, tokens, n: int, sp_scalars):
        prefix_len, prefix_pages = 0, ()
        if self.prefix is not None:
            prefix_len, prefix_pages = self.prefix.lookup(tokens)
            prefix_len, prefix_pages = self._trim_prefix(
                n, prefix_len, tuple(prefix_pages))
        suffix = list(tokens[prefix_len:])
        bucket = self.bucket_for(len(suffix))
        exe = self._prefill_exec(bucket)
        slot = self.cache.alloc(length=n, prefix_pages=prefix_pages)
        table_row = self.cache.table_row(slot)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(suffix)] = np.asarray(suffix, np.int32)
        t0 = time.perf_counter_ns()
        try:
            kp, vp, logits, tok = exe(
                self.qparams, self.cache.k, self.cache.v, padded,
                np.int32(len(suffix)), np.int32(prefix_len), table_row,
                *sp_scalars)
            logits = np.asarray(logits)
            tok = int(tok)
        except Exception as e:
            self._poison_on_donation_failure(f"prefill_b{bucket}", e)
            self.cache.free(slot)
            raise
        t1 = time.perf_counter_ns()
        smetrics.m_prefill_ms.observe((t1 - t0) / 1e6)
        smetrics.m_prefill_tokens.inc(len(suffix))
        _spans.record("serve/prefill", t0, t1 - t0,
                      attrs={"bucket": bucket, "prompt_len": n,
                             "prefix_len": prefix_len, "slot": slot})
        self.cache.k, self.cache.v = kp, vp
        if self.prefix is not None:
            added = self.prefix.insert(tokens, table_row)
            if added and self.prefix_store is not None:
                # persist at publish time: the pages just written are the
                # ones a recycled replica restores (async, CRC-committed)
                self.prefix_store.maybe_publish(tokens, table_row,
                                                self.cache)
        return slot, logits, tok

    def resume_sequence_sampled(
            self, tokens: Sequence[int], params: SamplingParams
    ) -> Tuple[int, np.ndarray, int]:
        """Re-prefill a preempted request's prompt+generated stream —
        which may exceed the bucket ladder: the head prefills through
        the largest bucket and the tail replays through the decode
        executable (whose sampled outputs are discarded; the stream is
        already known). Returns (slot, last logits[V], next token) like
        :meth:`start_sequence_sampled` — same executables, zero new
        compiles."""
        n = len(tokens)
        if n <= self.buckets[-1]:
            return self.start_sequence_sampled(tokens, params)
        head = list(tokens[:self.buckets[-1]])
        slot, _logits, _tok = self.start_sequence_sampled(head, params)
        try:
            for i in range(len(head), n - 1):
                self.decode_step_sampled({slot: int(tokens[i])}, None)
            out = self.decode_step_sampled(
                {slot: int(tokens[n - 1])}, {slot: params})
        except Exception:
            if self.poisoned is None and self.cache.is_live(slot):
                self.cache.free(slot)
            raise
        tok, logits = out[slot]
        return slot, logits, tok

    def ensure_decode_capacity(self, slot: int, extra: int = 1) -> bool:
        """Make the next ``extra`` token positions of ``slot`` writable.
        Paged: maps pages on demand (False = pool dry even after
        prefix-cache reclaim — the scheduler preempts). Slab: always
        True (the slab IS the capacity; headroom is checked separately)."""
        if not self.paged:
            return True
        return self.cache.ensure_capacity(
            slot, self.cache.length(slot) + extra)

    def _decode_feed(self, slot_tokens: Dict[int, int]):
        B = self.ecfg.max_batch
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        for slot, tok in slot_tokens.items():
            if not self.cache.is_live(slot):
                raise ValueError(f"slot {slot} is not live")
            if self.cache.headroom(slot) < 1:
                raise ValueError(
                    f"slot {slot} is at max_seq {self.ecfg.max_seq}")
            tokens[slot] = tok
            positions[slot] = self.cache.length(slot)
        return tokens, positions

    def _masked_tables(self, active_slots) -> np.ndarray:
        """Page-table feed with non-participating lanes zeroed so their
        writes land in the scratch page — a live slot absent from this
        call keeps its pages untouched."""
        tables = self.cache.tables()
        active = set(active_slots)
        for s in range(self.ecfg.max_batch):
            if s not in active:
                tables[s, :] = 0
        return tables

    def decode_step(self, slot_tokens: Dict[int, int]) -> Dict[int, np.ndarray]:
        """One greedy-compatible decode step for the given
        {slot: input_token} map. Returns {slot: logits[V]} (PR 9 API —
        callers argmax host-side; :meth:`decode_step_sampled` returns the
        in-executable sampled tokens too)."""
        out = self.decode_step_sampled(slot_tokens, None)
        return {slot: logits for slot, (_tok, logits) in out.items()}

    def decode_step_sampled(
            self, slot_tokens: Dict[int, int],
            params_by_slot: Optional[Dict[int, SamplingParams]]
    ) -> Dict[int, Tuple[int, np.ndarray]]:
        """One decode step with per-slot sampling: {slot: input_token} ->
        {slot: (next_token, logits[V])}. Slots not in the map ride as
        masked lanes — same shapes, same executable, zero recompiles."""
        if not slot_tokens:
            return {}
        self._check_poisoned()
        tokens, positions = self._decode_feed(slot_tokens)
        sp = samp.batch_arrays(params_by_slot or {}, self.ecfg.max_batch)
        exe = self._decode_exec()
        t0 = time.perf_counter_ns()
        try:
            if self.paged:
                for slot in slot_tokens:
                    if not self.ensure_decode_capacity(slot):
                        raise PagePoolFullError(
                            f"slot {slot}: no free page for position "
                            f"{self.cache.length(slot)}")
                tables = self._masked_tables(slot_tokens)
                ck, cv, logits, toks = exe(
                    self.qparams, self.cache.k, self.cache.v, tokens,
                    positions, tables, *sp)
            else:
                actives = np.zeros((self.ecfg.max_batch,), np.int32)
                for slot in slot_tokens:
                    actives[slot] = 1
                ck, cv, logits, toks = exe(
                    self.qparams, self.cache.k, self.cache.v, tokens,
                    positions, actives, *sp)
            logits = np.asarray(logits)
            toks = np.asarray(toks)
        except PagePoolFullError:
            raise                      # host-side: nothing was donated
        except Exception as e:
            self._poison_on_donation_failure("decode", e)
            raise
        smetrics.m_decode_ms.observe((time.perf_counter_ns() - t0) / 1e6)
        self.cache.k, self.cache.v = ck, cv
        out: Dict[int, Tuple[int, np.ndarray]] = {}
        for slot in slot_tokens:
            self.cache.set_length(slot, self.cache.length(slot) + 1)
            out[slot] = (int(toks[slot]), logits[slot])
        self.note_tokens(len(slot_tokens))
        return out

    def generate_step(
            self, slot_tokens: Dict[int, int],
            params_by_slot: Optional[Dict[int, SamplingParams]] = None
    ) -> Dict[int, List[int]]:
        """Uniform scheduler surface: one generation step -> {slot:
        [emitted tokens]}. The plain engine emits exactly one token per
        slot; the speculative wrapper (serving/spec_decode.py) emits up
        to its window."""
        return {slot: [tok] for slot, (tok, _logits) in
                self.decode_step_sampled(slot_tokens,
                                         params_by_slot).items()}

    def verify_step(
            self, windows: Dict[int, Sequence[int]],
            params_by_slot: Optional[Dict[int, SamplingParams]] = None
    ) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
        """Speculative verification: {slot: [W window tokens]} -> {slot:
        (logits[W, V], target_tokens[W])} in ONE batched call. Window
        rows are written into the cache at ``length + w``; slot lengths
        are NOT advanced — the caller decides how many window positions
        were accepted and calls :meth:`commit_window`."""
        if not windows:
            return {}
        self._check_poisoned()
        W = self.ecfg.verify_window
        if W < 2:
            raise RuntimeError("engine compiled without a verify window")
        B = self.ecfg.max_batch
        tokens = np.zeros((B, W), np.int32)
        starts = np.zeros((B,), np.int32)
        for slot, win in windows.items():
            if len(win) != W:
                raise ValueError(
                    f"slot {slot}: window {len(win)} != {W}")
            if not self.cache.is_live(slot):
                raise ValueError(f"slot {slot} is not live")
            if self.cache.headroom(slot) < W:
                raise ValueError(f"slot {slot}: headroom < window {W}")
            tokens[slot] = np.asarray(win, np.int32)
            starts[slot] = self.cache.length(slot)
        sp = samp.batch_arrays(params_by_slot or {}, B)
        exe = self._verify_exec()
        t0 = time.perf_counter_ns()
        try:
            if self.paged:
                for slot in windows:
                    if not self.ensure_decode_capacity(slot, extra=W):
                        raise PagePoolFullError(
                            f"slot {slot}: no free pages for a {W}-token "
                            "verify window")
                tables = self._masked_tables(windows)
                ck, cv, logits, toks = exe(
                    self.qparams, self.cache.k, self.cache.v, tokens,
                    starts, tables, *sp)
            else:
                actives = np.zeros((B,), np.int32)
                for slot in windows:
                    actives[slot] = 1
                ck, cv, logits, toks = exe(
                    self.qparams, self.cache.k, self.cache.v, tokens,
                    starts, actives, *sp)
            logits = np.asarray(logits)
            toks = np.asarray(toks)
        except PagePoolFullError:
            raise
        except Exception as e:
            self._poison_on_donation_failure(
                f"verify_w{W}", e)
            raise
        smetrics.m_decode_ms.observe((time.perf_counter_ns() - t0) / 1e6)
        self.cache.k, self.cache.v = ck, cv
        return {slot: (logits[slot], toks[slot]) for slot in windows}

    def commit_window(self, slot: int, n_accepted_rows: int) -> None:
        """Advance ``slot`` past ``n_accepted_rows`` verified window rows
        (their K/V are already in the cache; rejected rows simply get
        overwritten by later writes)."""
        self.cache.set_length(slot, self.cache.length(slot)
                              + int(n_accepted_rows))

    def free_sequence(self, slot: int) -> None:
        self.cache.free(slot)

    # ------------------------------------------------------------------
    def note_tokens(self, n: int, window_s: float = 5.0) -> None:
        if not self.meter_tokens:
            return
        now = time.monotonic()
        smetrics.m_tokens.inc(n)
        w = self._tokens_window
        w.append((now, n))
        while w and w[0][0] < now - window_s:
            w.pop(0)
        span = now - w[0][0] if len(w) > 1 else 0.0
        if span > 0:
            smetrics.m_tokens_per_s.set(sum(x[1] for x in w) / span)

    # ------------------------------------------------------------------
    # reference / parity surface (tests + serve_bench quality bar)
    # ------------------------------------------------------------------
    def reference_logits(self, tokens: Sequence[int]) -> np.ndarray:
        """Full-forward f32-weight logits [T, V] for a prompt — the truth
        the cached decode path and the quantized weights are held to."""
        if self._ref_params is None:
            raise RuntimeError("reference params were dropped")
        toks = np.asarray(tokens, np.int32)[None]
        return np.asarray(
            gpt_mod.forward(self._ref_params, toks, self.cfg)[0],
            np.float32)

    def drop_reference_params(self) -> None:
        self._ref_params = None

    @property
    def executables(self) -> List[str]:
        return sorted(self._exec)
