"""TPU-native decode engine: AOT prefill/decode executables over a
preallocated KV cache (docs/serving.md).

The training side already proved the ingredients — PR 1's cached dispatch,
PR 4's explicit ``lower()+compile()`` AOT executables and recompile
explainer, PR 5's chunk-scaled quantizer. This module assembles them into
the serving shape:

- **Two program families, compiled once.** Prefill is shape-bucketed: a
  small fixed ladder of sequence lengths (``EngineConfig.prefill_buckets``),
  one executable per bucket, every prompt padded up to its bucket. Decode
  is ONE executable over the static ``[max_batch]`` slot layout — requests
  join and leave the batch by editing host-side slot state, never a shape.
  After :meth:`DecodeEngine.warmup`, steady-state serving performs zero
  compiles; the PR 4 ``paddle_recompiles_total`` counter is the guardrail
  (tools/metrics_check.py asserts its delta is exactly zero across a
  warmed smoke serve).
- **KV cache as carried state.** Both executables take the cache slabs as
  arguments and return the updated slabs; on TPU the buffers are donated,
  so the update is an in-place HBM write (donation is skipped on backends
  that do not support it — CPU — where it would only emit warnings).
- **Weights in serving precision.** ``weight_dtype="int8"|"bf16"`` stores
  params through serving/quant.py; dequantization happens inside the
  compiled functions so HBM holds the quantized bytes. The f32 reference
  params are kept host-side for the parity bar (drop them with
  :meth:`drop_reference_params` when HBM matters).

The engine is single-threaded by contract: exactly one scheduler loop
calls it (serving/scheduler.py). It is GPT-first (models/gpt.py param
tree); other decoder families plug in by matching the param-tree layout.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models import gpt as gpt_mod
from ..models.gpt import GPTConfig
from ..observability import program_report as _prep
from ..observability import spans as _spans
from ..ops.decode_attention import (cache_update, decode_attention,
                                    prefill_attention)
from . import metrics as smetrics
from .kv_cache import KVCache
from .quant import dequantize_params, quantize_params, quantized_nbytes

__all__ = ["EngineConfig", "DecodeEngine", "PromptTooLongError",
           "default_bucket_ladder"]


class PromptTooLongError(ValueError):
    """Prompt exceeds the largest prefill bucket."""


def default_bucket_ladder(max_seq: int, smallest: int = 16) -> Tuple[int, ...]:
    """Powers of two from ``smallest`` up to ``max_seq`` (inclusive as the
    last rung). Each rung is one AOT-compiled prefill executable — the
    ladder trades warmup compiles against padding waste."""
    out: List[int] = []
    b = smallest
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(sorted(set(out)))


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static serving geometry — every field here is baked into executable
    shapes, so changing one means a new engine (and new compiles)."""
    max_batch: int = 8               # decode slots (the static batch)
    max_seq: int = 256               # per-slot prompt+generation bound
    prefill_buckets: Tuple[int, ...] = ()   # () -> default_bucket_ladder
    weight_dtype: str = "f32"        # "f32" | "bf16" | "int8"
    quant_chunk: int = 256           # int8 scale granularity
    cache_dtype: Any = None          # None -> the model's compute dtype
    eos_id: Optional[int] = None     # greedy decode stops on this token

    def resolved_buckets(self) -> Tuple[int, ...]:
        buckets = tuple(sorted(set(
            int(b) for b in (self.prefill_buckets
                             or default_bucket_ladder(self.max_seq)))))
        if not buckets:
            raise ValueError("prefill_buckets must not be empty")
        if buckets[-1] > self.max_seq:
            raise ValueError(
                f"largest prefill bucket {buckets[-1]} exceeds max_seq "
                f"{self.max_seq}")
        return buckets


class DecodeEngine:
    def __init__(self, params, cfg: GPTConfig, ecfg: EngineConfig):
        if ecfg.max_seq > cfg.max_seq_len:
            raise ValueError(
                f"EngineConfig.max_seq {ecfg.max_seq} exceeds the model's "
                f"positional table {cfg.max_seq_len}")
        self.cfg = cfg
        self.ecfg = ecfg
        self.buckets = ecfg.resolved_buckets()
        self._donate = jax.default_backend() != "cpu"
        self._ref_params = params                  # f32 truth for parity
        self.qparams = jax.device_put(
            quantize_params(params, ecfg.weight_dtype, ecfg.quant_chunk))
        self.weight_nbytes = quantized_nbytes(self.qparams)
        cache_dtype = ecfg.cache_dtype or cfg.dtype
        self.cache = KVCache(cfg.num_layers, ecfg.max_batch, ecfg.max_seq,
                             cfg.num_heads, cfg.head_dim, dtype=cache_dtype)
        self._exec: Dict[str, Any] = {}
        self._sig_history: Dict[str, List[dict]] = {}
        self.compiles = 0
        self.steady_state_recompiles = 0
        self._warm = False
        # set when an executable fails AFTER its cache buffers were donated
        # (the slabs are invalidated by donation, so no later call can be
        # trusted) — every serving entrypoint refuses from then on
        self.poisoned: Optional[str] = None
        self._tokens_window: List[Tuple[float, int]] = []  # (t, n) samples

    # ------------------------------------------------------------------
    # pure functions (traced once per executable)
    # ------------------------------------------------------------------
    def _dequant(self, qparams):
        return dequantize_params(qparams)

    def _prefill_fn(self, qparams, ck, cv, tokens, length, slot):
        """tokens [1, T] int32, length/slot scalars -> (ck, cv, logits[V]).

        Runs the full causal forward over the padded bucket, writes the
        per-layer K/V for positions [0, T) into the cache at ``slot``
        (padding rows land too, but the length mask keeps decode from ever
        reading them), and returns the logits of the LAST VALID position —
        the first generated token comes straight out of prefill."""
        cfg = self.cfg
        params = self._dequant(qparams)
        dt = cfg.dtype
        ln = gpt_mod._layer_norm
        x = gpt_mod.embed(params, tokens, cfg)          # [1, T, D]

        def body(h, layer_p):
            h1 = ln(h, layer_p["ln1_scale"], layer_p["ln1_bias"])
            qkv = jnp.einsum("btd,dcnh->btcnh", h1,
                             layer_p["w_qkv"].astype(dt))
            qkv = qkv + layer_p["b_qkv"].astype(dt)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            a = prefill_attention(q, k, v)
            o = jnp.einsum("btnh,nhd->btd", a, layer_p["w_proj"].astype(dt))
            h = h + o + layer_p["b_proj"].astype(dt)
            h2 = ln(h, layer_p["ln2_scale"], layer_p["ln2_bias"])
            f = jnp.einsum("btd,df->btf", h2, layer_p["w_fc"].astype(dt))
            f = jax.nn.gelu(f + layer_p["b_fc"].astype(dt), approximate=True)
            o2 = jnp.einsum("btf,fd->btd", f, layer_p["w_out"].astype(dt))
            h = h + o2 + layer_p["b_out"].astype(dt)
            return h, (k, v)

        x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
        # ks: [L, 1, T, nh, hd] -> cache slab write at (slot, 0..T)
        ck = jax.lax.dynamic_update_slice(
            ck, ks.astype(ck.dtype), (0, slot, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(
            cv, vs.astype(cv.dtype), (0, slot, 0, 0, 0))
        h_last = jax.lax.dynamic_index_in_dim(x[0], length - 1, axis=0,
                                              keepdims=False)      # [D]
        h_last = ln(h_last, params["ln_f_scale"], params["ln_f_bias"])
        logits = jnp.einsum("d,dv->v", h_last,
                            params["lm_head"].astype(dt))
        return ck, cv, logits.astype(jnp.float32)

    def _decode_fn(self, qparams, ck, cv, tokens, positions):
        """tokens/positions [max_batch] int32 -> (ck, cv, logits[B, V]).

        One token per slot: write this step's K/V at ``positions``, attend
        over each slot's valid prefix (positions+1), emit next-token
        logits. Inactive lanes ride along with position 0 — their writes
        land in a dead slot's position 0, which the next prefill into that
        slot overwrites before it can ever be read."""
        cfg = self.cfg
        params = self._dequant(qparams)
        dt = cfg.dtype
        ln = gpt_mod._layer_norm
        x = (params["wte"][tokens] + params["wpe"][positions]).astype(dt)

        def body(h, xs):
            layer_p, ck_l, cv_l = xs
            h1 = ln(h, layer_p["ln1_scale"], layer_p["ln1_bias"])
            qkv = jnp.einsum("bd,dcnh->bcnh", h1,
                             layer_p["w_qkv"].astype(dt))
            qkv = qkv + layer_p["b_qkv"].astype(dt)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]      # [B, nh, hd]
            ck_l = cache_update(ck_l, k, positions)
            cv_l = cache_update(cv_l, v, positions)
            a = decode_attention(q, ck_l, cv_l, positions + 1)
            o = jnp.einsum("bnh,nhd->bd", a, layer_p["w_proj"].astype(dt))
            h = h + o + layer_p["b_proj"].astype(dt)
            h2 = ln(h, layer_p["ln2_scale"], layer_p["ln2_bias"])
            f = jnp.einsum("bd,df->bf", h2, layer_p["w_fc"].astype(dt))
            f = jax.nn.gelu(f + layer_p["b_fc"].astype(dt), approximate=True)
            o2 = jnp.einsum("bf,fd->bd", f, layer_p["w_out"].astype(dt))
            h = h + o2 + layer_p["b_out"].astype(dt)
            return h, (ck_l, cv_l)

        x, (ck, cv) = jax.lax.scan(body, x,
                                   (params["blocks"], ck, cv))
        x = ln(x, params["ln_f_scale"], params["ln_f_bias"])
        logits = jnp.einsum("bd,dv->bv", x, params["lm_head"].astype(dt))
        return ck, cv, logits.astype(jnp.float32)

    # ------------------------------------------------------------------
    # AOT compilation (PR 4 discipline: explicit lower+compile, program
    # report, recompile-explainer integration)
    # ------------------------------------------------------------------
    def _make_sig(self, example_args) -> dict:
        leaves = jax.tree_util.tree_leaves(example_args)
        feed_sig = [(f"arg{i}", tuple(np.shape(a)),
                     str(jnp.result_type(a))) for i, a in enumerate(leaves)]
        return _prep.make_sig(feed_sig, fetch_names=())

    def _compile(self, name: str, fn, example_args,
                 donate_argnums: Tuple[int, ...]) -> Any:
        from ..parallel import health as _health

        sig = self._make_sig(example_args)
        hist = self._sig_history.setdefault(name, [])
        if hist:
            # a same-name rebuild is exactly what steady state must never
            # do: explain it through the PR 4 taxonomy and count it
            cause, detail = _prep.explain_recompile(sig, hist)
            _prep.note_recompile(f"serve/{name}", cause, detail)
            if self._warm:
                self.steady_state_recompiles += 1
        hist.append(sig)
        del hist[:-8]
        jitted = jax.jit(
            fn, donate_argnums=donate_argnums if self._donate else ())
        t0 = time.perf_counter_ns()
        with _health.suspend():
            lowered = jitted.lower(*example_args)
            compiled = lowered.compile()
        compile_ms = (time.perf_counter_ns() - t0) / 1e6
        self.compiles += 1
        donated = [f"arg{i}" for i in donate_argnums] if self._donate else []
        _prep.capture(
            f"serve/{name}", compiled=compiled, compile_ms=compile_ms,
            donated=donated, inputs=example_args,
            extra={"engine": {
                "max_batch": self.ecfg.max_batch,
                "max_seq": self.ecfg.max_seq,
                "weight_dtype": self.ecfg.weight_dtype,
                "cache_dtype": str(jnp.dtype(self.cache.dtype).name),
                "buckets": list(self.buckets),
            }})
        return compiled

    def _prefill_exec(self, bucket: int):
        name = f"prefill_b{bucket}"
        exe = self._exec.get(name)
        if exe is None:
            example = (self.qparams, self.cache.k, self.cache.v,
                       np.zeros((1, bucket), np.int32), np.int32(1),
                       np.int32(0))
            exe = self._compile(name, self._prefill_fn, example,
                                donate_argnums=(1, 2))
            self._exec[name] = exe
        return exe

    def _decode_exec(self):
        exe = self._exec.get("decode")
        if exe is None:
            B = self.ecfg.max_batch
            example = (self.qparams, self.cache.k, self.cache.v,
                       np.zeros((B,), np.int32), np.zeros((B,), np.int32))
            exe = self._compile("decode", self._decode_fn, example,
                                donate_argnums=(1, 2))
            self._exec["decode"] = exe
        return exe

    def warmup(self) -> Dict[str, float]:
        """Compile every executable the steady state will ever need (the
        decode program + one prefill per bucket) and run each once so the
        first real request pays no compile and no first-dispatch cost.
        Returns {executable_name: compile_ms is implicit in the program
        reports; here: wall ms per warm call}."""
        timings: Dict[str, float] = {}
        t0 = time.perf_counter()
        dec = self._decode_exec()
        B = self.ecfg.max_batch
        ck, cv, logits = dec(self.qparams, self.cache.k, self.cache.v,
                             np.zeros((B,), np.int32),
                             np.zeros((B,), np.int32))
        jax.block_until_ready(logits)
        self.cache.k, self.cache.v = ck, cv
        timings["decode"] = (time.perf_counter() - t0) * 1e3
        for bucket in self.buckets:
            t0 = time.perf_counter()
            exe = self._prefill_exec(bucket)
            ck, cv, logits = exe(self.qparams, self.cache.k, self.cache.v,
                                 np.zeros((1, bucket), np.int32),
                                 np.int32(1), np.int32(0))
            jax.block_until_ready(logits)
            self.cache.k, self.cache.v = ck, cv
            timings[f"prefill_b{bucket}"] = (time.perf_counter() - t0) * 1e3
        self._warm = True
        return timings

    # ------------------------------------------------------------------
    # host-side serving API (one scheduler thread)
    # ------------------------------------------------------------------
    def _check_poisoned(self) -> None:
        if self.poisoned is not None:
            raise RuntimeError(f"engine poisoned: {self.poisoned}")

    def _poison_on_donation_failure(self, name: str, exc: Exception) -> None:
        """An executable compiled with donate_argnums died mid-call: the
        cache slabs it was handed are donation-invalidated, so cache.k/v
        can no longer be trusted. Mark the engine fatally poisoned rather
        than let later calls read freed buffers. (Without donation — CPU —
        the slabs are untouched and the engine stays usable.)"""
        if self._donate and self.poisoned is None:
            self.poisoned = (
                f"{name} failed after cache-buffer donation "
                f"({type(exc).__name__}: {exc}); KV slabs invalidated — "
                f"rebuild the engine")

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise PromptTooLongError(
            f"prompt length {n} exceeds the largest prefill bucket "
            f"{self.buckets[-1]}")

    def start_sequence(self, tokens: Sequence[int]) -> Tuple[int, np.ndarray]:
        """Claim a slot, prefill the prompt, return (slot, logits[V]) of
        the last prompt position — argmax of it is the first generated
        token. Raises CacheFullError when no slot is free and
        PromptTooLongError above the ladder."""
        self._check_poisoned()
        n = len(tokens)
        if n < 1:
            raise ValueError("empty prompt")
        bucket = self.bucket_for(n)
        exe = self._prefill_exec(bucket)
        slot = self.cache.alloc(length=n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = np.asarray(tokens, np.int32)
        t0 = time.perf_counter_ns()
        try:
            ck, cv, logits = exe(self.qparams, self.cache.k, self.cache.v,
                                 padded, np.int32(n), np.int32(slot))
            logits = np.asarray(logits)
        except Exception as e:
            self._poison_on_donation_failure(f"prefill_b{bucket}", e)
            self.cache.free(slot)
            raise
        t1 = time.perf_counter_ns()
        smetrics.m_prefill_ms.observe((t1 - t0) / 1e6)
        # inherits the scheduler's per-request span context (the admit
        # path wraps this call in the request's trace)
        _spans.record("serve/prefill", t0, t1 - t0,
                      attrs={"bucket": bucket, "prompt_len": n,
                             "slot": slot})
        self.cache.k, self.cache.v = ck, cv
        return slot, logits

    def decode_step(self, slot_tokens: Dict[int, int]) -> Dict[int, np.ndarray]:
        """One decode step for the given {slot: input_token} map (the
        token each sequence generated last). Returns {slot: logits[V]}.
        Slots not in the map ride as masked lanes — same shapes, same
        executable, zero recompiles."""
        if not slot_tokens:
            return {}
        self._check_poisoned()
        B = self.ecfg.max_batch
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        for slot, tok in slot_tokens.items():
            if not self.cache.is_live(slot):
                raise ValueError(f"slot {slot} is not live")
            if self.cache.headroom(slot) < 1:
                raise ValueError(
                    f"slot {slot} is at max_seq {self.ecfg.max_seq}")
            tokens[slot] = tok
            positions[slot] = self.cache.length(slot)
        exe = self._decode_exec()
        t0 = time.perf_counter_ns()
        try:
            ck, cv, logits = exe(self.qparams, self.cache.k, self.cache.v,
                                 tokens, positions)
            logits = np.asarray(logits)
        except Exception as e:
            self._poison_on_donation_failure("decode", e)
            raise
        smetrics.m_decode_ms.observe((time.perf_counter_ns() - t0) / 1e6)
        self.cache.k, self.cache.v = ck, cv
        out: Dict[int, np.ndarray] = {}
        for slot in slot_tokens:
            self.cache.set_length(slot, self.cache.length(slot) + 1)
            out[slot] = logits[slot]
        self.note_tokens(len(slot_tokens))
        return out

    def free_sequence(self, slot: int) -> None:
        self.cache.free(slot)

    # ------------------------------------------------------------------
    def note_tokens(self, n: int, window_s: float = 5.0) -> None:
        now = time.monotonic()
        smetrics.m_tokens.inc(n)
        w = self._tokens_window
        w.append((now, n))
        while w and w[0][0] < now - window_s:
            w.pop(0)
        span = now - w[0][0] if len(w) > 1 else 0.0
        if span > 0:
            smetrics.m_tokens_per_s.set(sum(x[1] for x in w) / span)

    # ------------------------------------------------------------------
    # reference / parity surface (tests + serve_bench quality bar)
    # ------------------------------------------------------------------
    def reference_logits(self, tokens: Sequence[int]) -> np.ndarray:
        """Full-forward f32-weight logits [T, V] for a prompt — the truth
        the cached decode path and the quantized weights are held to."""
        if self._ref_params is None:
            raise RuntimeError("reference params were dropped")
        toks = np.asarray(tokens, np.int32)[None]
        return np.asarray(
            gpt_mod.forward(self._ref_params, toks, self.cfg)[0],
            np.float32)

    def drop_reference_params(self) -> None:
        self._ref_params = None

    @property
    def executables(self) -> List[str]:
        return sorted(self._exec)
