"""Persistent prefix-cache store: published pages survive engine restarts
(ISSUE 15, ROADMAP 2(c), docs/serving.md "Resilience").

The paged engine's :class:`~paddle_tpu.serving.paged_kv.PrefixCache`
makes a shared system prompt prefill ONCE — per engine *incarnation*.
A crash (or a gang recycle) used to throw the warmed pages away, so a
restarted replica re-paid every shared-prefix prefill. This module
closes that gap: at publish time the engine hands the store the
page-aligned prefix (token stream + the K/V page contents read off the
pool) and the store persists it through an :class:`ElasticCheckpointer`
— the same crash-safe format training checkpoints use (per-leaf CRC
manifests, atomic COMMIT marker, async writes, ``keep_last`` GC), so a
mid-save kill can never leave a half-written record that a restore
would trust. On boot :meth:`restore_into` replays committed records:
claims pages from the pool, writes their contents back, and re-registers
every nested page-boundary prefix in the prefix cache — the first
request after a recycle hits the cache exactly like the ten-thousandth
before it.

Contents are tied to the engine geometry (model hash is the caller's
concern). Records carry the writing pool's config fingerprint
(serving/kv_transfer.py — layout, layers, heads, head_dim, dtype,
page_size); restoring into a differently-configured engine raises
:class:`~paddle_tpu.serving.kv_transfer.CacheConfigMismatch` naming
every differing field instead of silently skipping (ISSUE 17 fix: the
old shape-tail check skipped quietly, hiding a misconfigured replica).
Legacy fingerprint-less records keep the skip-on-shape-drift behavior.

Metered by ``paddle_serve_prefix_store_total{op=save|restore|
restore_skipped}`` (gated by tools/metrics_check.py).
"""
from __future__ import annotations

import hashlib

import numpy as np

from ..parallel.checkpoint import CheckpointError, ElasticCheckpointer
from . import metrics as smetrics
from .kv_transfer import (CacheConfigMismatch, cache_fingerprint,
                          fingerprint_mismatch)

__all__ = ["PrefixStore"]


class PrefixStore:
    """One store directory per replica slot. Records are numbered
    ``step_<N>`` in publish order; ``max_records`` bounds the store with
    the checkpointer's ``keep_last`` GC (oldest published drop first —
    matching the prefix cache's own LRU bias toward fresh prefixes)."""

    def __init__(self, dirname: str, max_records: int = 64,
                 use_async: bool = True):
        self.dirname = str(dirname)
        self.max_records = int(max_records)
        self._ck = ElasticCheckpointer(self.dirname, use_async=use_async,
                                       keep_last=self.max_records)
        self.saved = 0
        self.restored = 0
        self.restore_skipped = 0
        # token-hash index of records already on disk (loaded lazily,
        # extended on publish) — a re-published prefix is not re-saved
        self._keys = None
        self._next_step = None

    @staticmethod
    def _key(tokens) -> str:
        return hashlib.sha1(
            np.asarray(tokens, np.int64).tobytes()).hexdigest()

    def _load_index(self) -> None:
        if self._keys is not None:
            return
        self._keys = set()
        steps = self._ck.all_steps()
        self._next_step = (steps[-1] + 1) if steps else 0
        for step in steps:
            try:
                man = self._ck.manifest(step)
            except CheckpointError:
                continue
            key = (man.get("extra") or {}).get("token_hash")
            if key:
                self._keys.add(key)

    # ------------------------------------------------------------------
    def maybe_publish(self, tokens, table_row: np.ndarray, pool) -> bool:
        """Persist the longest page-aligned prefix of ``tokens`` (its
        nested sub-prefixes restore for free — the page layout is
        nested by construction). No-op when nothing is page-aligned or
        the prefix is already stored. Returns True when a record was
        written (async; the checkpointer commits it atomically)."""
        self._load_index()
        ps = pool.page_size
        full = len(tokens) // ps
        if full < 1:
            return False
        prefix = [int(t) for t in tokens[:full * ps]]
        pages = [int(p) for p in table_row[:full]]
        if any(p == 0 for p in pages):
            return False                      # unmapped — nothing stored
        key = self._key(prefix)
        if key in self._keys:
            return False
        k_pages, v_pages = pool.read_pages(pages)
        step = self._next_step
        self._ck.save(step, {
            "tokens": np.asarray(prefix, np.int64),
            "k": np.asarray(k_pages),
            "v": np.asarray(v_pages),
        }, extra={"token_hash": key, "n_pages": len(pages),
                  "page_size": ps,
                  "fingerprint": cache_fingerprint(pool)})
        self._keys.add(key)
        self._next_step = step + 1
        self.saved += 1
        smetrics.m_prefix_store.labels("save").inc()
        return True

    def restore_into(self, engine) -> int:
        """Replay every committed record into ``engine``'s pool + prefix
        cache (boot time, before :meth:`DecodeEngine.warmup`). Records
        that no longer fit — pool pressure, token hash already live —
        are skipped, never half-applied. Returns how many records were
        restored.

        A record carrying a config fingerprint that does not match the
        receiving pool raises :class:`CacheConfigMismatch` naming every
        differing field — restoring KV bytes shaped for another config
        is an operator error, not something to paper over. Legacy
        records without a fingerprint fall back to the old silent
        shape-tail skip."""
        if engine.prefix is None:
            raise ValueError("prefix store needs a paged engine with "
                             "prefix_cache enabled")
        pool, cache = engine.cache, engine.prefix
        fp_local = cache_fingerprint(pool)
        expect = (pool.num_layers, pool.page_size, pool.num_heads,
                  pool.head_dim)
        n = 0
        for step in self._ck.all_steps():
            try:
                rec, _man = self._ck.restore(step)
            except CheckpointError:
                self.restore_skipped += 1
                smetrics.m_prefix_store.labels("restore_skipped").inc()
                continue
            fp_rec = (_man.get("extra") or {}).get("fingerprint")
            if fp_rec is not None:
                diffs = fingerprint_mismatch(fp_local, fp_rec)
                if diffs:
                    raise CacheConfigMismatch(
                        f"prefix store {self.dirname!r} step_{step} was "
                        f"written for a different cache config — "
                        + "; ".join(diffs)
                        + " (point the replica at a store written by a "
                          "matching engine, or clear the store)")
            tokens = [int(t) for t in np.asarray(rec["tokens"])]
            k_pages = np.asarray(rec["k"])
            v_pages = np.asarray(rec["v"])
            shape_tail = (k_pages.shape[0],) + k_pages.shape[2:]
            n_pages = k_pages.shape[1]
            if (shape_tail != expect or k_pages.shape != v_pages.shape
                    or n_pages * pool.page_size != len(tokens)
                    or cache._key(tokens) in cache._entries
                    or pool.free_page_count() <= n_pages):
                # geometry drift / duplicate / pool too tight (leave at
                # least one free page for live traffic) — skip cleanly
                self.restore_skipped += 1
                smetrics.m_prefix_store.labels("restore_skipped").inc()
                continue
            pages = pool.claim_pages(n_pages)
            pool.write_pages(pages, k_pages, v_pages)
            cache.adopt_nested(tokens, pages)
            n += 1
            self.restored += 1
            smetrics.m_prefix_store.labels("restore").inc()
        return n

    def record_count(self) -> int:
        return len(self._ck.all_steps())

    def wait(self) -> None:
        """Join in-flight async publishes (tests / clean shutdown)."""
        self._ck.wait()

    def close(self) -> None:
        self._ck.close()
