"""KV handoff between serving replicas (ISSUE 17, docs/serving.md
"Disaggregation").

Disaggregated serving migrates a request from its PREFILL replica to a
DECODE replica at the first-token boundary. What actually moves is the
request's KV cache state: the live pages of its page table (paged
engines) or its valid slab rows (slab engines). This module is that
path — the prefix store's record discipline (content CRC per payload,
explicit COMMIT marker, config fingerprint) over an in-memory channel
instead of disk: either a handoff dict passed within one process, or a
length-prefixed frame stream over a TCP socket between replicas
(:class:`KVTransferServer` / :func:`send_handoff`).

Layout redistribution rides the same path. A tp=2 prefill replica holds
the KV head axis sharded across its mesh; a tp=1 decode replica wants
the canonical unsharded layout. Following the chunk-wise discipline of
memory-efficient array redistribution (PAPERS.md arXiv:2112.01075), the
transfer never materializes both layouts for the full cache: pages move
in fixed-size chunks, each chunk is split into per-shard frames on the
source and merged along the head axis on the target, and a
:class:`TransferStats` residency meter ASSERTS in-path that the peak
transient canonical-layout footprint stays within the chunk budget —
orders of magnitude below the pool itself.

Fingerprinting is shared with ``serving/prefix_store.py``: a handoff
(or a persisted prefix record) carries the source cache's geometry and
the receiver refuses adoption with a field-by-field
:class:`CacheConfigMismatch` instead of silently writing mis-shaped
rows.
"""
from __future__ import annotations

import itertools
import json
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import spans as _spans
from . import metrics as smetrics

__all__ = [
    "CacheConfigMismatch", "TransferStats", "cache_fingerprint",
    "fingerprint_mismatch", "export_slot", "adopt_into_engine",
    "adopt_prefix", "export_prefix", "iter_frames", "KVTransferServer",
    "send_handoff", "last_stats", "handoff_to_jsonable",
    "handoff_from_jsonable",
    "DEFAULT_CHUNK_PAGES", "DEFAULT_CHUNK_ROWS",
]

# chunk sizes for the staged transfer: small enough that the transient
# canonical-layout footprint is pages, not pools; large enough that the
# per-chunk host round trip amortizes
DEFAULT_CHUNK_PAGES = 4
DEFAULT_CHUNK_ROWS = 64

_transfer_ids = itertools.count(1)


class CacheConfigMismatch(RuntimeError):
    """KV bytes shaped for one cache geometry were offered to another.
    The message names every differing field — the fix is config, not
    retry."""


def cache_fingerprint(cache) -> Dict[str, Any]:
    """The geometry that determines the shape of transferred KV bytes.
    Two caches with equal fingerprints can exchange pages/rows byte-for
    byte; anything else must be refused up front."""
    fp = {
        "layout": "paged" if hasattr(cache, "page_size") else "slab",
        "num_layers": int(cache.num_layers),
        "num_heads": int(cache.num_heads),
        "head_dim": int(cache.head_dim),
        "dtype": str(np.dtype(cache.dtype).name),
    }
    if fp["layout"] == "paged":
        fp["page_size"] = int(cache.page_size)
    return fp


def fingerprint_mismatch(expected: Dict[str, Any],
                         got: Dict[str, Any]) -> List[str]:
    """Human-readable list of differing fingerprint fields (empty =
    compatible)."""
    keys = sorted(set(expected) | set(got))
    return [f"{k}: expected {expected.get(k)!r}, got {got.get(k)!r}"
            for k in keys if expected.get(k) != got.get(k)]


class TransferStats:
    """Residency meter for the canonical (unsharded) layout during a
    transfer. ``note_alloc`` is called when a merged chunk is
    materialized, ``note_free`` when it is written/serialized and
    dropped — the in-path assertion is the arXiv:2112.01075 discipline
    made executable: at no point may the transient canonical footprint
    exceed the per-chunk budget (let alone approach the full cache)."""

    def __init__(self, budget_bytes: int, full_cache_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self.full_cache_bytes = int(full_cache_bytes)
        self.inflight_bytes = 0
        self.peak_bytes = 0
        self.total_bytes = 0       # wire payload bytes moved
        self.chunks = 0
        self.elapsed_ms = 0.0

    def note_alloc(self, nbytes: int) -> None:
        self.inflight_bytes += int(nbytes)
        self.peak_bytes = max(self.peak_bytes, self.inflight_bytes)
        self.chunks += 1
        if self.inflight_bytes > self.budget_bytes:
            raise AssertionError(
                f"KV transfer residency {self.inflight_bytes}B exceeds "
                f"the chunk budget {self.budget_bytes}B — the transfer "
                f"must stay chunk-wise (full cache: "
                f"{self.full_cache_bytes}B)")

    def note_free(self, nbytes: int) -> None:
        self.inflight_bytes -= int(nbytes)


_stats_lock = threading.Lock()
_last_stats: Dict[str, TransferStats] = {}


def last_stats(kind: str = "adopt") -> Optional[TransferStats]:
    """The most recent transfer's residency stats (``kind`` is
    "export" or "adopt") — how tests assert the peak-residency
    contract held."""
    with _stats_lock:
        return _last_stats.get(kind)


def _note_stats(kind: str, stats: TransferStats) -> None:
    with _stats_lock:
        _last_stats[kind] = stats


def _shard_count(engine) -> int:
    ecfg = getattr(engine, "ecfg", None)
    if ecfg is not None and getattr(ecfg, "sharding", None) == "tp":
        return int(ecfg.tp)
    return 1


def _split_frames(arr: np.ndarray, proj: str, axis: int,
                  nshards: int) -> List[Dict[str, Any]]:
    """Serialize one merged chunk into per-shard wire frames. On a tp
    source each frame is one mesh shard's slice of the head axis — the
    canonical chunk lives only between read and this split."""
    parts = (np.split(arr, nshards, axis=axis) if nshards > 1 else [arr])
    frames = []
    for si, part in enumerate(parts):
        data = np.ascontiguousarray(part).tobytes()
        frames.append({"proj": proj, "shard": si, "nshards": nshards,
                       "shape": list(part.shape),
                       "dtype": str(part.dtype),
                       "crc": zlib.crc32(data), "data": data})
    return frames


def _assemble_chunk(chunk: Dict[str, Any], axis: int,
                    stats: TransferStats
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Verify CRCs and merge a chunk's shard frames back into the
    canonical layout (head-axis concat). Returns (k, v)."""
    out: Dict[str, np.ndarray] = {}
    for proj in ("k", "v"):
        frames = sorted((f for f in chunk["shards"]
                         if f["proj"] == proj),
                        key=lambda f: f["shard"])
        if not frames:
            raise ValueError(f"handoff chunk missing {proj!r} frames")
        parts = []
        for f in frames:
            data = f["data"]
            if zlib.crc32(data) != f["crc"]:
                raise ValueError(
                    f"KV transfer CRC mismatch on chunk "
                    f"{chunk['index']} {proj}/shard {f['shard']}")
            parts.append(np.frombuffer(data, np.dtype(f["dtype"]))
                         .reshape(f["shape"]))
        merged = (np.concatenate(parts, axis=axis)
                  if len(parts) > 1 else parts[0])
        stats.note_alloc(merged.nbytes)
        out[proj] = merged
    return out["k"], out["v"]


def _wire_bytes(handoff: Dict[str, Any]) -> int:
    return sum(len(f["data"]) for ch in handoff["chunks"]
               for f in ch["shards"])


# ----------------------------------------------------------------------
# export (prefill side)
# ----------------------------------------------------------------------
def export_slot(engine, slot: int,
                tokens: Optional[Sequence[int]] = None,
                chunk_pages: int = DEFAULT_CHUNK_PAGES,
                chunk_rows: int = DEFAULT_CHUNK_ROWS) -> Dict[str, Any]:
    """Serialize a live slot's KV state into a handoff dict: config
    fingerprint + chunked, per-shard, CRC-stamped frames + COMMIT flag.
    The slot stays live — the caller frees it after the handoff is
    accepted (or keeps it on failure)."""
    cache = engine.cache
    fp = cache_fingerprint(cache)
    length = int(cache.length(slot))
    if length <= 0:
        raise ValueError(f"slot {slot} has no valid KV rows to export")
    t0 = time.perf_counter_ns()
    itemsize = np.dtype(cache.dtype).itemsize
    nshards = _shard_count(engine)
    chunks: List[Dict[str, Any]] = []
    if fp["layout"] == "paged":
        n_pages = cache.pages_for(length)
        row = cache.table_row(slot)
        pages = [int(p) for p in row[:n_pages]]
        unit = (cache.num_layers * cache.page_size * cache.num_heads
                * cache.head_dim * itemsize)
        stats = TransferStats(2 * chunk_pages * unit, cache.nbytes)
        for ci, i in enumerate(range(0, len(pages), chunk_pages)):
            group = pages[i:i + chunk_pages]
            k_np, v_np = cache.read_pages(group)
            nbytes = k_np.nbytes + v_np.nbytes
            stats.note_alloc(nbytes)
            shards = (_split_frames(k_np, "k", 3, nshards)
                      + _split_frames(v_np, "v", 3, nshards))
            del k_np, v_np
            stats.note_free(nbytes)
            chunks.append({"index": ci, "n": len(group),
                           "shards": shards})
    else:
        unit = (cache.num_layers * cache.num_heads * cache.head_dim
                * itemsize)
        stats = TransferStats(2 * chunk_rows * unit, cache.nbytes)
        for ci, start in enumerate(range(0, length, chunk_rows)):
            n = min(chunk_rows, length - start)
            k_np, v_np = cache.read_rows(slot, start, n)
            nbytes = k_np.nbytes + v_np.nbytes
            stats.note_alloc(nbytes)
            shards = (_split_frames(k_np, "k", 2, nshards)
                      + _split_frames(v_np, "v", 2, nshards))
            del k_np, v_np
            stats.note_free(nbytes)
            chunks.append({"index": ci, "start": start, "n": n,
                           "shards": shards})
    handoff = {
        "version": 1,
        "transfer_id": f"t{next(_transfer_ids)}-{id(engine) & 0xffff:x}",
        "fingerprint": fp,
        "length": length,
        "tokens": ([int(t) for t in tokens]
                   if tokens is not None else None),
        "chunks": chunks,
        "committed": True,
    }
    stats.total_bytes = _wire_bytes(handoff)
    stats.elapsed_ms = (time.perf_counter_ns() - t0) / 1e6
    _note_stats("export", stats)
    smetrics.m_kv_transfer_bytes.labels("out").inc(stats.total_bytes)
    smetrics.m_kv_transfer_ms.observe(stats.elapsed_ms)
    return handoff


# ----------------------------------------------------------------------
# adopt (decode side)
# ----------------------------------------------------------------------
def adopt_into_engine(engine, handoff: Dict[str, Any]) -> int:
    """Materialize a handoff into the receiving engine's cache and
    return the slot it now lives in. Fingerprints are checked FIRST
    (:class:`CacheConfigMismatch` on any differing field); chunks are
    merged shard-by-shard and written page-/row-wise so the canonical
    layout only ever exists chunk-sized."""
    cache = engine.cache
    fp_local = cache_fingerprint(cache)
    diffs = fingerprint_mismatch(fp_local, handoff["fingerprint"])
    if diffs:
        raise CacheConfigMismatch(
            "KV handoff rejected — cache config mismatch: "
            + "; ".join(diffs))
    if not handoff.get("committed"):
        raise ValueError("handoff was never committed — refusing "
                         "partial KV state")
    if cache.free_slot_count() == 0:
        # fail BEFORE claiming pages and scattering chunks: under
        # backlog the scheduler retries adoption every tick, and doing
        # the full transfer work just to hit CacheFullError in
        # adopt_slot taxes every decode gap (~2ms a tick)
        from .kv_cache import CacheFullError
        raise CacheFullError(
            f"no free decode slot for handoff "
            f"{handoff.get('transfer_id')!r}")
    t0 = time.perf_counter_ns()
    length = int(handoff["length"])
    max_chunk = max((int(ch["n"]) for ch in handoff["chunks"]),
                    default=1)
    itemsize = np.dtype(cache.dtype).itemsize
    if fp_local["layout"] == "paged":
        unit = (cache.num_layers * cache.page_size * cache.num_heads
                * cache.head_dim * itemsize)
        stats = TransferStats(2 * max_chunk * unit, cache.nbytes)
        pages = cache.claim_pages(cache.pages_for(length))
        try:
            written = 0
            for ch in sorted(handoff["chunks"],
                             key=lambda c: c["index"]):
                k_np, v_np = _assemble_chunk(ch, 3, stats)
                cache.write_pages(pages[written:written + int(ch["n"])],
                                  k_np, v_np)
                stats.note_free(k_np.nbytes + v_np.nbytes)
                written += int(ch["n"])
                del k_np, v_np
            if written != len(pages):
                raise ValueError(
                    f"handoff covered {written} page(s), table needs "
                    f"{len(pages)}")
            slot = cache.adopt_slot(length, pages)
        except Exception:
            cache.deref_pages(pages)
            raise
    else:
        unit = (cache.num_layers * cache.num_heads * cache.head_dim
                * itemsize)
        stats = TransferStats(2 * max_chunk * unit, cache.nbytes)
        slot = cache.alloc(length)
        try:
            for ch in sorted(handoff["chunks"],
                             key=lambda c: c["index"]):
                k_np, v_np = _assemble_chunk(ch, 2, stats)
                cache.write_rows(slot, int(ch["start"]), k_np, v_np)
                stats.note_free(k_np.nbytes + v_np.nbytes)
                del k_np, v_np
        except Exception:
            cache.free(slot)
            raise
    stats.total_bytes = _wire_bytes(handoff)
    stats.elapsed_ms = (time.perf_counter_ns() - t0) / 1e6
    _note_stats("adopt", stats)
    smetrics.m_kv_transfer_bytes.labels("in").inc(stats.total_bytes)
    smetrics.m_kv_transfer_ms.observe(stats.elapsed_ms)
    # adoption runs under the request's span context (the scheduler's
    # handoff-ingest wrapper), so this lands inside the shared trace
    _spans.record("serve/kv_adopt", t0,
                  time.perf_counter_ns() - t0,
                  attrs={"transfer_id": handoff.get("transfer_id"),
                         "bytes": stats.total_bytes})
    return slot


def export_prefix(pool, tokens: Sequence[int], table_row,
                  chunk_pages: int = DEFAULT_CHUNK_PAGES
                  ) -> Optional[Dict[str, Any]]:
    """Serialize the longest page-aligned prefix of ``tokens`` (pages
    per ``table_row``) into a blob :func:`adopt_prefix` can replay on
    any same-fingerprint engine — the payload of the gang-shared prefix
    index (serving/disagg.py). Returns None when nothing page-aligned
    is mapped. Chunk-wise, same residency discipline as a slot
    export."""
    ps = pool.page_size
    full = len(tokens) // ps
    if full < 1:
        return None
    prefix = [int(t) for t in tokens[:full * ps]]
    pages = [int(p) for p in table_row[:full]]
    if any(p == 0 for p in pages):
        return None
    itemsize = np.dtype(pool.dtype).itemsize
    unit = (pool.num_layers * pool.page_size * pool.num_heads
            * pool.head_dim * itemsize)
    stats = TransferStats(2 * chunk_pages * unit, pool.nbytes)
    chunks: List[Dict[str, Any]] = []
    for ci, i in enumerate(range(0, len(pages), chunk_pages)):
        group = pages[i:i + chunk_pages]
        k_np, v_np = pool.read_pages(group)
        nbytes = k_np.nbytes + v_np.nbytes
        stats.note_alloc(nbytes)
        shards = (_split_frames(k_np, "k", 3, 1)
                  + _split_frames(v_np, "v", 3, 1))
        del k_np, v_np
        stats.note_free(nbytes)
        chunks.append({"index": ci, "n": len(group), "shards": shards})
    return {
        "version": 1,
        "transfer_id": f"p{next(_transfer_ids)}",
        "fingerprint": cache_fingerprint(pool),
        "length": len(prefix),
        "tokens": prefix,
        "chunks": chunks,
        "committed": True,
    }


def adopt_prefix(engine, blob: Dict[str, Any]) -> int:
    """Adopt a gang-shared prefix record (export_slot payload whose
    ``tokens`` cover exactly its page-aligned length) into the local
    pool + prefix cache, so the next prefill of those tokens hits
    locally. Returns prefix-cache entries registered (0 when the
    prefix is already cached). Paged engines with a prefix cache only."""
    cache = engine.cache
    if not getattr(engine, "paged", False) or engine.prefix is None:
        raise ValueError("prefix adoption needs kv_layout='paged' with "
                         "prefix_cache enabled")
    diffs = fingerprint_mismatch(cache_fingerprint(cache),
                                 blob["fingerprint"])
    if diffs:
        raise CacheConfigMismatch(
            "prefix record rejected — cache config mismatch: "
            + "; ".join(diffs))
    tokens = [int(t) for t in (blob.get("tokens") or [])]
    length = int(blob["length"])
    if not tokens or len(tokens) != length or length % cache.page_size:
        raise ValueError("prefix record must carry page-aligned tokens "
                         "matching its length")
    if engine.prefix.has(tokens):
        return 0
    max_chunk = max((int(ch["n"]) for ch in blob["chunks"]), default=1)
    itemsize = np.dtype(cache.dtype).itemsize
    unit = (cache.num_layers * cache.page_size * cache.num_heads
            * cache.head_dim * itemsize)
    stats = TransferStats(2 * max_chunk * unit, cache.nbytes)
    pages = cache.claim_pages(cache.pages_for(length))
    try:
        written = 0
        for ch in sorted(blob["chunks"], key=lambda c: c["index"]):
            k_np, v_np = _assemble_chunk(ch, 3, stats)
            cache.write_pages(pages[written:written + int(ch["n"])],
                              k_np, v_np)
            stats.note_free(k_np.nbytes + v_np.nbytes)
            written += int(ch["n"])
            del k_np, v_np
        if written != len(pages):
            raise ValueError(
                f"prefix record covered {written} page(s), need "
                f"{len(pages)}")
        # claim_pages' single reference becomes the cache's reference
        return engine.prefix.adopt_nested(tokens, pages)
    except Exception:
        cache.deref_pages(pages)
        raise


# ----------------------------------------------------------------------
# JSON-inline form (HTTP fallback channel, tests)
# ----------------------------------------------------------------------
def handoff_to_jsonable(handoff: Dict[str, Any]) -> Dict[str, Any]:
    """Base64 the shard payloads so a handoff can ride a JSON body —
    the fallback channel when the receiver runs no KVTransferServer.
    ~33% size overhead; the socket channel is the real path."""
    import base64

    out = {k: v for k, v in handoff.items() if k != "chunks"}
    out["chunks"] = [
        dict(ch, shards=[
            dict(f, data=base64.b64encode(f["data"]).decode())
            for f in ch["shards"]])
        for ch in handoff["chunks"]]
    return out


def handoff_from_jsonable(obj: Dict[str, Any]) -> Dict[str, Any]:
    import base64

    out = {k: v for k, v in obj.items() if k != "chunks"}
    out["chunks"] = [
        dict(ch, shards=[
            dict(f, data=base64.b64decode(f["data"]))
            for f in ch["shards"]])
        for ch in obj["chunks"]]
    return out


# ----------------------------------------------------------------------
# socket channel (between replica processes)
# ----------------------------------------------------------------------
# frame = [4B header length][header JSON][8B payload length][payload]
_HDR = struct.Struct(">I")
_PAY = struct.Struct(">Q")


def iter_frames(handoff: Dict[str, Any]
                ) -> Iterator[Tuple[Dict[str, Any], bytes]]:
    """The handoff as a frame stream: one meta frame, one frame per
    shard payload, one commit frame — the prefix store's record/COMMIT
    shape, on the wire."""
    meta = {k: v for k, v in handoff.items() if k != "chunks"}
    meta["kind"] = "meta"
    meta["committed"] = False       # commit is its own frame
    meta["n_chunks"] = len(handoff["chunks"])
    yield meta, b""
    for ch in handoff["chunks"]:
        base = {k: v for k, v in ch.items() if k != "shards"}
        for f in ch["shards"]:
            hdr = dict(base, kind="chunk",
                       transfer_id=handoff["transfer_id"],
                       **{k: v for k, v in f.items() if k != "data"})
            yield hdr, f["data"]
    yield {"kind": "commit", "transfer_id": handoff["transfer_id"]}, b""


def _send_frame(sock: socket.socket, header: Dict[str, Any],
                payload: bytes) -> None:
    hdr = json.dumps(header).encode()
    sock.sendall(_HDR.pack(len(hdr)) + hdr + _PAY.pack(len(payload)))
    if payload:
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(min(1 << 20, n - len(buf)))
        if not part:
            raise ConnectionError("KV transfer peer closed mid-frame")
        buf.extend(part)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Tuple[Dict[str, Any], bytes]:
    hdr_len = _HDR.unpack(_recv_exact(sock, _HDR.size))[0]
    header = json.loads(_recv_exact(sock, hdr_len).decode())
    pay_len = _PAY.unpack(_recv_exact(sock, _PAY.size))[0]
    payload = _recv_exact(sock, pay_len) if pay_len else b""
    return header, payload


def send_handoff(host: str, port: int, handoff: Dict[str, Any],
                 timeout_s: float = 30.0) -> None:
    """Stream a handoff to a :class:`KVTransferServer` and wait for its
    post-commit ACK. Raises on any transport fault — the caller's cue
    to fall back to colocated dispatch (degrade, never drop)."""
    # the handoff's own trace context (stamped at export) parents the
    # send span — the wire hop shows up inside the request's timeline
    with _spans.default_tracer().context(_spans.extract(handoff)):
        with _spans.span("serve/kv_send",
                         attrs={"transfer_id": handoff["transfer_id"],
                                "length": int(handoff["length"])}):
            with socket.create_connection((host, int(port)),
                                          timeout=timeout_s) as sock:
                for header, payload in iter_frames(handoff):
                    _send_frame(sock, header, payload)
                ack = _recv_exact(sock, 2)
                if ack != b"OK":
                    raise ConnectionError(
                        f"KV transfer not acknowledged (got {ack!r})")


class KVTransferServer:
    """Per-replica TCP endpoint that buffers incoming handoffs until
    the serving loop adopts them. Frames for a transfer are staged
    under its transfer_id and become visible to :meth:`pop` only after
    the commit frame — a connection dying mid-stream leaves nothing
    behind (the record-or-nothing discipline)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.5)
        self.host = host
        self.port = int(self._sock.getsockname()[1])
        self._ready: Dict[str, Dict[str, Any]] = {}
        self._cv = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(
            target=self._serve, daemon=True, name="kv-transfer-server")

    def start(self) -> "KVTransferServer":
        self._thread.start()
        return self

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True,
                             name="kv-transfer-conn").start()

    def _handle(self, conn: socket.socket) -> None:
        staged: Dict[str, Any] = {}
        chunks: Dict[int, Dict[str, Any]] = {}
        try:
            with conn:
                conn.settimeout(30.0)
                while True:
                    header, payload = _recv_frame(conn)
                    kind = header.get("kind")
                    if kind == "meta":
                        staged = {k: v for k, v in header.items()
                                  if k not in ("kind", "n_chunks")}
                        chunks = {}
                    elif kind == "chunk":
                        ci = int(header["index"])
                        ch = chunks.setdefault(ci, {
                            "index": ci, "n": header["n"],
                            "shards": []})
                        if "start" in header:
                            ch["start"] = header["start"]
                        ch["shards"].append({
                            "proj": header["proj"],
                            "shard": header["shard"],
                            "nshards": header["nshards"],
                            "shape": header["shape"],
                            "dtype": header["dtype"],
                            "crc": header["crc"], "data": payload})
                    elif kind == "commit":
                        handoff = dict(
                            staged, committed=True,
                            chunks=[chunks[i]
                                    for i in sorted(chunks)])
                        n = _wire_bytes(handoff)
                        smetrics.m_kv_transfer_bytes.labels("in").inc(n)
                        with self._cv:
                            self._ready[handoff["transfer_id"]] = handoff
                            self._cv.notify_all()
                        conn.sendall(b"OK")
                        return
                    else:
                        raise ValueError(f"unknown frame kind {kind!r}")
        except (ConnectionError, OSError, ValueError, KeyError):
            # mid-stream death: nothing was published — the sender's
            # missing ACK triggers its colocated fallback
            return

    def pop(self, transfer_id: str,
            timeout_s: float = 30.0) -> Dict[str, Any]:
        """Block until the transfer committed, then hand it over
        (exactly once). TimeoutError when it never lands."""
        with self._cv:
            ok = self._cv.wait_for(
                lambda: transfer_id in self._ready or self._stop,
                timeout=timeout_s)
            if not ok or transfer_id not in self._ready:
                raise TimeoutError(
                    f"KV transfer {transfer_id!r} never committed")
            return self._ready.pop(transfer_id)

    def close(self) -> None:
        self._stop = True
        with self._cv:
            self._cv.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=5)
