"""Draft-model speculative decoding over the serving engine (ISSUE 13,
docs/serving.md "Speculative decoding").

Decode is memory-bandwidth-bound: every step reads the whole weight set
and cache to emit ONE token per slot. Speculative decoding is the lever
that beats that physics — a small draft model proposes ``k`` tokens
autoregressively (cheap reads), then the target model scores the whole
window in ONE batched verify call (`DecodeEngine.verify_step`, the
``[B, W]`` executable) and accepts the longest prefix consistent with
its own distribution. Accepted tokens cost one target pass for up to
``k+1`` emissions.

Correctness contract (the acceptance bar tests hold this to):

- **Greedy (temperature=0)**: emitted tokens are EXACTLY what the target
  alone would emit — a draft token is accepted iff it equals the
  target's argmax at that position, the first mismatch is replaced by
  the target's own choice, and a fully-accepted window earns the bonus
  token from the last verify position.
- **Sampled**: standard rejection sampling (Leviathan et al. /
  arXiv:2211.17192): draft token ``d`` proposed from the draft's
  adjusted distribution ``p_d`` is accepted with probability
  ``min(1, p_t(d)/p_d(d))``; a rejection resamples from the residual
  ``norm(max(p_t - p_d, 0))`` — the emitted marginal is exactly the
  target's adjusted distribution. Both adjusted distributions come from
  ``sampling.adjusted_probs_np``, the numpy twin of the in-executable
  masking. Acceptance randomness derives from the request seed (host
  RNG, independent of the proposal keys) — deterministic replays.

Cache discipline: the verify window writes all ``W`` rows; only the
accepted prefix is committed (`commit_window`), rejected rows are simply
overwritten later. The draft keeps its own (smaller) cache in lockstep —
rolled back to the accepted length after every window, with a one-token
catch-up feed when a fully-accepted window leaves the draft one row
behind. Every shape is static, so speculative serving inherits the
zero-recompile steady state unchanged.

Acceptance telemetry: ``paddle_serve_spec_accepted_tokens`` (histogram
of accepted draft tokens per window) +
``paddle_serve_spec_{proposed_tokens,windows}_total`` — mean accepted
per window IS the speedup meter.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import metrics as smetrics
from . import sampling as samp
from .engine import DecodeEngine
from .sampling import GREEDY, SamplingParams

__all__ = ["SpecDecodeEngine", "SpecStats"]


@dataclasses.dataclass
class SpecStats:
    windows: int = 0
    proposed: int = 0
    accepted: int = 0
    emitted: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    @property
    def tokens_per_window(self) -> float:
        return self.emitted / self.windows if self.windows else 0.0


class SpecDecodeEngine:
    """Target + draft engine pair presenting the scheduler's engine
    surface (``start_sequence_sampled`` / ``generate_step`` /
    ``free_sequence`` / admission + capacity hooks), emitting up to
    ``k+1`` tokens per step.

    The target must be built with ``EngineConfig(verify_window=k+1)``;
    the draft is any (smaller) engine with the same vocab, slot count,
    max_seq and bucket ladder, so slot ids stay aligned across the two
    allocators by construction."""

    def __init__(self, target: DecodeEngine, draft: DecodeEngine):
        W = target.ecfg.verify_window
        if W < 2:
            raise ValueError(
                "target engine needs EngineConfig(verify_window=k+1>=2)")
        if draft.cfg.vocab_size != target.cfg.vocab_size:
            raise ValueError("draft/target vocab mismatch")
        for attr in ("max_batch", "max_seq"):
            if getattr(draft.ecfg, attr) != getattr(target.ecfg, attr):
                raise ValueError(f"draft/target {attr} mismatch")
        if draft.buckets != target.buckets:
            raise ValueError("draft/target bucket ladders differ "
                             "(slot alignment needs identical admission)")
        self.target = target
        self.draft = draft
        self.draft.meter_tokens = False      # draft tokens aren't served
        self.k = W - 1
        self.window = W
        # the scheduler evicts below this headroom: a verify window
        # writes W rows, so speculative requests stop within k tokens of
        # max_seq (max_new_tokens usually stops them far earlier)
        self.min_headroom = W
        self.stats = SpecStats()
        # tokens the target has cached that the draft hasn't ingested
        # yet (at most one — the fully-accepted window's last draft
        # token); fed to the draft at the head of the next proposal round
        self._pending: Dict[int, List[int]] = {}

    # -- facade ------------------------------------------------------------
    @property
    def cfg(self):
        return self.target.cfg

    @property
    def ecfg(self):
        return self.target.ecfg

    @property
    def cache(self):
        return self.target.cache

    @property
    def prefix(self):
        return self.target.prefix

    @property
    def buckets(self):
        return self.target.buckets

    @property
    def paged(self):
        return self.target.paged

    @property
    def poisoned(self):
        return self.target.poisoned or self.draft.poisoned

    @property
    def compiles(self):
        return self.target.compiles + self.draft.compiles

    @property
    def steady_state_recompiles(self):
        return (self.target.steady_state_recompiles
                + self.draft.steady_state_recompiles)

    def warmup(self) -> Dict[str, float]:
        out = {f"target/{k}": v for k, v in self.target.warmup().items()}
        out.update({f"draft/{k}": v
                    for k, v in self.draft.warmup().items()})
        return out

    def bucket_for(self, n: int) -> int:
        return self.target.bucket_for(n)

    def can_admit(self, prompt_len: int) -> bool:
        return (self.target.can_admit(prompt_len)
                and self.draft.can_admit(prompt_len))

    def note_tokens(self, n: int) -> None:
        self.target.note_tokens(n)

    def reference_logits(self, tokens):
        return self.target.reference_logits(tokens)

    # -- lifecycle ---------------------------------------------------------
    def start_sequence(self, tokens: Sequence[int]):
        slot, logits, _tok = self.start_sequence_sampled(tokens, GREEDY)
        return slot, logits

    def start_sequence_sampled(self, tokens: Sequence[int],
                               params: SamplingParams):
        slot, logits, tok = self.target.start_sequence_sampled(
            tokens, params)
        try:
            d_slot, _d_logits, _d_tok = self.draft.start_sequence_sampled(
                tokens, GREEDY)
        except Exception:
            self.target.free_sequence(slot)
            raise
        if d_slot != slot:       # identical admission order -> identical
            self.draft.free_sequence(d_slot)
            self.target.free_sequence(slot)
            raise RuntimeError(
                f"draft slot {d_slot} != target slot {slot} — the two "
                "allocators fell out of lockstep")
        self._pending[slot] = []
        return slot, logits, tok

    def resume_sequence_sampled(self, tokens: Sequence[int],
                                params: SamplingParams):
        """Preemption resume (see DecodeEngine.resume_sequence_sampled):
        both engines replay the stream, keeping slots in lockstep."""
        slot, logits, tok = self.target.resume_sequence_sampled(
            tokens, params)
        try:
            d_slot, _dl, _dt = self.draft.resume_sequence_sampled(
                tokens, GREEDY)
        except Exception:
            self.target.free_sequence(slot)
            raise
        if d_slot != slot:
            self.draft.free_sequence(d_slot)
            self.target.free_sequence(slot)
            raise RuntimeError(
                f"draft slot {d_slot} != target slot {slot} on resume")
        self._pending[slot] = []
        return slot, logits, tok

    def free_sequence(self, slot: int) -> None:
        self.target.free_sequence(slot)
        self.draft.free_sequence(slot)
        self._pending.pop(slot, None)

    def ensure_decode_capacity(self, slot: int, extra: int = 0) -> bool:
        extra = extra or self.window
        return (self.target.ensure_decode_capacity(slot, extra=extra)
                and self.draft.ensure_decode_capacity(slot, extra=extra))

    # -- the speculative step ---------------------------------------------
    def _accept_greedy(self, proposals: List[int],
                       target_toks: np.ndarray) -> Tuple[int, List[int]]:
        """Longest matching prefix; emitted = accepted + target's fix-up
        (which is the bonus token when everything matched)."""
        m = 0
        while m < len(proposals) and proposals[m] == int(target_toks[m]):
            m += 1
        return m, proposals[:m] + [int(target_toks[m])]

    def _accept_sampled(self, slot: int, start: int,
                        proposals: List[int],
                        draft_logits: List[np.ndarray],
                        target_logits: np.ndarray,
                        target_toks: np.ndarray,
                        sp: SamplingParams) -> Tuple[int, List[int]]:
        """Leviathan rejection sampling against the adjusted
        distributions. ``target_logits`` is [W, V]; row i is conditioned
        on the window up to (and including) proposal i-1."""
        rng = np.random.RandomState(
            (int(np.uint32(sp.seed)) * 2654435761
             + int(start) * 40503 + int(slot)) % 0x7FFFFFFF)
        emitted: List[int] = []
        m = 0
        for i, d in enumerate(proposals):
            pt = samp.adjusted_probs_np(target_logits[i], sp)
            pd = samp.adjusted_probs_np(draft_logits[i], sp)
            if pd[d] <= 0:           # defensive: proposal off-support
                ratio = 0.0
            else:
                ratio = min(1.0, float(pt[d] / pd[d]))
            if rng.uniform() < ratio:
                emitted.append(int(d))
                m += 1
                continue
            residual = np.maximum(pt - pd, 0.0)
            tot = residual.sum()
            if tot <= 0:             # pt == pd exactly: keep pt's sample
                emitted.append(int(np.argmax(pt)))
            else:
                emitted.append(int(rng.choice(len(residual),
                                              p=residual / tot)))
            return m, emitted
        # fully accepted: the bonus token is the executable's own sample
        # at the last window position (conditioned on every proposal)
        emitted.append(int(target_toks[len(proposals)]))
        return m, emitted

    def generate_step(
            self, slot_tokens: Dict[int, int],
            params_by_slot: Optional[Dict[int, SamplingParams]] = None
    ) -> Dict[int, List[int]]:
        """One speculative step for {slot: last emitted token} ->
        {slot: emitted tokens} (1..k+1 per slot)."""
        if not slot_tokens:
            return {}
        params_by_slot = params_by_slot or {}
        k = self.k
        # 1. draft catch-up: feed tokens the target cached last round
        pending = {s: list(self._pending.get(s, ()))
                   for s in slot_tokens}
        while any(pending.values()):
            round_feed = {s: toks.pop(0)
                          for s, toks in pending.items() if toks}
            self.draft.decode_step_sampled(round_feed, None)
        for s in slot_tokens:
            self._pending[s] = []
        # 2. draft proposes k tokens (sampled from ITS adjusted
        # distribution under the request's knobs — the proposal
        # distribution the rejection test assumes)
        proposals: Dict[int, List[int]] = {s: [] for s in slot_tokens}
        draft_logits: Dict[int, List[np.ndarray]] = {
            s: [] for s in slot_tokens}
        feed = dict(slot_tokens)
        for _ in range(k):
            out = self.draft.decode_step_sampled(feed, params_by_slot)
            feed = {}
            for s, (tok, logits) in out.items():
                proposals[s].append(int(tok))
                draft_logits[s].append(logits)
                feed[s] = int(tok)
        # 3. ONE batched target verify over [t_last, d_1..d_k]
        windows = {s: [slot_tokens[s]] + proposals[s]
                   for s in slot_tokens}
        starts = {s: self.target.cache.length(s) for s in slot_tokens}
        vout = self.target.verify_step(windows, params_by_slot)
        # 4. host-side acceptance
        result: Dict[int, List[int]] = {}
        total_emitted = 0
        for s, (t_logits, t_toks) in vout.items():
            sp = params_by_slot.get(s, GREEDY)
            if sp.greedy:
                m, emitted = self._accept_greedy(proposals[s], t_toks)
            else:
                m, emitted = self._accept_sampled(
                    s, starts[s], proposals[s], draft_logits[s],
                    t_logits, t_toks, sp)
            # target: rows start..start+m hold [t_last, d_1..d_m] — all
            # emitted-but-last tokens plus the window input
            self.target.commit_window(s, m + 1)
            # draft: proposal steps advanced it to start+k; roll back to
            # the accepted length (rows start..start+m are valid there
            # too for m < k; a fully-accepted window leaves d_k pending)
            if m < k:
                self.draft.cache.set_length(s, starts[s] + m + 1)
            else:
                self.draft.cache.set_length(s, starts[s] + k)
                self._pending[s] = [proposals[s][-1]]
            smetrics.m_spec_windows.inc()
            smetrics.m_spec_proposed.inc(k)
            smetrics.m_spec_accepted.observe(m)
            self.stats.windows += 1
            self.stats.proposed += k
            self.stats.accepted += m
            self.stats.emitted += len(emitted)
            total_emitted += len(emitted)
            result[s] = emitted
        self.note_tokens(total_emitted)
        return result
