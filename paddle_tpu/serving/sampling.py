"""In-executable token sampling for the serving engine (docs/serving.md).

Temperature / top-k / top-p live INSIDE the compiled decode (and prefill
and verify) functions: per-slot parameters arrive as plain ``[max_batch]``
batch inputs and per-slot PRNG keys derive from a per-request integer
seed folded with the token position — so a request changing its sampling
knobs, or two requests with different knobs sharing a decode batch, never
changes a shape and never triggers a recompile (the zero-recompile
contract extends to sampling by construction).

Semantics per slot:

- ``temperature <= 0`` — greedy argmax, bit-identical to the pre-sampling
  engine (the parity bars and the slab/paged token-match tests key off
  this lane);
- ``temperature > 0`` — logits are divided by the temperature, then
  masked by top-k (keep the k highest-logit tokens; ``k <= 0`` disables)
  and nucleus top-p (keep the smallest set of tokens whose probability
  mass reaches ``p``; ``p >= 1`` disables), then sampled with
  ``jax.random.categorical`` under a key
  ``fold_in(PRNGKey(seed), position)`` — deterministic per
  (seed, position), independent across slots and steps.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["SamplingParams", "GREEDY", "sample_token", "sample_batch",
           "sample_window", "batch_arrays", "adjusted_probs_np"]


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (host-side truth; becomes batch inputs).

    ``temperature == 0`` is greedy decode — the default, and exactly the
    engine's historical behavior."""
    temperature: float = 0.0
    top_k: int = 0            # 0 disables
    top_p: float = 1.0        # 1.0 disables
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(f"temperature {self.temperature} < 0")
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(f"top_p {self.top_p} outside (0, 1]")
        if self.top_k < 0:
            raise ValueError(f"top_k {self.top_k} < 0")

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


GREEDY = SamplingParams()


def _masked_logits(logits, temp, top_k, top_p):
    """[V] f32 logits -> temperature-scaled, top-k/top-p-masked logits.

    ONE descending sort serves both filters: the top-k threshold reads
    straight off it, and the nucleus threshold converts to logit space
    through the (monotone) softmax of the k-masked sorted row — keeping
    the executable's compile cost down (this runs inside every decode/
    prefill/verify program)."""
    V = logits.shape[-1]
    scaled = logits / jnp.maximum(temp, 1e-6)
    desc = jnp.sort(scaled)[::-1]
    # top-k: threshold at the k-th largest logit (k<=0 or k>=V disables)
    kk = jnp.where(top_k <= 0, V, jnp.minimum(top_k, V))
    k_thresh = desc[jnp.maximum(kk - 1, 0)]
    # top-p over the k-masked distribution, in sorted space: keep the
    # smallest descending-probability set whose cumulative mass reaches p
    in_k = jnp.arange(V) < kk
    e = jnp.where(in_k, jnp.exp(desc - desc[0]), 0.0)
    p_desc = e / jnp.sum(e)
    cum = jnp.cumsum(p_desc)
    idx = jnp.argmax(cum >= jnp.minimum(top_p, cum[-1]))
    thresh = jnp.where(top_p >= 1.0, k_thresh,
                       jnp.maximum(k_thresh, desc[idx]))
    return jnp.where(scaled >= thresh, scaled, -jnp.inf)


def sample_token(logits, temp, top_k, top_p, seed, position):
    """One token from one [V] logits row (jit-traceable; scalars traced).

    Greedy lane (temp <= 0) short-circuits to argmax — no PRNG consumed,
    bitwise what the host-side ``np.argmax`` used to produce. The PRNG
    key is the raw pair ``(position, seed)`` — deterministic per
    (seed, position), independent across slots and steps, one threefry
    application per draw (a fold_in chain would compile two more)."""
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits).astype(jnp.int32)
    key = jnp.stack([position.astype(jnp.uint32),
                     seed.astype(jnp.uint32)])
    sampled = jax.random.categorical(
        key, _masked_logits(logits, temp, top_k, top_p)).astype(jnp.int32)
    return jnp.where(temp <= 0.0, greedy_tok, sampled)


def sample_batch(logits, temps, top_ks, top_ps, seeds, positions):
    """[B, V] logits + [B] per-slot params -> [B] int32 tokens."""
    return jax.vmap(sample_token)(logits, temps, top_ks, top_ps, seeds,
                                  positions)


def sample_window(logits, temps, top_ks, top_ps, seeds, positions):
    """[B, W, V] logits + [B] params + [B, W] positions -> [B, W] tokens
    (the speculative-verify window: every window position gets its own
    position-folded key off the slot's seed)."""

    def per_slot(lg, t, k, p, s, pos):
        return jax.vmap(
            lambda l, q: sample_token(l, t, k, p, s, q))(lg, pos)

    return jax.vmap(per_slot)(logits, temps, top_ks, top_ps, seeds,
                              positions)


def adjusted_probs_np(logits: np.ndarray, sp: SamplingParams
                      ) -> np.ndarray:
    """Numpy twin of the in-executable temperature/top-k/top-p masking:
    the normalized distribution a slot actually samples from. Used by
    the speculative-decoding rejection sampler (serving/spec_decode.py),
    where target-vs-draft acceptance must be computed against EXACTLY
    the adjusted distributions the executables sample.

    Greedy (temperature <= 0) returns the argmax one-hot."""
    logits = np.asarray(logits, np.float64).reshape(-1)
    V = logits.shape[0]
    if sp.greedy:
        out = np.zeros((V,), np.float64)
        out[int(np.argmax(logits))] = 1.0
        return out
    scaled = logits / max(sp.temperature, 1e-6)
    kk = V if sp.top_k <= 0 else min(sp.top_k, V)
    desc = np.sort(scaled)[::-1]
    masked = np.where(scaled >= desc[kk - 1], scaled, -np.inf)
    m = masked.max()
    probs = np.exp(masked - m)
    probs /= probs.sum()
    if sp.top_p < 1.0:
        p_desc = np.sort(probs)[::-1]
        cum = np.cumsum(p_desc)
        idx = int(np.argmax(cum >= min(sp.top_p, cum[-1])))
        probs = np.where(probs >= p_desc[idx], probs, 0.0)
        probs /= probs.sum()
    return probs


def batch_arrays(params_by_slot: Dict[int, SamplingParams],
                 max_batch: int) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray, np.ndarray]:
    """Host helper: {slot: SamplingParams} -> the four [max_batch] feed
    vectors (temps f32, top_ks i32, top_ps f32, seeds i32). Slots absent
    from the map ride greedy."""
    temps = np.zeros((max_batch,), np.float32)
    top_ks = np.zeros((max_batch,), np.int32)
    top_ps = np.ones((max_batch,), np.float32)
    seeds = np.zeros((max_batch,), np.int32)
    for slot, sp in params_by_slot.items():
        temps[slot] = sp.temperature
        top_ks[slot] = sp.top_k
        top_ps[slot] = sp.top_p
        seeds[slot] = np.int32(np.uint32(sp.seed))
    return temps, top_ks, top_ps, seeds
