"""Serving metric families (docs/serving.md, gated by tools/metrics_check.py).

All families live in the same default registry as the training telemetry,
so one Prometheus exposition carries both sides of the system. Children
are resolved once at import/call-site-build time per the registry's
hot-path cost model (observability/metrics.py).
"""
from __future__ import annotations

from ..observability import metrics as _obs

__all__ = [
    "m_requests", "m_queue_depth", "m_active", "m_occupancy",
    "m_ttft_ms", "m_tpot_ms", "m_tokens", "m_tokens_per_s",
    "m_prefill_ms", "m_decode_ms", "m_evictions", "m_queue_wait_ms",
    "m_prefix_cache", "m_prefill_tokens", "m_page_occupancy",
    "m_page_fragmentation", "m_spec_accepted", "m_spec_proposed",
    "m_spec_windows", "m_preemptions", "m_hol_admits",
    "m_shed", "m_replica_restarts", "m_failover", "m_prefix_store",
    "m_kv_transfer_bytes", "m_kv_transfer_ms", "m_pool_prefix",
    "m_disagg_fallback", "request_code",
]

_REG = _obs.default_registry()

# request outcomes by HTTP-style code ("200", "400", "429", "500", "503",
# "504") — the front door stamps every response; engine-level drivers
# (tools/serve_bench.py) stamp the logical equivalent
m_requests = _REG.counter(
    "paddle_serve_requests_total",
    "Serving requests by response code", ("code",))
m_queue_depth = _REG.gauge(
    "paddle_serve_queue_depth",
    "Requests waiting for a decode slot (admission queue)")
m_active = _REG.gauge(
    "paddle_serve_active_requests",
    "Requests currently holding a decode slot")
m_occupancy = _REG.gauge(
    "paddle_serve_batch_occupancy",
    "Live decode slots / max_batch at the last scheduler tick")
# TTFT spans prefill + queueing; TPOT is the per-token decode cadence —
# sub-ms buckets matter there. Both are split by the serving phase that
# produced the sample and the role of the replica that ran it (ISSUE 17:
# disaggregated serving needs per-phase latency, not a blended number).
m_ttft_ms = _REG.histogram(
    "paddle_serve_ttft_ms",
    "Time to first token (submit -> first generated token), ms",
    ("phase", "role"))
m_tpot_ms = _REG.histogram(
    "paddle_serve_tpot_ms",
    "Per-output-token latency after the first token, ms",
    ("phase", "role"))
m_tokens = _REG.counter(
    "paddle_serve_tokens_total", "Generated tokens")
m_tokens_per_s = _REG.gauge(
    "paddle_serve_tokens_per_s",
    "Generated tokens per second over the last scheduler window")
m_prefill_ms = _REG.histogram(
    "paddle_serve_prefill_ms",
    "Prefill executable wall time (bucket-padded prompt), ms")
m_decode_ms = _REG.histogram(
    "paddle_serve_decode_step_ms",
    "Decode executable wall time (one token across the batch), ms")
m_evictions = _REG.counter(
    "paddle_serve_slot_evictions_total",
    "Decode-slot evictions by reason", ("reason",))
# queue wait is the request's pre-TTFT tax: submit -> decode-slot
# admission (the span tracer stamps the same window as serve/queue_wait)
m_queue_wait_ms = _REG.histogram(
    "paddle_serve_queue_wait_ms",
    "Admission-queue wait (submit -> prefill start), ms")


# prefix cache (serving/paged_kv.py): a hit means the shared prompt
# prefix attached by refcount instead of prefilling again
m_prefix_cache = _REG.counter(
    "paddle_serve_prefix_cache_total",
    "Prefix-cache lookups by outcome", ("event",))
# VALID tokens prefilled (bucket padding excluded) — with prefix caching
# a repeated system prompt's second request only adds its suffix here,
# which is how metrics_check proves "a shared prefix prefills once"
m_prefill_tokens = _REG.counter(
    "paddle_serve_prefill_tokens_total",
    "Prompt tokens actually prefilled (prefix-cache hits excluded)")
m_page_occupancy = _REG.gauge(
    "paddle_serve_page_pool_occupancy",
    "Allocated KV pages / allocatable pages (scratch page excluded)")
m_page_fragmentation = _REG.gauge(
    "paddle_serve_page_pool_fragmentation",
    "Internal page waste: 1 - used rows / allocated rows")
# speculative decoding (serving/spec_decode.py): the acceptance histogram
# IS the speedup meter — mean accepted/window vs the draft+verify cost
m_spec_accepted = _REG.histogram(
    "paddle_serve_spec_accepted_tokens",
    "Draft tokens accepted per verify window")
m_spec_proposed = _REG.counter(
    "paddle_serve_spec_proposed_tokens_total",
    "Draft tokens proposed to the verifier")
m_spec_windows = _REG.counter(
    "paddle_serve_spec_windows_total", "Speculative verify windows run")
# scheduler preemptions (page pool dry mid-generation -> recompute
# requeue) and head-of-line bypass admissions
m_preemptions = _REG.counter(
    "paddle_serve_preemptions_total",
    "Active requests preempted (recompute-requeued) by reason",
    ("reason",))
m_hol_admits = _REG.counter(
    "paddle_serve_hol_bypass_admits_total",
    "Requests admitted past a head-of-line prompt that did not fit")


# resilience families (ISSUE 15, docs/serving.md "Resilience") -----------
# adaptive overload control: requests rejected up front instead of being
# queued into a guaranteed 504 — "deadline" = drain ETA beyond the
# request deadline, "queue_full" = admission queue at capacity
m_shed = _REG.counter(
    "paddle_serve_shed_total",
    "Requests shed by the overload control, by reason", ("reason",))
# gang supervisor (serving/gang.py): replica recycles by cause — crash
# (nonzero exit / signal death), hang (exit 43 or stale health probe),
# poisoned (exit 44 or /health status poisoned)
m_replica_restarts = _REG.counter(
    "paddle_serve_replica_restarts_total",
    "Serving replica recycles by cause (crash, hang, poisoned)",
    ("cause",))
# in-flight requests re-dispatched to a sibling replica after their
# replica died mid-request (partials discarded, the retry re-prefills)
m_failover = _REG.counter(
    "paddle_serve_failover_requests_total",
    "Requests re-dispatched to a sibling replica after a replica fault")
# warm restart (serving/prefix_store.py): published prefix-cache records
# persisted / restored through the elastic checkpoint store
m_prefix_store = _REG.counter(
    "paddle_serve_prefix_store_total",
    "Prefix-store operations (save, restore, restore_skipped)", ("op",))


# disaggregation families (ISSUE 17, docs/serving.md "Disaggregation") ---
# KV handoff volume/latency between prefill and decode replicas. These
# move ONLY on disagg runs — tools/metrics_check.py asserts they stay
# flat through a plain colocated serve.
m_kv_transfer_bytes = _REG.counter(
    "paddle_kv_transfer_bytes_total",
    "KV page bytes shipped between replicas, by direction",
    ("direction",))
m_kv_transfer_ms = _REG.histogram(
    "paddle_kv_transfer_ms",
    "Wall time of one request's KV handoff (export+ship+adopt), ms")
# gang-shared prefix index: a hit means a prompt prefix prefilled on ANY
# replica was reused here without recompute
m_pool_prefix = _REG.counter(
    "paddle_serve_pool_prefix_cache_total",
    "Pool-level (gang-shared) prefix index events, by phase",
    ("event", "phase"))
# disagg router degradations: a failed handoff or an empty phase fleet
# falls back to colocated dispatch — degrade, never drop
m_disagg_fallback = _REG.counter(
    "paddle_serve_disagg_fallback_total",
    "Disagg requests degraded to colocated dispatch, by reason",
    ("reason",))


def request_code(code: int) -> None:
    """Count one request outcome."""
    m_requests.labels(str(int(code))).inc()
