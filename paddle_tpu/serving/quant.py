"""Serving-side weight quantization: int8 / bf16 params for the decode
engine, reusing comm_opt's EQuARX-style chunk-scaled quantizer
(arXiv:2506.17615 — the same quantize/dequantize pair PR 5 put on the
gradient wire now shrinks the serving weight residency).

Storage layout per leaf (int8): the leaf is flattened, zero-padded to a
chunk multiple and quantized symmetric per chunk — payload ``int8 [n]``
plus ``f32 [n/chunk]`` scales, a 3.97x HBM cut at chunk=256. bf16 is a
plain cast (2x). Dequantization happens INSIDE the compiled prefill/decode
functions, so the f32 view exists only transiently in VMEM-sized tiles
after XLA fusion; HBM holds the quantized bytes.

The quality bar: int8 decode logits must stay within
:data:`INT8_LOGIT_TOL` of the f32 engine (max |Δlogit| relative to the
f32 logit spread) and within :data:`INT8_PPL_REL_TOL` on perplexity over
a held-out token stream — asserted by tests/test_serving_engine.py and
recorded in SERVE_BENCH.json by tools/serve_bench.py.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..parallel.comm_opt import dequantize_chunked, quantize_chunked

__all__ = [
    "QuantizedLeaf", "quantize_params", "dequantize_params",
    "quantized_nbytes", "logit_error_stats",
    "INT8_LOGIT_TOL", "INT8_PPL_REL_TOL", "WEIGHT_DTYPES",
]

WEIGHT_DTYPES = ("f32", "bf16", "int8")

# max |logit_int8 - logit_f32| / (max|logit_f32| over the row), worst row.
# Chunk-scaled symmetric int8 on GPT-2-init weights lands ~1e-2; the bar
# leaves ~6x headroom without letting a real regression through.
INT8_LOGIT_TOL = 0.06
# relative perplexity drift |ppl_q/ppl_f32 - 1| over the eval stream
INT8_PPL_REL_TOL = 0.02


class QuantizedLeaf:
    """One int8-quantized parameter leaf (payload + scales + shape)."""

    __slots__ = ("payload", "scales", "shape", "pad", "chunk")

    def __init__(self, payload, scales, shape, pad: int, chunk: int):
        self.payload = payload        # int8 [numel + pad]
        self.scales = scales          # f32 [(numel + pad) / chunk]
        self.shape = tuple(shape)
        self.pad = int(pad)
        self.chunk = int(chunk)

    def tree_flatten(self):
        return (self.payload, self.scales), (self.shape, self.pad, self.chunk)

    @classmethod
    def tree_unflatten(cls, aux, children):
        payload, scales = children
        shape, pad, chunk = aux
        return cls(payload, scales, shape, pad, chunk)


jax.tree_util.register_pytree_node(
    QuantizedLeaf,
    lambda q: q.tree_flatten(),
    QuantizedLeaf.tree_unflatten)


def _quantize_leaf(leaf, chunk: int) -> QuantizedLeaf:
    flat = jnp.asarray(leaf, jnp.float32).reshape(-1)
    pad = (-flat.shape[0]) % chunk
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    payload, scales = quantize_chunked(flat, "int8", chunk)
    return QuantizedLeaf(payload, scales, np.shape(leaf), pad, chunk)


def _dequantize_leaf(q: QuantizedLeaf):
    flat = dequantize_chunked(q.payload, q.scales, "int8", q.chunk)
    n = int(np.prod(q.shape)) if q.shape else 1
    return flat[:n].reshape(q.shape)


def quantize_params(params, weight_dtype: str, chunk: int = 256):
    """f32 param pytree -> serving storage pytree.

    "f32"  -> unchanged; "bf16" -> leaves cast to bf16; "int8" -> every
    floating leaf becomes a :class:`QuantizedLeaf` (integer leaves pass
    through untouched).
    """
    if weight_dtype not in WEIGHT_DTYPES:
        raise ValueError(
            f"weight_dtype {weight_dtype!r}: expected one of "
            f"{WEIGHT_DTYPES}")
    if weight_dtype == "f32":
        return params
    if weight_dtype == "bf16":
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            params)
    return jax.tree_util.tree_map(
        lambda x: _quantize_leaf(x, chunk)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
        params)


def dequantize_params(qparams):
    """Serving storage pytree -> f32 compute pytree (call INSIDE jit — the
    dequant fuses into the consuming matmuls; QuantizedLeaf is a pytree
    node, so tree_map over ``is_leaf`` picks the quantized leaves out)."""
    return jax.tree_util.tree_map(
        lambda x: _dequantize_leaf(x) if isinstance(x, QuantizedLeaf)
        else (x.astype(jnp.float32)
              if jnp.asarray(x).dtype == jnp.bfloat16 else x),
        qparams, is_leaf=lambda x: isinstance(x, QuantizedLeaf))


def quantized_nbytes(qparams) -> int:
    """Device bytes of the serving weight set (payloads + scales)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(qparams):
        total += int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
    return total


def logit_error_stats(ref_logits, q_logits) -> Dict[str, float]:
    """Quality metrics of quantized vs reference logits.

    ref/q: [..., V]. Returns max/mean absolute error, the spread-relative
    max error (the :data:`INT8_LOGIT_TOL` bar), and top-1 agreement."""
    ref = np.asarray(ref_logits, np.float64)
    q = np.asarray(q_logits, np.float64)
    if ref.shape != q.shape:
        raise ValueError(f"shape mismatch {ref.shape} vs {q.shape}")
    err = np.abs(ref - q)
    rows = ref.reshape(-1, ref.shape[-1])
    qrows = q.reshape(-1, q.shape[-1])
    spread = np.max(np.abs(rows), axis=1)
    spread = np.where(spread > 0, spread, 1.0)
    rel = np.max(err.reshape(rows.shape), axis=1) / spread
    return {
        "max_abs_err": float(err.max()),
        "mean_abs_err": float(err.mean()),
        "max_rel_err": float(rel.max()),
        "top1_agreement": float(
            np.mean(rows.argmax(1) == qrows.argmax(1))),
    }


def perplexity(logits, labels) -> float:
    """Token perplexity of next-token logits [N, V] against labels [N]."""
    logits = np.asarray(logits, np.float64)
    labels = np.asarray(labels).reshape(-1)
    lse = np.log(np.sum(np.exp(logits - logits.max(-1, keepdims=True)),
                        axis=-1)) + logits.max(-1)
    gold = logits[np.arange(len(labels)), labels]
    return float(np.exp(np.mean(lse - gold)))
