"""TPU-native serving engine (ISSUE 9, docs/serving.md).

The production-inference half of the north star: AOT-compiled prefill
(shape-bucketed ladder) and decode (static ``[max_batch]`` slot batch)
executables over a preallocated, donated KV cache; continuous / in-flight
batching at token boundaries; int8/bf16 serving weights through the
comm_opt chunk-scaled quantizer; an HTTP front door with admission
control, deadlines, backpressure and graceful drain. Steady state is
ZERO-recompile by construction — ``paddle_recompiles_total`` (PR 4) is
the enforced guardrail.

Quick start::

    from paddle_tpu.models import gpt
    from paddle_tpu import serving

    params = gpt.init_params(jax.random.PRNGKey(0), gpt.GPT_SMALL)
    engine = serving.DecodeEngine(
        params, gpt.GPT_SMALL,
        serving.EngineConfig(max_batch=8, max_seq=256,
                             weight_dtype="int8"))
    engine.warmup()                      # all compiles happen HERE
    sched = serving.Scheduler(engine)
    front = serving.FrontDoor(scheduler=sched, port=8866).start()
"""
from .engine import (  # noqa: F401
    DecodeEngine,
    EngineConfig,
    PromptTooLongError,
    default_bucket_ladder,
)
from .kv_cache import CacheFullError, KVCache  # noqa: F401
from .paged_kv import (  # noqa: F401
    PagedKVCache,
    PagePoolFullError,
    PrefixCache,
)
from .sampling import GREEDY, SamplingParams  # noqa: F401
from .spec_decode import SpecDecodeEngine, SpecStats  # noqa: F401
from .quant import (  # noqa: F401
    INT8_LOGIT_TOL,
    INT8_PPL_REL_TOL,
    dequantize_params,
    logit_error_stats,
    quantize_params,
)
from .scheduler import (  # noqa: F401
    QueueFullError,
    Request,
    Scheduler,
    SchedulerConfig,
)
from .server import EngineLoop, FrontDoor, shed_decision  # noqa: F401
from .prefix_store import PrefixStore  # noqa: F401
from .replica import POISONED_EXIT_CODE, ReplicaRole  # noqa: F401
from .gang import (  # noqa: F401
    GangConfig,
    GangFrontDoor,
    ReplicaGang,
)
from .kv_transfer import (  # noqa: F401
    CacheConfigMismatch,
    KVTransferServer,
    adopt_into_engine,
    cache_fingerprint,
    export_slot,
)
from .disagg import (  # noqa: F401
    DisaggRouter,
    LocalReplica,
    SharedPrefixIndex,
)

__all__ = [
    "DecodeEngine", "EngineConfig", "PromptTooLongError",
    "default_bucket_ladder", "KVCache", "CacheFullError",
    "PagedKVCache", "PrefixCache", "PagePoolFullError",
    "SamplingParams", "GREEDY", "SpecDecodeEngine", "SpecStats",
    "quantize_params", "dequantize_params", "logit_error_stats",
    "INT8_LOGIT_TOL", "INT8_PPL_REL_TOL",
    "Scheduler", "SchedulerConfig", "Request", "QueueFullError",
    "FrontDoor", "EngineLoop", "shed_decision",
    "PrefixStore", "POISONED_EXIT_CODE", "ReplicaRole",
    "ReplicaGang", "GangConfig", "GangFrontDoor",
    "CacheConfigMismatch", "KVTransferServer", "cache_fingerprint",
    "export_slot", "adopt_into_engine",
    "DisaggRouter", "LocalReplica", "SharedPrefixIndex",
]
