"""Production HTTP front door for the serving engine (docs/serving.md).

One server class, two backends:

- **engine backend** (``FrontDoor(scheduler=...)``): ``POST /generate``
  with ``{"prompt": [token ids], "max_new_tokens": N, "timeout_s": T}`` —
  requests queue into the continuous-batching scheduler and stream through
  the AOT decode engine. A dedicated loop thread ticks the scheduler; the
  handler thread blocks on the request's completion event.
- **predictor backend** (``FrontDoor(predictor=...)``): ``POST /predict``
  with ``{"inputs": {name: nested-list}}`` — the PR-era StableHLO /
  save_inference_model artifact path, now behind the same admission
  control.

Shared production semantics (the ISSUE 9 robustness satellite):

- bounded admission: queue-full -> **429** with a JSON error body;
- per-request deadlines: blown -> **504** (a queued generate request whose
  deadline passes is expired by the scheduler at the token boundary);
- error taxonomy: malformed/mismatched client input -> **400**, internal
  handler failure -> **500**, always with a JSON body (never a raw
  traceback or an empty 500);
- graceful drain: SIGTERM (``install_signal_handlers()``) flips the server
  to *draining* — new work is refused with **503**, in-flight requests
  finish, then the listener closes. ``/health`` reports the phase.
- every response increments ``paddle_serve_requests_total{code}``;
  ``GET /metrics`` serves the Prometheus exposition of the shared
  registry.
"""
from __future__ import annotations

import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from ..observability import spans as _ospans
from . import metrics as smetrics
from .engine import PromptTooLongError
from .scheduler import QueueFullError, Scheduler

__all__ = ["FrontDoor", "EngineLoop", "shed_decision"]


def shed_decision(scheduler: Scheduler, timeout_s: float,
                  retry_after_cap_s: float = 60.0):
    """Deadline-aware admission check (docs/serving.md "Resilience"):
    when the measured queue-drain ETA already exceeds the request's
    deadline, admitting it only guarantees a 504 after the client waited
    the full timeout — shed NOW with a Retry-After computed from the
    drain rate instead. Returns ``None`` (admit) or ``(reason,
    retry_after_s)``; counts ``paddle_serve_shed_total{reason}``."""
    eta = scheduler.queue_eta_s()
    if eta is None or eta <= timeout_s:
        return None
    smetrics.m_shed.labels("deadline").inc()
    return "deadline", scheduler.retry_after_s(retry_after_cap_s)


class EngineLoop:
    """Background thread ticking ``scheduler.step()``; parks on an event
    when idle so an empty server burns no CPU.

    A ``step()`` exception must never kill this thread silently while the
    HTTP server keeps accepting work (every handler would then block to
    504 with no operator-visible signal): the loop catches it, fails every
    queued/active request so their waiters wake with an error, records the
    fault (``faults``/``last_fault``, surfaced through ``/health``), and
    keeps ticking.

    A POISONED engine is different: no later step can ever succeed
    (donated KV slabs are invalid — engine.py), so instead of 500ing
    every request forever the loop fails fast — it aborts everything
    with ``refuse_new`` (late submits get a clean error), records
    ``poison_reason``, invokes ``on_poison`` (a supervised replica exits
    with :data:`~paddle_tpu.serving.replica.POISONED_EXIT_CODE` here so
    the gang recycles it with ``cause=poisoned``), and stops ticking.
    ``/health`` reports status ``poisoned``.

    Every iteration stamps hang-watchdog progress (``serve/tick``), so a
    replica armed via the ``PADDLE_HEALTH_*`` env contract exits 43 when
    the loop wedges — the same contract training workers follow."""

    def __init__(self, scheduler: Scheduler, idle_sleep_s: float = 0.002,
                 on_poison=None):
        self.scheduler = scheduler
        self.idle_sleep_s = idle_sleep_s
        self.faults = 0
        self.last_fault: Optional[str] = None
        self.on_poison = on_poison
        self.poison_reason: Optional[str] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "EngineLoop":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-engine-loop")
        self._thread.start()
        return self

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def wake(self) -> None:
        self._wake.set()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        from ..parallel import health as _health

        while not self._stop.is_set():
            _health.progress("serve/tick")
            if self._check_poisoned():
                return
            worked = False
            if self.scheduler.pending():
                try:
                    worked = self.scheduler.step()
                except Exception as e:
                    self.faults += 1
                    self.last_fault = f"{type(e).__name__}: {e}"
                    try:
                        self.scheduler.abort_all(
                            f"engine loop fault: {self.last_fault}")
                    except Exception:
                        pass  # never let cleanup kill the loop either
                    if self._check_poisoned():
                        return
            if not worked:
                self._wake.wait(timeout=self.idle_sleep_s)
                self._wake.clear()

    def _check_poisoned(self) -> bool:
        """Fail-fast on a poisoned engine: abort + refuse, fire
        ``on_poison``, stop the loop. Returns True when poisoned."""
        reason = getattr(self.scheduler.engine, "poisoned", None)
        if reason is None:
            return False
        if self.poison_reason is None:
            self.poison_reason = str(reason)
            try:
                self.scheduler.abort_all(
                    f"engine poisoned: {reason}", refuse_new=True)
            except Exception:
                pass
            if self.on_poison is not None:
                try:
                    self.on_poison(self.poison_reason)
                except Exception:
                    pass
        self._stop.set()
        return True


class _Server(ThreadingHTTPServer):
    # the stdlib default listen backlog (5) resets connections under a
    # burst of simultaneous connects — exactly the overload moment the
    # shedding path exists for; shed with a 429, not a TCP reset
    request_queue_size = 128


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        if self.server.front.verbose:
            super().log_message(fmt, *args)

    # -- plumbing ----------------------------------------------------------
    def _json(self, code: int, obj: Dict[str, Any],
              retry_after: Optional[int] = None) -> None:
        if retry_after is not None:
            # both the header (standard clients) and a JSON field
            # (the gang router + simple SDKs read the body only)
            obj = dict(obj, retry_after_s=int(retry_after))
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        if retry_after is not None:
            self.send_header("Retry-After", str(int(retry_after)))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; the count below still records it
        smetrics.request_code(code)

    def _read_json(self) -> Optional[Dict[str, Any]]:
        n = int(self.headers.get("Content-Length", 0))
        if n > self.server.front.max_body_bytes:
            self._json(413, {"error": "body too large"})
            return None
        try:
            return json.loads(self.rfile.read(n).decode())
        except (ValueError, UnicodeDecodeError) as e:
            self._json(400, {"error": f"malformed JSON body: {e}"})
            return None

    # -- routes ------------------------------------------------------------
    def do_GET(self):
        front = self.server.front
        if self.path == "/health":
            return self._json(200, front.health())
        if self.path == "/metrics":
            from ..observability import prom

            text = prom.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            self.wfile.write(text)
            smetrics.request_code(200)
            return
        self._json(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):
        front = self.server.front
        if self.path == "/generate":
            return self._generate(front)
        if self.path == "/prefill":
            return self._prefill(front)
        if self.path == "/resume":
            return self._resume(front)
        if self.path == "/predict":
            return self._predict(front)
        self._json(404, {"error": f"unknown path {self.path!r}"})

    @staticmethod
    def _parse_sampling(req_obj):
        if not any(k in req_obj for k in ("temperature", "top_k",
                                          "top_p", "seed")):
            return None
        from .sampling import SamplingParams

        return SamplingParams(
            temperature=float(req_obj.get("temperature", 0.0)),
            top_k=int(req_obj.get("top_k", 0)),
            top_p=float(req_obj.get("top_p", 1.0)),
            seed=int(req_obj.get("seed", 0)))

    # -- disaggregated phases (ISSUE 17) -----------------------------------
    def _prefill(self, front: "FrontDoor"):
        """Prefill-only: run to the first token, then either push the
        KV handoff to the caller-named decode replica's transfer
        endpoint (``kv_target``) or return it inline (base64)."""
        if front.scheduler is None:
            return self._json(400, {"error": "no generation engine loaded"})
        if front.draining:
            return self._json(503, {"error": "server is draining"},
                              retry_after=front._retry_after())
        req_obj = self._read_json()
        if req_obj is None:
            return
        prompt = req_obj.get("prompt") or req_obj.get("tokens")
        if not isinstance(prompt, list) or not prompt:
            return self._json(
                400, {"error": "body must carry a non-empty token list "
                               "under 'prompt'"})
        timeout_s = req_obj.get("timeout_s")
        timeout_s = (front.request_timeout_s if timeout_s is None
                     else float(timeout_s))
        try:
            request = front.scheduler.submit(
                prompt, max_new_tokens=int(req_obj.get(
                    "max_new_tokens", 16)),
                timeout_s=timeout_s,
                sampling=self._parse_sampling(req_obj),
                prefill_only=True,
                trace_ctx=_ospans.extract(req_obj))
        except QueueFullError as e:
            smetrics.m_shed.labels("queue_full").inc()
            return self._json(429, {"error": str(e)},
                              retry_after=front._retry_after())
        except PromptTooLongError as e:
            return self._json(400, {"error": str(e)})
        except (TypeError, ValueError) as e:
            return self._json(400, {"error": f"{type(e).__name__}: {e}"})
        except RuntimeError as e:
            return self._json(503, {"error": str(e)},
                              retry_after=front._retry_after())
        front.loop.wake()
        request.wait(timeout=timeout_s + 1.0)
        if request.state != "done" or request.handoff is None:
            if request.state in ("expired", "queued", "active"):
                return self._json(504, {
                    "error": request.error or "deadline exceeded"})
            return self._json(500, {"error": request.error
                                    or f"request {request.state}"})
        from . import kv_transfer as kvt

        handoff = request.handoff
        resp = {"first_token": int(request.tokens[0]),
                "ttft_ms": round(request.ttft_ms, 3),
                "transfer_id": handoff["transfer_id"]}
        kv_target = req_obj.get("kv_target")
        if kv_target:
            handoff = dict(handoff, transfer_id=str(
                kv_target.get("transfer_id") or handoff["transfer_id"]))
            try:
                kvt.send_handoff(kv_target["host"],
                                 int(kv_target["port"]), handoff,
                                 timeout_s=timeout_s)
            except Exception as e:
                # the prefill itself succeeded; the handoff channel did
                # not — 502 tells the router to degrade to colocated
                return self._json(502, {
                    "error": f"KV push failed: {type(e).__name__}: {e}",
                    "first_token": int(request.tokens[0])})
            resp["transfer_id"] = handoff["transfer_id"]
            resp["transferred"] = True
        else:
            resp["kv"] = kvt.handoff_to_jsonable(handoff)
        return self._json(200, resp)

    def _resume(self, front: "FrontDoor"):
        """Decode a migrated request: adopt its KV handoff (socket
        transfer by id, or inline) and generate from the first token."""
        if front.scheduler is None:
            return self._json(400, {"error": "no generation engine loaded"})
        if front.draining:
            return self._json(503, {"error": "server is draining"},
                              retry_after=front._retry_after())
        req_obj = self._read_json()
        if req_obj is None:
            return
        if "first_token" not in req_obj:
            return self._json(400, {"error": "body must carry "
                                             "'first_token'"})
        prompt = req_obj.get("prompt") or []
        timeout_s = req_obj.get("timeout_s")
        timeout_s = (front.request_timeout_s if timeout_s is None
                     else float(timeout_s))
        from . import kv_transfer as kvt

        if req_obj.get("transfer_id"):
            if front.kv_server is None:
                return self._json(400, {
                    "error": "no KV transfer server on this replica"})
            try:
                handoff = front.kv_server.pop(
                    str(req_obj["transfer_id"]),
                    timeout_s=min(timeout_s, 10.0))
            except TimeoutError as e:
                return self._json(504, {"error": str(e)})
        elif req_obj.get("kv"):
            try:
                handoff = kvt.handoff_from_jsonable(req_obj["kv"])
            except Exception as e:
                return self._json(400, {
                    "error": f"malformed inline handoff: {e}"})
        else:
            return self._json(400, {"error": "body must carry "
                                             "'transfer_id' or 'kv'"})
        try:
            request = front.scheduler.submit_handoff(
                handoff, int(req_obj["first_token"]),
                max_new_tokens=int(req_obj.get("max_new_tokens", 16)),
                timeout_s=timeout_s,
                sampling=self._parse_sampling(req_obj),
                prompt=prompt or None,
                trace_ctx=_ospans.extract(req_obj))
        except QueueFullError as e:
            smetrics.m_shed.labels("queue_full").inc()
            return self._json(429, {"error": str(e)},
                              retry_after=front._retry_after())
        except (TypeError, ValueError) as e:
            return self._json(400, {"error": f"{type(e).__name__}: {e}"})
        except RuntimeError as e:
            return self._json(503, {"error": str(e)},
                              retry_after=front._retry_after())
        front.loop.wake()
        request.wait(timeout=timeout_s + 1.0)
        if request.state == "done":
            return self._json(200, {
                "tokens": request.tokens,
                "num_tokens": len(request.tokens),
                "tpot_ms": (round(request.tpot_ms, 3)
                            if request.tpot_ms is not None else None),
            })
        if request.state in ("expired", "queued", "active"):
            return self._json(504, {
                "error": request.error or "deadline exceeded",
                "partial_tokens": request.tokens})
        return self._json(500, {"error": request.error
                                or f"request {request.state}"})

    # -- engine backend ----------------------------------------------------
    def _generate(self, front: "FrontDoor"):
        if front.scheduler is None:
            return self._json(400, {"error": "no generation engine loaded"})
        if front.draining:
            return self._json(503, {"error": "server is draining"},
                              retry_after=front._retry_after())
        req_obj = self._read_json()
        if req_obj is None:
            return
        prompt = req_obj.get("prompt") or req_obj.get("tokens")
        if not isinstance(prompt, list) or not prompt:
            return self._json(
                400, {"error": "body must carry a non-empty token list "
                               "under 'prompt'"})
        timeout_s = req_obj.get("timeout_s")
        timeout_s = (front.request_timeout_s if timeout_s is None
                     else float(timeout_s))
        if front.shed_deadline_aware:
            shed = shed_decision(front.scheduler, timeout_s,
                                 front.retry_after_cap_s)
            if shed is not None:
                reason, after = shed
                return self._json(429, {
                    "error": f"queue drain ETA exceeds the request "
                             f"deadline ({timeout_s:.1f}s) — shed "
                             f"({reason})"}, retry_after=after)
        try:
            sampling = self._parse_sampling(req_obj)
            request = front.scheduler.submit(
                prompt, max_new_tokens=int(req_obj.get(
                    "max_new_tokens", 16)),
                timeout_s=timeout_s, sampling=sampling,
                trace_ctx=_ospans.extract(req_obj))
        except QueueFullError as e:
            smetrics.m_shed.labels("queue_full").inc()
            return self._json(429, {"error": str(e)},
                              retry_after=front._retry_after())
        except PromptTooLongError as e:
            return self._json(400, {"error": str(e)})
        except (TypeError, ValueError) as e:
            return self._json(400, {"error": f"{type(e).__name__}: {e}"})
        except RuntimeError as e:
            # draining raced the check above, or a poisoned engine's
            # refusal — either way: clean 503, come back later/elsewhere
            return self._json(503, {"error": str(e)},
                              retry_after=front._retry_after())
        front.loop.wake()
        # the scheduler owns the deadline; +1s of slack covers loop wakeup
        request.wait(timeout=timeout_s + 1.0)
        if request.state == "done":
            return self._json(200, {
                "tokens": request.tokens,
                "num_tokens": len(request.tokens),
                "ttft_ms": round(request.ttft_ms, 3),
                "tpot_ms": (round(request.tpot_ms, 3)
                            if request.tpot_ms is not None else None),
            })
        if request.state in ("expired", "queued", "active"):
            return self._json(504, {
                "error": request.error or "deadline exceeded",
                "partial_tokens": request.tokens})
        return self._json(500, {"error": request.error
                                or f"request {request.state}"})

    # -- predictor backend -------------------------------------------------
    def _predict(self, front: "FrontDoor"):
        if front.predictor is None:
            return self._json(400, {"error": "no predictor loaded"})
        if front.draining:
            return self._json(503, {"error": "server is draining"})
        req_obj = self._read_json()
        if req_obj is None:
            return
        if "inputs" not in req_obj or not isinstance(req_obj["inputs"],
                                                     dict):
            return self._json(400, {"error": "body must carry 'inputs'"})
        if not front._predict_slots.acquire(blocking=False):
            smetrics.m_shed.labels("queue_full").inc()
            return self._json(429, {
                "error": f"predict queue at capacity "
                         f"({front.max_queue})"}, retry_after=1)
        t0 = time.monotonic()
        deadline = t0 + front.request_timeout_s
        try:
            feed = {k: np.asarray(v) for k, v in req_obj["inputs"].items()}
            # predictor calls are serialized (one device queue); waiting
            # for the run lock IS the queueing — bounded by the deadline
            if not front._run_lock.acquire(
                    timeout=max(0.0, deadline - time.monotonic())):
                return self._json(504, {
                    "error": "deadline exceeded while queued"})
            try:
                front._inflight += 1
                outs = front.predictor.run(feed)
            finally:
                front._inflight -= 1
                front._run_lock.release()
        except (KeyError, ValueError, TypeError) as e:
            # client-shaped failure: wrong names, shapes, dtypes
            return self._json(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:
            return self._json(500, {"error": f"{type(e).__name__}: {e}"})
        finally:
            front._predict_slots.release()
        smetrics.m_ttft_ms.labels("predict", "colocated").observe(
            (time.monotonic() - t0) * 1e3)
        return self._json(200, {"outputs": [np.asarray(o).tolist()
                                            for o in outs]})


class FrontDoor:
    """The serving HTTP server. Construct with exactly one backend:
    ``scheduler=`` (generation) or ``predictor=`` (artifact inference);
    both may be present (generation servers usually also expose their
    tokenizer-side artifact — not required)."""

    def __init__(self, scheduler: Optional[Scheduler] = None,
                 predictor=None, host: str = "127.0.0.1", port: int = 0,
                 max_queue: int = 64, request_timeout_s: float = 30.0,
                 max_body_bytes: int = 256 << 20, verbose: bool = False,
                 shed_deadline_aware: bool = True,
                 retry_after_cap_s: float = 60.0, on_poison=None,
                 kv_server=None):
        if scheduler is None and predictor is None:
            raise ValueError("FrontDoor needs a scheduler or a predictor")
        self.scheduler = scheduler
        self.predictor = predictor
        # KVTransferServer for the socket handoff channel (decode-role
        # replicas in a disaggregated gang; None = inline handoffs only)
        self.kv_server = kv_server
        self.max_queue = int(max_queue)
        self.request_timeout_s = float(request_timeout_s)
        self.max_body_bytes = int(max_body_bytes)
        self.verbose = verbose
        # adaptive overload control (docs/serving.md "Resilience"):
        # reject requests whose measured queue-drain ETA already exceeds
        # their deadline, with a Retry-After from the drain rate
        self.shed_deadline_aware = bool(shed_deadline_aware)
        self.retry_after_cap_s = float(retry_after_cap_s)
        self._draining = False
        self._inflight = 0
        self._run_lock = threading.Lock()
        self._predict_slots = threading.BoundedSemaphore(self.max_queue)
        self.loop = (EngineLoop(scheduler, on_poison=on_poison).start()
                     if scheduler is not None else None)
        self.httpd = _Server((host, port), _Handler)
        self.httpd.daemon_threads = True
        self.httpd.front = self
        self._thread: Optional[threading.Thread] = None
        self._old_handlers: Dict[int, Any] = {}

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def draining(self) -> bool:
        return self._draining

    def _retry_after(self) -> int:
        """Retry-After seconds for 429/503 responses, from the measured
        scheduler drain rate (1 when no scheduler / no rate yet)."""
        if self.scheduler is None:
            return 1
        try:
            return self.scheduler.retry_after_s(self.retry_after_cap_s)
        except Exception:
            return 1

    def start(self) -> "FrontDoor":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self.loop is not None:
            self.loop.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def health(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "status": "draining" if self._draining else "ok",
        }
        if self.scheduler is not None:
            out["role"] = getattr(self.scheduler.engine, "role",
                                  "colocated")
        if self.predictor is not None:
            out["inputs"] = self.predictor.get_input_names()
            out["outputs"] = self.predictor.get_output_names()
        if self.scheduler is not None:
            # per-span-name percentile rollups (queue wait, prefill,
            # decode ticks, evictions, whole requests) off the tracer ring
            from ..observability import spans as _ospans

            out["span_rollups_ms"] = {
                k: v for k, v in _ospans.default_tracer().summary().items()
                if k.startswith("serve/")}
            out["queue_depth"] = self.scheduler.queue_depth()
            out["active"] = len(self.scheduler._active)
            out["max_batch"] = self.scheduler.engine.ecfg.max_batch
            out["buckets"] = list(self.scheduler.engine.buckets)
            out["weight_dtype"] = self.scheduler.engine.ecfg.weight_dtype
            if self.loop is not None:
                out["loop_alive"] = self.loop.alive
                out["loop_faults"] = self.loop.faults
                if self.loop.last_fault is not None:
                    out["loop_last_fault"] = self.loop.last_fault
                if not self.loop.alive and not self._draining:
                    out["status"] = "degraded"
            # a poisoned engine outranks everything: donation invalidated
            # its KV slabs, no request will ever succeed again — the gang
            # supervisor recycles the replica on this status
            poisoned = getattr(self.scheduler.engine, "poisoned", None)
            if self.loop is not None and self.loop.poison_reason:
                poisoned = poisoned or self.loop.poison_reason
            if poisoned:
                out["status"] = "poisoned"
                out["engine_poisoned"] = str(poisoned)
        return out

    # -- graceful drain ----------------------------------------------------
    def drain(self, timeout_s: float = 60.0) -> bool:
        """Refuse new work, finish what is in flight, then stop. Returns
        True when everything completed inside the timeout."""
        from ..observability import goodput as _goodput

        with _goodput.timer("drain"):
            return self._drain_inner(timeout_s)

    def _drain_inner(self, timeout_s: float) -> bool:
        self._draining = True
        ok = True
        if self.scheduler is not None:
            with self.scheduler._lock:
                self.scheduler._draining = True
            if self.loop is not None:
                self.loop.wake()
            end = time.monotonic() + timeout_s
            while time.monotonic() < end and self.scheduler.pending():
                time.sleep(0.01)
            ok = self.scheduler.pending() == 0
        end = time.monotonic() + max(0.1, timeout_s / 10)
        while time.monotonic() < end and self._inflight > 0:
            time.sleep(0.01)
        ok = ok and self._inflight == 0
        self.stop()
        return ok

    def install_signal_handlers(self, drain_timeout_s: float = 60.0) -> None:
        """SIGTERM/SIGINT -> graceful drain in a helper thread (the
        handler itself must return immediately — it may run on the main
        thread mid-request)."""

        def _on_signal(signum, frame):
            threading.Thread(target=self.drain,
                             kwargs={"timeout_s": drain_timeout_s},
                             daemon=True,
                             name="serve-drain").start()

        for sig in (signal.SIGTERM, signal.SIGINT):
            self._old_handlers[sig] = signal.signal(sig, _on_signal)

    def restore_signal_handlers(self) -> None:
        for sig, h in self._old_handlers.items():
            signal.signal(sig, h)
        self._old_handlers.clear()
