"""Continuous (in-flight) batching scheduler over the decode engine.

Requests join and leave the static ``[max_batch]`` decode batch at TOKEN
boundaries: each :meth:`Scheduler.step` (one tick of the serving loop)
first evicts finished/expired slots, then admits queued requests into the
freed slots (prefill through the bucket ladder), then runs exactly one
generation step for every live slot — one token per slot on the plain
engine, up to ``k+1`` on the speculative wrapper. No shape ever changes,
so a warmed engine ticks forever without a recompile — Orca-style
iteration-level scheduling (the same contract vLLM's continuous batching
popularized), implemented host-side against the AOT executables.

Admission is FIFO with a bounded head-of-line bypass: when the head's
prompt does not fit the current slot/page budget (paged engines meter
pages, not slots), the scheduler admits the NEXT fitting request instead
of stalling the queue — but a head that has been bypassed
``hol_starvation_limit`` times pins the queue until it fits, so a big
prompt is delayed, never starved.

Paged engines can run the pool dry mid-generation (a slot crossing a
page boundary with no free page): the scheduler preempts the YOUNGEST
active request — frees its pages, requeues it at the queue head with its
generated tokens folded into the prompt (recompute-style resume; with
the prefix cache warm, the recompute is usually a suffix prefill) — and
retries. ``paddle_serve_preemptions_total{reason}`` meters it.

Threading contract: ``submit``/``cancel`` may be called from any thread
(the HTTP front door's handler pool); ``step``/``drain`` run on exactly
one loop thread. Request completion is signaled through a per-request
``threading.Event``. ``abort_all(refuse_new=True)`` — the poisoned-
engine fail-fast path — is safe against racing submits: the refusal
flag is set under the queue lock before the queue drains, so a
concurrent submit is either failed with everyone else or cleanly
refused, never parked on a queue no step will serve again.

The scheduler also measures its own drain rate (terminal requests per
second over a trailing window): ``queue_eta_s``/``retry_after_s`` feed
the front door's deadline-aware shedding and Retry-After responses
(docs/serving.md "Resilience").
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence

import numpy as np

from ..observability import goodput as _goodput
from ..observability import spans as _spans
from . import metrics as smetrics
from .engine import DecodeEngine, PromptTooLongError
from .kv_cache import CacheFullError
from .paged_kv import PagePoolFullError
from .sampling import GREEDY, SamplingParams

__all__ = ["Request", "Scheduler", "SchedulerConfig", "QueueFullError"]


class QueueFullError(RuntimeError):
    """Admission queue at capacity — the front door maps this to 429."""


# request lifecycle
QUEUED, ACTIVE, DONE, EXPIRED, FAILED, CANCELLED = (
    "queued", "active", "done", "expired", "failed", "cancelled")

_ids = itertools.count(1)


@dataclasses.dataclass
class Request:
    prompt: List[int]
    max_new_tokens: int
    deadline: float                       # absolute time.monotonic()
    sampling: SamplingParams = GREEDY
    id: int = dataclasses.field(default_factory=lambda: next(_ids))
    submitted: float = dataclasses.field(default_factory=time.monotonic)
    state: str = QUEUED
    slot: Optional[int] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    token_times: List[float] = dataclasses.field(default_factory=list)
    ttft_ms: Optional[float] = None
    error: Optional[str] = None
    # head-of-line bookkeeping: how many times a fitting request was
    # admitted past this one while it sat at the queue head
    hol_skips: int = 0
    # preemption (page pool dry): the request resumes by re-prefilling
    # prompt + generated-so-far — True marks it so admission knows
    preempted: bool = False
    # phase disaggregation (ISSUE 17): a prefill_only request finishes
    # at the first-token boundary with its KV serialized into
    # ``handoff`` (serving/kv_transfer.py); on the decode side the same
    # field carries the payload awaiting adoption at the next tick.
    # ``prefix_blob`` is a gang-shared prefix-index record to adopt
    # into the local pool before this request prefills.
    prefill_only: bool = False
    handoff: Optional[dict] = None
    prefix_blob: Optional[dict] = None
    finished: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    # span identity (docs/observability.md): every lifecycle span of this
    # request — queue wait, prefill, each decode tick, eviction — carries
    # trace_id, parented under root_span ("serve/request"), so a slow p99
    # walks straight back to the tick that caused it
    trace_id: int = dataclasses.field(default_factory=_spans.gen_id)
    root_span: int = dataclasses.field(default_factory=_spans.gen_id)
    # cross-process propagation (ISSUE 18): a request arriving with wire
    # trace context keeps the originating trace_id and parents its local
    # "serve/request" span under the sender's span instead of rooting a
    # fresh trace — one request stays ONE trace across router, prefill
    # replica, KV transfer, decode replica, and every failover retry
    parent_span: Optional[int] = None
    submit_ns: int = dataclasses.field(
        default_factory=time.perf_counter_ns)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.finished.wait(timeout)

    def gen_prompt(self) -> List[int]:
        """The token stream a (re-)prefill must cover: the original
        prompt plus everything generated before a preemption."""
        return self.prompt + self.tokens

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean per-token latency after the first token."""
        if len(self.token_times) < 2:
            return None
        spans = np.diff(self.token_times)
        return float(np.mean(spans) * 1e3)


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    max_queue: int = 64               # queued (not yet admitted) requests
    default_timeout_s: float = 30.0   # per-request deadline when unset
    max_new_tokens_cap: int = 1024    # server-side clamp
    # how many times the FIFO head may be bypassed by later, fitting
    # requests before it pins the queue (the starvation bound)
    hol_starvation_limit: int = 32


class Scheduler:
    def __init__(self, engine: DecodeEngine,
                 cfg: Optional[SchedulerConfig] = None):
        self.engine = engine
        self.cfg = cfg or SchedulerConfig()
        self._queue: Deque[Request] = deque()
        self._active: Dict[int, Request] = {}     # slot -> request
        self._next_token: Dict[int, int] = {}     # slot -> token to feed
        self._admit_order: List[int] = []         # slots, oldest first
        self._lock = threading.Lock()
        self._draining = False
        # set by abort_all(refuse_new=True) — the fail-fast path for a
        # poisoned engine: later submits get a clean error instead of
        # queueing onto a scheduler that can never serve them
        self._refusing: Optional[str] = None
        self.steps = 0
        self.occupancy_sum = 0.0                  # for mean occupancy
        self.preemptions = 0
        self.completed = 0                        # requests finished DONE
        # terminal-event timestamps feeding the measured drain rate that
        # deadline-aware shedding / Retry-After are computed from
        # (own lock: _finish runs under self._lock on some paths)
        self._rate_lock = threading.Lock()
        self._done_times: Deque[float] = deque(maxlen=256)
        # migrated requests waiting for KV adoption — drained at the
        # START of each tick, on the loop thread (cache writes must
        # never race a decode step's array swap)
        self._pending_handoffs: Deque[Request] = deque()
        # TTFT/TPOT children resolved once: phase is structural (TTFT
        # ends prefill, TPOT is decode cadence), role is this engine's
        self.role = getattr(engine, "role", "colocated")
        self._ttft_hist = smetrics.m_ttft_ms.labels("prefill", self.role)
        self._tpot_hist = smetrics.m_tpot_ms.labels("decode", self.role)

    # ------------------------------------------------------------------
    # producer side (any thread)
    # ------------------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               timeout_s: Optional[float] = None,
               sampling: Optional[SamplingParams] = None,
               prefill_only: bool = False,
               prefix_blob: Optional[dict] = None,
               trace_ctx: Optional[_spans.Context] = None) -> Request:
        """Enqueue a request; raises QueueFullError on backpressure,
        PromptTooLongError for prompts above the bucket ladder, and
        RuntimeError once draining.

        ``prefill_only=True`` (disaggregated serving) stops the request
        at the first-token boundary: its KV state is serialized into
        ``req.handoff`` and the slot is released — the caller migrates
        the payload to a decode replica via :meth:`submit_handoff`.
        ``prefix_blob`` is a gang-shared prefix record adopted into the
        local pool right before prefill (best-effort).

        ``trace_ctx`` (ISSUE 18) joins this request to an existing trace
        — (trace_id, parent_span) extracted from the wire — instead of
        rooting a fresh one."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        # validate against the ladder NOW so the caller gets a 400, not a
        # request that dies at admission time
        self.engine.bucket_for(len(prompt))
        max_new = max(1, min(int(max_new_tokens),
                             self.cfg.max_new_tokens_cap))
        timeout = (self.cfg.default_timeout_s if timeout_s is None
                   else float(timeout_s))
        kw = {}
        if trace_ctx is not None:
            kw = {"trace_id": int(trace_ctx[0]),
                  "parent_span": int(trace_ctx[1])}
        req = Request(prompt=prompt, max_new_tokens=max_new,
                      deadline=time.monotonic() + timeout,
                      sampling=sampling or GREEDY,
                      prefill_only=bool(prefill_only),
                      prefix_blob=prefix_blob, **kw)
        with self._lock:
            if self._refusing is not None:
                raise RuntimeError(self._refusing)
            if self._draining:
                raise RuntimeError("scheduler is draining")
            if len(self._queue) >= self.cfg.max_queue:
                raise QueueFullError(
                    f"admission queue at capacity ({self.cfg.max_queue})")
            self._queue.append(req)
            smetrics.m_queue_depth.set(len(self._queue))
        # open-sentinel root span (dur 0, attrs.open; superseded by the
        # full "serve/request" record in _finish): a process SIGKILLed
        # mid-request has already flushed its children's parent to disk,
        # so the partial trace still stitches orphan-free
        _spans.record("serve/request", req.submit_ns, 0,
                      trace=req.trace_id, parent=req.parent_span,
                      span_id=req.root_span, attrs={"open": True})
        return req

    def submit_handoff(self, handoff: dict, first_token: int,
                       max_new_tokens: int = 16,
                       timeout_s: Optional[float] = None,
                       sampling: Optional[SamplingParams] = None,
                       prompt: Optional[Sequence[int]] = None,
                       trace_ctx: Optional[_spans.Context] = None
                       ) -> Request:
        """Enqueue a MIGRATED request (disaggregated serving): the
        prefill replica already produced ``first_token`` and serialized
        its KV into ``handoff``; this scheduler adopts the payload at
        the start of its next tick and decodes from there. The request
        is seeded with the first token so finish counting and greedy
        output match the colocated path bit-for-bit."""
        prompt = [int(t) for t in
                  (prompt if prompt is not None
                   else (handoff.get("tokens") or []))]
        if not prompt:
            raise ValueError("handoff carries no prompt tokens — "
                             "preemption resume would be impossible")
        max_new = max(1, min(int(max_new_tokens),
                             self.cfg.max_new_tokens_cap))
        timeout = (self.cfg.default_timeout_s if timeout_s is None
                   else float(timeout_s))
        if trace_ctx is None:
            # the handoff frame itself carries the originating trace
            # (kv_transfer stamps it at export) — adopt it so the decode
            # half of a migrated request lands in the SAME trace
            trace_ctx = _spans.extract(handoff)
        kw = {}
        if trace_ctx is not None:
            kw = {"trace_id": int(trace_ctx[0]),
                  "parent_span": int(trace_ctx[1])}
        req = Request(prompt=prompt, max_new_tokens=max_new,
                      deadline=time.monotonic() + timeout,
                      sampling=sampling or GREEDY, handoff=handoff,
                      **kw)
        req.tokens.append(int(first_token))
        req.token_times.append(time.monotonic())
        with self._lock:
            if self._refusing is not None:
                raise RuntimeError(self._refusing)
            if self._draining:
                raise RuntimeError("scheduler is draining")
            if len(self._pending_handoffs) >= self.cfg.max_queue:
                raise QueueFullError(
                    f"handoff queue at capacity ({self.cfg.max_queue})")
            self._pending_handoffs.append(req)
        # same open-sentinel contract as submit(): the decode half of a
        # migrated request leaves its root on disk at admission
        _spans.record("serve/request", req.submit_ns, 0,
                      trace=req.trace_id, parent=req.parent_span,
                      span_id=req.root_span, attrs={"open": True})
        return req

    def cancel(self, req: Request) -> bool:
        """Cancel a QUEUED request (active ones finish their current
        token and are evicted by deadline instead)."""
        with self._lock:
            if req.state == QUEUED and req in self._queue:
                self._queue.remove(req)
                smetrics.m_queue_depth.set(len(self._queue))
                self._finish(req, CANCELLED)
                return True
        return False

    # ------------------------------------------------------------------
    # loop side (one thread)
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One serving tick: evict -> admit -> decode. Returns True when
        any work happened (False = idle, the loop may sleep)."""
        now = time.monotonic()
        self._expire_queued(now)
        ingested = self._ingest_handoffs(now)
        admitted = self._admit(now)
        decoded = self._decode(now)
        self.steps += 1
        occ = self.engine.cache.occupancy
        self.occupancy_sum += occ
        smetrics.m_occupancy.set(occ)
        smetrics.m_active.set(len(self._active))
        return bool(ingested or admitted or decoded)

    def _ingest_handoffs(self, now: float) -> int:
        """Adopt migrated requests' KV payloads into the cache — at the
        tick START, on the loop thread, because adoption swaps the cache
        arrays and must never race a decode step doing the same."""
        n = 0
        while True:
            with self._lock:
                if not self._pending_handoffs:
                    break
                req = self._pending_handoffs[0]
            if req.deadline <= now:
                with self._lock:
                    self._pending_handoffs.popleft()
                self._finish(req, EXPIRED,
                             "deadline exceeded before KV adoption")
                continue
            try:
                with _spans.default_tracer().context(
                        (req.trace_id, req.root_span)):
                    slot = self.engine.adopt_request_kv(req.handoff)
            except (CacheFullError, PagePoolFullError):
                break              # slot/pool pressure — retry next tick
            except Exception as e:
                with self._lock:
                    self._pending_handoffs.popleft()
                self._finish(req, FAILED, f"{type(e).__name__}: {e}")
                continue
            with self._lock:
                self._pending_handoffs.popleft()
            req.handoff = None
            req.state = ACTIVE
            req.slot = slot
            self._active[slot] = req
            self._next_token[slot] = req.tokens[-1]
            self._admit_order.append(slot)
            n += 1
        return n

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Stop admitting new requests and run the loop until every
        queued+active request finished (or the timeout hits). Returns
        True when fully drained."""
        with self._lock:
            self._draining = True
        end = time.monotonic() + timeout_s
        # drain wall time is its own goodput category: the engine is
        # finishing old work but admitting nothing
        with _goodput.timer("drain"):
            while time.monotonic() < end:
                with self._lock:
                    idle = (not self._queue and not self._active
                            and not self._pending_handoffs)
                if idle:
                    return True
                self.step()
        return False

    def abort_all(self, reason: str, refuse_new: bool = False) -> int:
        """Fail every queued and active request (the loop's fault path —
        a step() exception must not leave waiters hanging on events that
        will never fire). Slots are freed; returns how many requests were
        failed.

        ``refuse_new=True`` (the poisoned-engine fail-fast path) also
        flips the scheduler into refusal: the flag is set under the lock
        BEFORE the queue is drained, so a ``submit`` racing this call
        either lands in the drained snapshot (and is failed here) or
        raises the refusal error — it can never be parked on a queue no
        step will ever serve again."""
        with self._lock:
            if refuse_new:
                self._refusing = reason
            queued = list(self._queue)
            self._queue.clear()
            queued += list(self._pending_handoffs)
            self._pending_handoffs.clear()
            smetrics.m_queue_depth.set(0)
        n = 0
        for slot in list(self._active):
            self._evict(slot, FAILED, reason)
            n += 1
        for req in queued:
            self._finish(req, FAILED, reason)
            n += 1
        smetrics.m_active.set(0)
        return n

    @property
    def refusing(self) -> Optional[str]:
        return self._refusing

    @property
    def draining(self) -> bool:
        return self._draining

    def pending(self) -> int:
        with self._lock:
            return (len(self._queue) + len(self._active)
                    + len(self._pending_handoffs))

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def mean_occupancy(self) -> float:
        return self.occupancy_sum / self.steps if self.steps else 0.0

    # ------------------------------------------------------------------
    # measured drain rate -> deadline-aware shedding / Retry-After
    # (docs/serving.md "Resilience": the front door rejects requests
    # whose queue-drain ETA already exceeds their deadline, and tells
    # the client when to come back instead of a flat 429)
    # ------------------------------------------------------------------
    def drain_rate(self, window_s: float = 10.0) -> Optional[float]:
        """Terminal requests per second over the trailing window — the
        rate the admission queue is actually draining at. None until two
        requests have finished (no measurable rate yet)."""
        now = time.monotonic()
        with self._rate_lock:
            recent = [t for t in self._done_times if t >= now - window_s]
        if len(recent) < 2:
            return None
        span = max(now - recent[0], 1e-6)
        return len(recent) / span

    def queue_eta_s(self) -> Optional[float]:
        """Estimated seconds until a request submitted NOW reaches a
        decode slot: queue depth over the measured drain rate. 0.0 for an
        empty queue; None when the rate is not yet measurable."""
        with self._lock:
            depth = len(self._queue)
        if depth == 0:
            return 0.0
        rate = self.drain_rate()
        if rate is None or rate <= 0:
            return None
        return depth / rate

    def retry_after_s(self, cap_s: float = 60.0) -> int:
        """Whole seconds a shed client should wait before retrying,
        from the measured drain rate (>= 1; capped)."""
        eta = self.queue_eta_s()
        if eta is None:
            return 1
        return int(min(max(1.0, np.ceil(eta)), cap_s))

    # ------------------------------------------------------------------
    def _expire_queued(self, now: float) -> None:
        with self._lock:
            keep: Deque[Request] = deque()
            for req in self._queue:
                if req.deadline <= now:
                    self._finish(req, EXPIRED,
                                 "deadline exceeded while queued")
                else:
                    keep.append(req)
            self._queue = keep
            smetrics.m_queue_depth.set(len(self._queue))

    def _pop_admissible(self) -> Optional[Request]:
        """FIFO pop with bounded head-of-line bypass: the first request
        whose prompt fits the current slot/page budget. A head bypassed
        past the starvation limit pins the queue until it fits."""
        with self._lock:
            if not self._queue:
                return None
            head = self._queue[0]
            for i, req in enumerate(self._queue):
                if i > 0 and head.hol_skips >= self.cfg.hol_starvation_limit:
                    return None       # head pinned: wait for its budget
                if self.engine.can_admit(len(req.gen_prompt())):
                    del self._queue[i]
                    smetrics.m_queue_depth.set(len(self._queue))
                    if i > 0:
                        head.hol_skips += 1
                        smetrics.m_hol_admits.inc()
                    return req
            return None

    def _admit(self, now: float) -> int:
        """Prefill queued requests into free slots — FIFO with the
        head-of-line bypass above."""
        admitted = 0
        while self.engine.cache.free_slot_count() > 0:
            req = self._pop_admissible()
            if req is None:
                break
            t_admit = time.perf_counter_ns()
            if req.prefix_blob is not None:
                # gang-shared prefix record: adopt into the local pool
                # first so the prefill below hits instead of recomputing.
                # Best-effort — any failure just means a cold prefill.
                blob, req.prefix_blob = req.prefix_blob, None
                try:
                    from .kv_transfer import adopt_prefix

                    adopt_prefix(self.engine, blob)
                except Exception:
                    pass
            try:
                # prefill runs inside the request's span context so the
                # engine's serve/prefill span parents under its root
                with _spans.default_tracer().context(
                        (req.trace_id, req.root_span)):
                    if req.preempted:
                        # recompute resume: may exceed the ladder — the
                        # engine chunk-replays the known stream
                        slot, logits, first = \
                            self.engine.resume_sequence_sampled(
                                req.gen_prompt(), req.sampling)
                    else:
                        slot, logits, first = \
                            self.engine.start_sequence_sampled(
                                req.gen_prompt(), req.sampling)
            except (CacheFullError, PagePoolFullError):
                # raced headroom / pool pressure — requeue in order
                with self._lock:
                    self._queue.appendleft(req)
                break
            except Exception as e:
                self._finish(req, FAILED, f"{type(e).__name__}: {e}")
                continue
            # queue wait: submit -> prefill start (span + histogram)
            smetrics.m_queue_wait_ms.observe(
                (t_admit - req.submit_ns) / 1e6)
            _spans.record("serve/queue_wait", req.submit_ns,
                          t_admit - req.submit_ns,
                          trace=req.trace_id, parent=req.root_span)
            t = time.monotonic()
            req.state = ACTIVE
            req.slot = slot
            resumed = req.preempted
            req.preempted = False
            if not resumed:
                req.tokens.append(int(first))
                req.token_times.append(t)
                req.ttft_ms = (t - req.submitted) * 1e3
                self._ttft_hist.observe(req.ttft_ms)
                self.engine.note_tokens(1)
                last = int(first)
            else:
                # resumed prefill covered prompt+generated; the sampled
                # continuation token is the next output token
                req.tokens.append(int(first))
                req.token_times.append(t)
                last = int(first)
            if req.prefill_only:
                # first-token boundary of a disaggregated request:
                # serialize the prompt's KV here on the loop thread
                # (the only context allowed to touch the cache arrays),
                # release the slot, and finish — the router migrates
                # req.handoff to a decode replica
                self._active[slot] = req
                admitted += 1
                try:
                    req.handoff = self.engine.export_request_kv(
                        slot, tokens=req.prompt)
                    # the handoff frame carries the trace so the decode
                    # replica's subtree lands in the SAME trace whether
                    # it arrives over the socket channel or inline
                    req.handoff[_spans.WIRE_KEY] = _spans.inject(
                        (req.trace_id, req.root_span))
                except Exception as e:
                    self._evict(slot, FAILED,
                                f"{type(e).__name__}: {e}")
                    continue
                self._evict(slot, DONE, reason="handoff")
                continue
            self._active[slot] = req
            self._next_token[slot] = last
            self._admit_order.append(slot)
            admitted += 1
            if self._should_finish(req, last):
                self._evict(slot, DONE)
            elif self.engine.cache.headroom(slot) < getattr(
                    self.engine, "min_headroom", 1):
                # prompt filled the slot to (near) max_seq: the prefill
                # logits already produced the one token that fits, and
                # the next generation step could not run — finish here
                self._evict(slot, DONE, "max_seq reached",
                            reason="max_seq")
        return admitted

    def _preempt_youngest(self, exclude_slot: Optional[int] = None) -> bool:
        """Free the most recently admitted active request's pages and
        requeue it at the queue head for recompute-resume. Returns False
        when there is nothing (else) to preempt."""
        for slot in reversed(self._admit_order):
            if slot == exclude_slot or slot not in self._active:
                continue
            req = self._active.pop(slot)
            self._next_token.pop(slot, None)
            self._admit_order.remove(slot)
            self.engine.free_sequence(slot)
            req.state = QUEUED
            req.slot = None
            req.preempted = True
            smetrics.m_preemptions.labels("page_pool").inc()
            self.preemptions += 1
            with self._lock:
                self._queue.appendleft(req)
                smetrics.m_queue_depth.set(len(self._queue))
            return True
        return False

    def _ensure_step_capacity(self) -> None:
        """Paged engines: map the pages this tick will write BEFORE the
        batched call; preempt the youngest request(s) while the pool
        cannot cover a slot."""
        for slot in sorted(self._active, key=self._admit_order.index):
            if slot not in self._active:      # preempted by an earlier
                continue                      # iteration's pool squeeze
            while not self.engine.ensure_decode_capacity(slot):
                if not self._preempt_youngest(exclude_slot=slot):
                    # nothing left to preempt: this request alone
                    # exceeds the pool — fail it rather than livelock
                    self._evict(slot, FAILED,
                                "KV page pool exhausted", reason="failed")
                    break

    def _decode(self, now: float) -> bool:
        # evict deadline-blown active requests at the token boundary
        for slot in list(self._active):
            req = self._active[slot]
            if req.deadline <= now:
                self._evict(slot, EXPIRED,
                            "deadline exceeded mid-generation")
        if not self._active:
            return False
        self._ensure_step_capacity()
        if not self._active:
            return False
        feed = {slot: self._next_token[slot] for slot in self._active}
        params = {slot: self._active[slot].sampling
                  for slot in self._active}
        t_tick0 = time.perf_counter_ns()
        out = self.engine.generate_step(feed, params)
        tick_ns = time.perf_counter_ns() - t_tick0
        t = time.monotonic()
        trace_on = _spans.tracing_enabled()
        for slot, emitted in out.items():
            req = self._active.get(slot)
            if req is None:
                continue
            if trace_on:
                # per-tick decode span on the request's trace: the whole
                # batch shares one executable call, so every rider gets
                # the tick's wall time (batch size + emitted count in
                # the attrs — speculative ticks emit several)
                _spans.record("serve/decode_tick", t_tick0, tick_ns,
                              trace=req.trace_id, parent=req.root_span,
                              attrs={"batch": len(out),
                                     "emitted": len(emitted),
                                     "token_index": len(req.tokens)})
            finished = False
            for tok in emitted:
                tok = int(tok)
                req.tokens.append(tok)
                if req.token_times:
                    self._tpot_hist.observe(
                        (t - req.token_times[-1]) * 1e3)
                req.token_times.append(t)
                self._next_token[slot] = tok
                if self._should_finish(req, tok):
                    self._evict(slot, DONE)
                    finished = True
                    break
            if not finished and self.engine.cache.headroom(slot) < getattr(
                    self.engine, "min_headroom", 1):
                self._evict(slot, DONE, "max_seq reached",
                            reason="max_seq")
        return True

    def _should_finish(self, req: Request, last_token: int) -> bool:
        eos = self.engine.ecfg.eos_id
        if eos is not None and last_token == eos:
            return True
        return len(req.tokens) >= req.max_new_tokens

    _EVICT_REASONS = {DONE: "done", EXPIRED: "deadline", FAILED: "failed"}

    def _evict(self, slot: int, state: str,
               detail: Optional[str] = None,
               reason: Optional[str] = None) -> None:
        req = self._active.pop(slot)
        self._next_token.pop(slot, None)
        if slot in self._admit_order:
            self._admit_order.remove(slot)
        t0 = time.perf_counter_ns()
        self.engine.free_sequence(slot)
        reason = reason or self._EVICT_REASONS.get(state, state)
        smetrics.m_evictions.labels(reason).inc()
        _spans.record("serve/evict", t0, time.perf_counter_ns() - t0,
                      trace=req.trace_id, parent=req.root_span,
                      attrs={"reason": reason, "slot": slot})
        self._finish(req, state, detail)

    def _finish(self, req: Request, state: str,
                detail: Optional[str] = None) -> None:
        req.state = state
        if detail and state in (EXPIRED, FAILED):
            req.error = detail
        if state == DONE:
            self.completed += 1
        with self._rate_lock:
            self._done_times.append(time.monotonic())
        # close the request's root span: submit -> terminal state.  The
        # explicit span_id is what the lifecycle children parented to;
        # parent_span (when the request arrived with wire trace context)
        # links this process's subtree under the sender's span.
        end = time.perf_counter_ns()
        _spans.record("serve/request", req.submit_ns,
                      end - req.submit_ns, trace=req.trace_id,
                      parent=req.parent_span, span_id=req.root_span,
                      attrs={"state": state, "tokens": len(req.tokens),
                             "request_id": req.id})
        req.finished.set()