"""Phase-disaggregated serving: in-process replica set + router
(ISSUE 17, docs/serving.md "Disaggregation").

Prefill is compute-bound and bursty; decode is HBM-bound and steady.
Colocated, they fight over the same chip — a long prompt's prefill
stalls every rider's decode tick, which is exactly the p99-TTFT/TPOT
interference the disagg split removes. This module is the in-process
form of the split (one Python process, one engine per role), used by
``tools/serve_bench.py --disagg``, the parity tests, and as the
reference implementation of the router policy the subprocess gang
(serving/gang.py) mirrors over HTTP:

- :class:`LocalReplica` — engine + scheduler + serving loop with the
  engine's role stamped on it;
- :class:`SharedPrefixIndex` — the pool-level prefix cache: a
  gang-shared, token-hash-keyed index of serialized prefix pages, so a
  system prompt prefilled on ANY replica is adoptable by all (metered
  per phase by ``paddle_serve_pool_prefix_cache_total{event,phase}``);
- :class:`DisaggRouter` — queue-depth + drain-rate placement per role,
  first-token migration over serving/kv_transfer.py, and the
  degrade-never-drop rule: an empty phase fleet or a failed handoff
  falls back to colocated dispatch
  (``paddle_serve_disagg_fallback_total{reason}``).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..observability import spans as _spans
from . import metrics as smetrics
from .kv_transfer import export_prefix
from .sampling import SamplingParams
from .scheduler import Scheduler, SchedulerConfig

__all__ = ["LocalReplica", "SharedPrefixIndex", "DisaggRouter",
           "DisaggResult"]


class SharedPrefixIndex:
    """Gang-shared prefix index: token-hash -> serialized prefix pages
    (kv_transfer blob). Plugs into an engine's ``prefix_store`` slot
    (duck-typed — the engine only calls ``maybe_publish``), so every
    prefill publish lands here as well as in the replica-local cache;
    consumers :meth:`fetch` the longest blob for a prompt and hand it
    to ``Scheduler.submit(prefix_blob=...)`` for pool adoption."""

    def __init__(self, max_records: int = 256):
        self.max_records = int(max_records)
        self._lock = threading.Lock()
        # insertion-ordered key -> blob (LRU-ish: re-publish refreshes)
        self._blobs: "Dict[Tuple[int, ...], Dict[str, Any]]" = {}
        self.hits = 0
        self.misses = 0
        self.published = 0

    def binding(self, role: str) -> "_IndexBinding":
        """A phase-stamping adapter suitable as ``engine.prefix_store``."""
        return _IndexBinding(self, role)

    def publish(self, tokens: Sequence[int], table_row, pool,
                phase: str = "colocated") -> bool:
        blob = export_prefix(pool, tokens, table_row)
        if blob is None:
            return False
        key = tuple(blob["tokens"])
        with self._lock:
            if key in self._blobs:
                return False
            self._blobs[key] = blob
            while len(self._blobs) > self.max_records:
                self._blobs.pop(next(iter(self._blobs)))
            self.published += 1
        smetrics.m_pool_prefix.labels("publish", phase).inc()
        return True

    def fetch(self, tokens: Sequence[int],
              phase: str = "colocated") -> Optional[Dict[str, Any]]:
        """Longest indexed page-aligned prefix of ``tokens`` that
        leaves at least one suffix token to prefill. Counts hit/miss
        per phase."""
        tokens = [int(t) for t in tokens]
        with self._lock:
            if not self._blobs:
                best = None
            else:
                best = None
                for key, blob in self._blobs.items():
                    n = len(key)
                    if (n < len(tokens) and tuple(tokens[:n]) == key
                            and (best is None
                                 or n > len(best["tokens"]))):
                        best = blob
        if best is None:
            self.misses += 1
            smetrics.m_pool_prefix.labels("miss", phase).inc()
            return None
        self.hits += 1
        smetrics.m_pool_prefix.labels("hit", phase).inc()
        return best

    def __len__(self) -> int:
        with self._lock:
            return len(self._blobs)


class _IndexBinding:
    """One replica's view of the shared index — stamps its role on the
    publish metric and satisfies the engine's prefix_store duck type."""

    def __init__(self, index: SharedPrefixIndex, role: str):
        self.index = index
        self.role = role

    def maybe_publish(self, tokens, table_row, pool) -> bool:
        return self.index.publish(tokens, table_row, pool,
                                  phase=self.role)


class LocalReplica:
    """One in-process serving replica: engine + continuous-batching
    scheduler + loop thread, with the engine's role on the tin."""

    def __init__(self, engine, scfg: Optional[SchedulerConfig] = None,
                 prefix_index: Optional[SharedPrefixIndex] = None,
                 name: Optional[str] = None):
        from .server import EngineLoop

        self.engine = engine
        self.role = getattr(engine, "role", "colocated")
        self.name = name or f"{self.role}-{id(engine) & 0xffff:x}"
        self.scheduler = Scheduler(engine, scfg)
        self.prefix_index = prefix_index
        if (prefix_index is not None and getattr(engine, "paged", False)
                and engine.prefix is not None
                and engine.prefix_store is None):
            engine.prefix_store = prefix_index.binding(self.role)
        self.loop = EngineLoop(self.scheduler).start()

    def wake(self) -> None:
        self.loop.wake()

    def stop(self) -> None:
        self.loop.stop()

    # -- placement signals (queue-depth + drain-rate policy) -----------
    def load_eta_s(self) -> float:
        """Placement score: seconds of work already committed here —
        queued + active over the measured drain rate (depth itself when
        no rate is measurable yet, so cold replicas still spread)."""
        sched = self.scheduler
        with sched._lock:
            depth = len(sched._queue) + len(sched._pending_handoffs)
        depth += len(sched._active)
        rate = sched.drain_rate()
        if rate is None or rate <= 0:
            return float(depth)
        return depth / rate


class DisaggResult:
    """What the router hands back — enough for parity checks (tokens)
    and latency accounting (prefill-side TTFT, decode-side cadence)."""

    __slots__ = ("tokens", "ttft_ms", "token_times", "state", "error",
                 "migrated", "fallback_reason", "handoff_ms", "trace_id")

    def __init__(self, tokens, ttft_ms, token_times, state,
                 error=None, migrated=False, fallback_reason=None,
                 handoff_ms=None, trace_id=None):
        self.tokens = tokens
        self.ttft_ms = ttft_ms
        self.token_times = token_times
        self.state = state
        self.error = error
        self.migrated = migrated
        self.fallback_reason = fallback_reason
        self.handoff_ms = handoff_ms
        self.trace_id = trace_id

    @property
    def tpot_ms(self) -> Optional[float]:
        if len(self.token_times) < 2:
            return None
        import numpy as np

        return float(np.mean(np.diff(self.token_times)) * 1e3)


class DisaggRouter:
    """Routes a request prefill-replica -> decode-replica at the
    first-token boundary; any failure degrades to colocated dispatch on
    whatever fleet can still serve (never drops)."""

    def __init__(self, replicas: Sequence[LocalReplica],
                 prefix_index: Optional[SharedPrefixIndex] = None):
        self.replicas = list(replicas)
        self.prefill_fleet = [r for r in self.replicas
                              if r.role == "prefill"]
        self.decode_fleet = [r for r in self.replicas
                             if r.role == "decode"]
        self.colocated_fleet = [r for r in self.replicas
                                if r.role == "colocated"]
        self.prefix_index = prefix_index
        self.migrated = 0
        self.fallbacks = 0

    @staticmethod
    def _pick(fleet: Sequence[LocalReplica]) -> LocalReplica:
        return min(fleet, key=lambda r: r.load_eta_s())

    def _fallback_fleet(self) -> List[LocalReplica]:
        # colocated replicas first; else any full engine can serve both
        # phases (roles are routing policy, not capability)
        return self.colocated_fleet or (self.decode_fleet
                                        + self.prefill_fleet)

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 timeout_s: float = 30.0,
                 sampling: Optional[SamplingParams] = None,
                 trace_ctx: Optional[_spans.Context] = None
                 ) -> DisaggResult:
        """Serve one request end to end (blocking — callers thread)."""
        deadline = time.monotonic() + timeout_s
        # ISSUE 18: one trace per routed request — prefill, KV handoff,
        # decode, AND the colocated fallback all inherit the context
        # minted here (a degraded request is the same trace, not a new
        # one)
        trace_id = trace_ctx[0] if trace_ctx is not None \
            else _spans.gen_id()
        route_span = _spans.gen_id()
        ctx = (trace_id, route_span)
        t0 = time.perf_counter_ns()
        try:
            res = self._generate(prompt, max_new_tokens, deadline,
                                 sampling, ctx)
        finally:
            attrs = {"router": "disagg"}
            if trace_ctx is not None:
                attrs["remote_parent"] = True
            _spans.record(
                "serve/route", t0, time.perf_counter_ns() - t0,
                trace=trace_id, span_id=route_span,
                parent=trace_ctx[1] if trace_ctx is not None else None,
                attrs=attrs)
        res.trace_id = trace_id
        return res

    def _generate(self, prompt, max_new_tokens, deadline, sampling,
                  ctx: _spans.Context) -> DisaggResult:
        if not self.prefill_fleet or not self.decode_fleet:
            return self._colocated(prompt, max_new_tokens, deadline,
                                   sampling, "no_phase_fleet", ctx)
        # -- phase 1: prefill to the first token -----------------------
        pr = self._pick(self.prefill_fleet)
        blob = (self.prefix_index.fetch(prompt, "prefill")
                if self.prefix_index is not None else None)
        try:
            preq = pr.scheduler.submit(
                prompt, max_new_tokens=max_new_tokens,
                timeout_s=max(0.1, deadline - time.monotonic()),
                sampling=sampling, prefill_only=True, prefix_blob=blob,
                trace_ctx=ctx)
        except Exception:
            return self._colocated(prompt, max_new_tokens, deadline,
                                   sampling, "prefill_refused", ctx)
        pr.wake()
        preq.wait(timeout=max(0.1, deadline - time.monotonic()) + 1.0)
        if preq.state != "done" or preq.handoff is None:
            return self._colocated(prompt, max_new_tokens, deadline,
                                   sampling, "prefill_failed", ctx)
        first = preq.tokens[0]
        if max_new_tokens <= 1:
            self.migrated += 1       # nothing left to decode
            return DisaggResult([first], preq.ttft_ms,
                                list(preq.token_times), "done",
                                migrated=True, handoff_ms=0.0)
        # -- phase 2: migrate KV, decode the rest ----------------------
        t_h0 = time.monotonic()
        dr = self._pick(self.decode_fleet)
        try:
            dreq = dr.scheduler.submit_handoff(
                preq.handoff, first, max_new_tokens=max_new_tokens,
                timeout_s=max(0.1, deadline - time.monotonic()),
                sampling=sampling, prompt=prompt)
        except Exception:
            return self._colocated(prompt, max_new_tokens, deadline,
                                   sampling, "handoff_refused", ctx)
        dr.wake()
        dreq.wait(timeout=max(0.1, deadline - time.monotonic()) + 1.0)
        if dreq.state != "done":
            return self._colocated(prompt, max_new_tokens, deadline,
                                   sampling, "decode_failed", ctx)
        handoff_ms = ((dreq.token_times[1] - t_h0) * 1e3
                      if len(dreq.token_times) > 1 else 0.0)
        self.migrated += 1
        return DisaggResult(list(dreq.tokens), preq.ttft_ms,
                            list(dreq.token_times), "done",
                            migrated=True, handoff_ms=handoff_ms)

    def _colocated(self, prompt, max_new_tokens, deadline, sampling,
                   reason: str,
                   ctx: Optional[_spans.Context] = None) -> DisaggResult:
        """Degrade, never drop: full re-dispatch on the fallback fleet.
        The retry inherits the original request's trace context — it
        shows up as a child span of the SAME trace (ISSUE 18)."""
        smetrics.m_disagg_fallback.labels(reason).inc()
        self.fallbacks += 1
        fleet = self._fallback_fleet()
        if not fleet:
            return DisaggResult([], None, [], "failed",
                                error="no replica can serve",
                                fallback_reason=reason)
        rep = self._pick(fleet)
        try:
            req = rep.scheduler.submit(
                prompt, max_new_tokens=max_new_tokens,
                timeout_s=max(0.1, deadline - time.monotonic()),
                sampling=sampling, trace_ctx=ctx)
        except Exception as e:
            return DisaggResult([], None, [], "failed",
                                error=f"{type(e).__name__}: {e}",
                                fallback_reason=reason)
        rep.wake()
        req.wait(timeout=max(0.1, deadline - time.monotonic()) + 1.0)
        return DisaggResult(list(req.tokens), req.ttft_ms,
                            list(req.token_times), req.state,
                            error=req.error, fallback_reason=reason)
