"""Replicated serving gang: N engine replicas behind one front door,
with failover, automatic recycle, and idempotent request dispatch
(ISSUE 15, docs/serving.md "Resilience").

The training stack survives worker death through ``parallel/launch.py``'s
supervised gang restarts; this module is the serving twin, built on the
same contracts:

- **Replicas are subprocesses** (``serving/replica.py``), each a full
  engine + scheduler + :class:`FrontDoor` on its own ephemeral port,
  reporting readiness through ``ready.json`` and liveness through a
  heartbeat file (the ``RankHeartbeat`` idea, serving-shaped).
- **Health model**: the supervisor thread watches three signals per
  replica — process exit (43 -> ``hang``, 44 -> ``poisoned``, anything
  else incl. signal death -> ``crash``), the ``/health`` probe (status
  ``poisoned``/``degraded``, or unreachable), and heartbeat staleness
  (a wedged process that still answers TCP). Any of them recycles the
  replica: SIGTERM, grace, SIGKILL, respawn — counted into
  ``paddle_serve_replica_restarts_total{cause}`` while the siblings
  keep serving.
- **Failover with idempotent request ids**: every request carries an id
  (client-supplied ``request_id`` or gang-assigned). A replica dying
  mid-request breaks the forwarded connection; the router discards the
  partial and re-dispatches the SAME request to a sibling — the retry
  re-prefills from scratch (correctness over speed), metered by
  ``paddle_serve_failover_requests_total``. A completed id is cached, so
  a client retry of an answered request returns the recorded response —
  never a second generation; a duplicate arriving while the first is in
  flight waits for it instead of racing it. A client therefore never
  sees a lost or double-answered request.
- **Warm restart**: each replica slot owns a persistent prefix store
  directory (``serving/prefix_store.py``); a recycled replica restores
  its published prefix pages on boot and serves shared-prefix traffic
  prefill-once from its first request.
- **Phase disaggregation** (ISSUE 17): ``GangConfig.roles`` types each
  slot ``prefill``/``decode``/``colocated``. With both phase fleets
  present, ``/generate`` dispatch runs phased — prefill replica to the
  first token, KV pages streamed to a decode replica
  (``serving/kv_transfer.py`` socket channel; inline JSON for stubs),
  decode continues there. Any phase failure (empty fleet, transfer
  fault, replica death mid-handoff) degrades the SAME request to
  classic colocated dispatch — counted in
  ``paddle_serve_disagg_fallback_total{reason}``, never dropped, and
  still idempotent under the request-id contract.

TPU caveat: replicas are separate processes — on a TPU host each must be
pinned to its own chip subset (``TPU_VISIBLE_DEVICES`` per replica, see
tools/run_tpu_session8.sh); the committed bench lanes are the CPU smoke
surface.
"""
from __future__ import annotations

import dataclasses
import itertools
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, List, Optional, Tuple

from ..observability import fleet as _fleet
from ..observability import slo as _slo
from ..observability import spans as _spans
from ..parallel import health as _health
from . import metrics as smetrics
from .replica import HEARTBEAT_NAME, POISONED_EXIT_CODE, READY_NAME

__all__ = ["GangConfig", "ReplicaGang", "ReplicaHandle", "GangFrontDoor"]

_REPLICA_SCRIPT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "replica.py")


def _exit_cause(ret: Optional[int]) -> str:
    """Popen returncode -> restart-cause label. Mirrors
    ``parallel.launch._restart_cause`` with the serving-specific
    poisoned code added."""
    if ret == _health.HANG_EXIT_CODE:
        return "hang"
    if ret == POISONED_EXIT_CODE:
        return "poisoned"
    return "crash"


@dataclasses.dataclass(frozen=True)
class GangConfig:
    n_replicas: int = 2
    # phase disaggregation (ISSUE 17): one role per replica slot
    # ("prefill" | "decode" | "colocated"). Empty = every slot
    # colocated (the pre-disagg gang). When both a prefill and a decode
    # slot are configured, /generate dispatch runs phased: prefill on a
    # prefill replica, KV handoff, decode on a decode replica — any
    # phase failure degrades to classic colocated dispatch (never drops)
    roles: Tuple[str, ...] = ()
    # supervisor probe cadence + the liveness deadline: an unreachable
    # /health or a heartbeat older than hang_deadline_s recycles the
    # replica with cause=hang (the worker's own watchdog usually beats
    # this by exiting 43 first — this is the backstop for a process
    # wedged outside the engine loop)
    probe_interval_s: float = 0.5
    probe_timeout_s: float = 2.0
    hang_deadline_s: float = 10.0
    ready_timeout_s: float = 180.0
    grace_period_s: float = 3.0
    restart_backoff_s: float = 0.2
    max_restarts_per_replica: int = 8
    # failover: how many distinct replica incarnations one request may
    # try before the router gives up with 503
    max_failover_attempts: int = 4
    dedup_capacity: int = 4096
    default_timeout_s: float = 30.0
    # fleet observability (ISSUE 18): supervisor-side poll cadence for
    # the FLEET.json / merged-exposition view, and the bound on the
    # slow-request forensic dir
    fleet_poll_interval_s: float = 2.0
    forensic_keep: int = 16


class ReplicaHandle:
    """One replica slot: the subprocess, its readiness/heartbeat files,
    and restart bookkeeping. A slot survives recycles; the process (and
    its port) changes per incarnation."""

    def __init__(self, index: int, config_path: str, run_dir: str,
                 role: str = "colocated"):
        self.index = int(index)
        self.config_path = config_path
        self.run_dir = run_dir
        self.role = str(role)
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.kv_port: Optional[int] = None   # KV transfer socket (decode)
        self.queue_depth = 0                 # refreshed by /health probes
        self.restored_prefix_records = 0
        self.incarnation = 0
        self.restarts = 0
        self.last_cause: Optional[str] = None
        self.inflight = 0                 # router-side load counter
        self.probe_misses = 0
        self._log = None

    # -- lifecycle ---------------------------------------------------------
    def spawn(self, env: Dict[str, str]) -> None:
        for name in (READY_NAME, HEARTBEAT_NAME):
            try:
                os.remove(os.path.join(self.run_dir, name))
            except OSError:
                pass
        self.port = None
        self.kv_port = None
        self.queue_depth = 0
        self.probe_misses = 0
        self.incarnation += 1
        if self._log is None or self._log.closed:
            self._log = open(os.path.join(self.run_dir, "worker.log"), "a")
        self.proc = subprocess.Popen(
            [sys.executable, _REPLICA_SCRIPT, "--config", self.config_path],
            env=env, stdout=self._log, stderr=subprocess.STDOUT)

    def kill(self, sig=signal.SIGKILL) -> None:
        """Deliver ``sig`` to the current incarnation (fault injection
        and supervisor recycle both come through here)."""
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.send_signal(sig)
            except OSError:
                pass

    def stop(self, grace_s: float) -> None:
        if self.proc is None:
            return
        self.kill(signal.SIGTERM)
        try:
            self.proc.wait(timeout=max(0.1, grace_s))
        except subprocess.TimeoutExpired:
            self.kill(signal.SIGKILL)
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        if self._log is not None and not self._log.closed:
            self._log.close()

    # -- liveness ----------------------------------------------------------
    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def check_ready(self) -> bool:
        """Refresh ``self.port`` from the incarnation's ready file (the
        pid gate rejects a stale file from a killed predecessor)."""
        if self.port is not None:
            return True
        if not self.alive:
            return False
        try:
            with open(os.path.join(self.run_dir, READY_NAME)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return False
        if rec.get("pid") != self.proc.pid:
            return False
        self.port = int(rec["port"])
        kvp = rec.get("kv_port")
        self.kv_port = int(kvp) if kvp else None
        self.restored_prefix_records = int(
            rec.get("restored_prefix_records", 0))
        return True

    def heartbeat_age_s(self) -> Optional[float]:
        try:
            with open(os.path.join(self.run_dir, HEARTBEAT_NAME)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None
        return max(0.0, time.time() - float(rec.get("ts", 0)))

    # -- HTTP --------------------------------------------------------------
    def get_json(self, path: str, timeout_s: float) -> Dict[str, Any]:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.port}{path}",
                timeout=timeout_s) as r:
            return json.loads(r.read().decode())

    def get_text(self, path: str, timeout_s: float = 5.0) -> str:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.port}{path}",
                timeout=timeout_s) as r:
            return r.read().decode()

    def post_json(self, path: str, body: Dict[str, Any],
                  timeout_s: float) -> Tuple[int, Dict[str, Any]]:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                return r.status, json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            # 4xx/5xx with a JSON body is a PROTOCOL answer, not a
            # transport fault — the router decides what to do with it
            try:
                return e.code, json.loads(e.read().decode())
            except ValueError:
                return e.code, {"error": f"HTTP {e.code}"}

    def post_generate(self, body: Dict[str, Any],
                      timeout_s: float) -> Tuple[int, Dict[str, Any]]:
        return self.post_json("/generate", body, timeout_s)


class ReplicaGang:
    """Spawn, supervise, and route over ``n_replicas`` replica workers.

    ``worker_config`` is the shared replica config (model/engine/
    scheduler sections — see serving/replica.py); the gang stamps
    per-slot ``index``/``run_dir``/``prefix_store_dir`` into each
    replica's own config file under ``run_dir``."""

    def __init__(self, worker_config: Dict[str, Any], run_dir: str,
                 cfg: Optional[GangConfig] = None,
                 prefix_store: bool = False,
                 env: Optional[Dict[str, str]] = None,
                 per_replica: Optional[Dict[int, dict]] = None):
        self.cfg = cfg or GangConfig()
        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self._env = dict(os.environ if env is None else env)
        # health env contract (docs/health.md): the worker's engine loop
        # stamps progress; a wedged loop exits 43 on its own
        self._env.setdefault(_health.ENV_DEADLINE,
                             str(float(self.cfg.hang_deadline_s)))
        self._env.setdefault(_health.ENV_DIR,
                             os.path.join(self.run_dir, "health"))
        # ISSUE 18: every process in the gang — supervisor and replicas —
        # appends its spans to its own JSONL under ONE shared trace dir;
        # tools/trace_assemble.py stitches them into per-request timelines
        self.trace_dir = os.path.join(self.run_dir, "trace")
        _spans.attach_process_sink(self.trace_dir, "gang")
        roles = tuple(self.cfg.roles)
        if roles and len(roles) != self.cfg.n_replicas:
            raise ValueError(
                f"GangConfig.roles has {len(roles)} entries for "
                f"{self.cfg.n_replicas} replicas")
        for role in roles:
            if role not in ("prefill", "decode", "colocated"):
                raise ValueError(f"unknown replica role {role!r}")
        self.replicas: List[ReplicaHandle] = []
        for i in range(self.cfg.n_replicas):
            rdir = os.path.join(self.run_dir, f"replica{i}")
            os.makedirs(rdir, exist_ok=True)
            role = roles[i] if roles else "colocated"
            rc = dict(worker_config, index=i, run_dir=rdir, role=role,
                      trace_dir=self.trace_dir)
            if "engine" in rc:
                rc["engine"] = dict(rc["engine"], role=role)
            if role == "decode" and "stub" not in rc:
                # decode engine replicas take KV pushes over the socket
                # channel (stubs ride the handoff inline in JSON)
                rc["kv_server"] = True
            # per-slot overrides (the fault bench injects faults into ONE
            # replica while its siblings stay clean)
            rc.update((per_replica or {}).get(i, {}))
            if prefix_store:
                rc["prefix_store_dir"] = os.path.join(
                    self.run_dir, "prefix_store", f"replica{i}")
            cpath = os.path.join(rdir, "config.json")
            with open(cpath, "w") as f:
                json.dump(rc, f, indent=1)
            self.replicas.append(ReplicaHandle(i, cpath, rdir, role=role))
        self.restart_causes: Dict[str, int] = {}
        self.failovers = 0
        self.disagg_requests = 0          # served via prefill->decode
        self.disagg_fallbacks = 0         # degraded to colocated
        self._rid = itertools.count(1)
        self._dedup_lock = threading.Lock()
        self._completed: "OrderedDict[str, Tuple[int, dict]]" = \
            OrderedDict()
        self._inflight: Dict[str, threading.Event] = {}
        self._rr = itertools.count()      # round-robin tiebreak
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # ISSUE 18: live SLO engine (burn-rate alerting + error-budget
        # ledger surviving warm restarts) fed from dispatch outcomes,
        # and the fleet poller that folds replica /metrics + heartbeats
        # into FLEET.json and the merged /fleet exposition
        self.slo = _slo.SLOEngine(
            ledger_dir=os.path.join(self.run_dir, "slo_ledger"),
            forensics=_slo.ForensicDir(
                os.path.join(self.run_dir, "forensics"),
                keep=self.cfg.forensic_keep),
            state_fn=self.health)
        _slo.set_default_engine(self.slo)
        self.fleet = _fleet.FleetPoller(
            self._collect_fleet,
            out_path=os.path.join(self.run_dir, "FLEET.json"),
            interval_s=self.cfg.fleet_poll_interval_s,
            slo=self.slo)

    # -- lifecycle ---------------------------------------------------------
    def start(self, wait_ready: bool = True) -> "ReplicaGang":
        for r in self.replicas:
            r.spawn(self._env)
        if wait_ready:
            self.wait_ready()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True, name="gang-monitor")
        self._monitor.start()
        self.fleet.start()
        return self

    def wait_ready(self, timeout_s: Optional[float] = None) -> None:
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.cfg.ready_timeout_s)
        while time.monotonic() < deadline:
            pending = [r for r in self.replicas if not r.check_ready()]
            if not pending:
                return
            dead = [r for r in pending if not r.alive]
            for r in dead:
                raise RuntimeError(
                    f"replica {r.index} died during startup "
                    f"(exit {r.proc.returncode}) — see "
                    f"{os.path.join(r.run_dir, 'worker.log')}")
            time.sleep(0.1)
        raise TimeoutError(
            f"replicas {[r.index for r in self.replicas if r.port is None]}"
            f" not ready within {self.cfg.ready_timeout_s}s")

    def stop(self) -> None:
        self._stop.set()
        self.fleet.stop()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        for r in self.replicas:
            r.stop(self.cfg.grace_period_s)
        try:
            self.slo.close()
        except Exception:
            pass

    # -- supervision -------------------------------------------------------
    def _recycle(self, r: ReplicaHandle, cause: str, detail: str) -> None:
        r.last_cause = cause
        r.restarts += 1
        self.restart_causes[cause] = self.restart_causes.get(cause, 0) + 1
        smetrics.m_replica_restarts.labels(cause).inc()
        sys.stderr.write(
            f"[gang] recycling replica {r.index} (cause={cause}: "
            f"{detail}); siblings keep serving\n")
        r.stop(self.cfg.grace_period_s if cause == "poisoned" else 0.2)
        if r.restarts > self.cfg.max_restarts_per_replica:
            sys.stderr.write(
                f"[gang] replica {r.index} exceeded "
                f"{self.cfg.max_restarts_per_replica} restarts — "
                "leaving it down\n")
            return
        time.sleep(self.cfg.restart_backoff_s)
        r.spawn(self._env)

    def _probe(self, r: ReplicaHandle) -> None:
        """One health probe of a ready replica; classifies and recycles
        on poisoned/degraded/unreachable/stale-heartbeat."""
        try:
            h = r.get_json("/health", self.cfg.probe_timeout_s)
            r.probe_misses = 0
        except Exception as e:
            r.probe_misses += 1
            hb = r.heartbeat_age_s()
            if (r.probe_misses * self.cfg.probe_interval_s
                    >= self.cfg.hang_deadline_s) or \
                    (hb is not None and hb >= self.cfg.hang_deadline_s):
                self._recycle(r, "hang",
                              f"/health unreachable x{r.probe_misses}, "
                              f"heartbeat age {hb}: {e}")
            return
        r.queue_depth = int(h.get("queue_depth") or 0)
        status = h.get("status")
        if status == "poisoned":
            self._recycle(r, "poisoned",
                          h.get("engine_poisoned", "engine poisoned"))
        elif status == "degraded":
            self._recycle(r, "crash", "engine loop died (degraded)")
        else:
            hb = r.heartbeat_age_s()
            if hb is not None and hb >= self.cfg.hang_deadline_s:
                self._recycle(r, "hang", f"heartbeat stale ({hb:.1f}s)")

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval_s):
            for r in self.replicas:
                if self._stop.is_set():
                    return
                if r.proc is None:
                    continue
                ret = r.proc.poll()
                if ret is not None:
                    self._recycle(r, _exit_cause(ret),
                                  f"exit code {ret}")
                    continue
                if r.check_ready():
                    self._probe(r)

    # -- fleet view (ISSUE 18) ---------------------------------------------
    def _collect_fleet(self) -> List["_fleet.ReplicaSample"]:
        """One fleet-poll sweep: scrape every ready replica's /metrics
        and heartbeat into :class:`ReplicaSample` rows (the poller turns
        them into FLEET.json + the merged exposition)."""
        samples = []
        for r in self.replicas:
            alive = r.alive
            text = None
            if alive and r.check_ready():
                try:
                    text = r.get_text("/metrics",
                                      timeout_s=self.cfg.probe_timeout_s)
                except Exception:
                    _fleet.m_fleet_scrape_errors.inc()
            samples.append(_fleet.ReplicaSample(
                index=r.index, role=r.role, alive=alive,
                heartbeat_age_s=r.heartbeat_age_s(),
                metrics_text=text, incarnation=r.incarnation,
                inflight=r.inflight))
        return samples

    # -- routing -----------------------------------------------------------
    def ready_replicas(self,
                       role: Optional[str] = None) -> List[ReplicaHandle]:
        return [r for r in self.replicas if r.alive and r.check_ready()
                and (role is None or r.role == role)]

    def _pick(self, exclude,
              role: Optional[str] = None) -> Optional[ReplicaHandle]:
        """Least-loaded ready replica not in ``exclude`` (an (index,
        incarnation) set — a RECYCLED replica is a fresh candidate).
        Load = router-side inflight + the probed queue depth (the
        drain-rate signal a remote scheduler exposes)."""
        cands = [r for r in self.ready_replicas(role)
                 if (r.index, r.incarnation) not in exclude]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.inflight + r.queue_depth,
                                         next(self._rr)))

    @property
    def disaggregated(self) -> bool:
        """Phased dispatch is on when both phase fleets are configured
        (static — role assignment never changes after construction)."""
        roles = [r.role for r in self.replicas]
        return "prefill" in roles and "decode" in roles

    def dispatch(self, body: Dict[str, Any],
                 timeout_s: Optional[float] = None
                 ) -> Tuple[int, Dict[str, Any]]:
        """Route one generate request with failover + idempotency.
        Returns ``(http_code, payload)``."""
        timeout = (self.cfg.default_timeout_s if timeout_s is None
                   else float(timeout_s))
        rid = str(body.get("request_id") or
                  f"gang-{os.getpid()}-{next(self._rid)}")
        # ISSUE 18: ONE trace per request, minted here (or adopted from
        # the client's wire context) and injected into the body BEFORE
        # the failover/disagg machinery — every retry attempt, phase
        # hop, and colocated fallback sends the same context, so a
        # replica scheduler adopts the trace instead of minting a fresh
        # one.  A retry is a child span of the SAME trace, never a new
        # trace (the PR-15 failover test asserts this).
        ctx_in = _spans.extract(body)
        trace_id = ctx_in[0] if ctx_in is not None else _spans.gen_id()
        route_span = _spans.gen_id()
        body = dict(body)
        body[_spans.WIRE_KEY] = _spans.inject((trace_id, route_span))
        t0 = time.perf_counter_ns()
        code, payload = self._dispatch_dedup(body, timeout, rid)
        if isinstance(payload, dict):
            # expose the trace id to the client (and to tests); a dedup
            # hit keeps the ORIGINAL attempt's id — the client retry is
            # part of that trace, not a new one
            payload.setdefault("trace_id", trace_id)
            if not payload.get("deduplicated"):
                try:
                    self.slo.note_request(
                        ttft_ms=payload.get("ttft_ms"),
                        tpot_ms=payload.get("tpot_ms"),
                        code=code, shed=code in (429, 503),
                        trace_id=payload.get("trace_id"),
                        request_id=rid)
                except Exception:
                    pass
        span_trace = (payload.get("trace_id", trace_id)
                      if isinstance(payload, dict) else trace_id)
        attrs = {"request_id": rid, "code": code}
        if ctx_in is not None:
            # the parent span lives in the CLIENT's process, outside
            # this gang's trace dir — trace_assemble treats a stamped
            # remote parent as a legitimate root, not a broken edge
            attrs["remote_parent"] = True
        _spans.record("serve/route", t0, time.perf_counter_ns() - t0,
                      trace=span_trace, span_id=route_span,
                      parent=ctx_in[1] if ctx_in is not None else None,
                      attrs=attrs)
        return code, payload

    def _dispatch_dedup(self, body: Dict[str, Any], timeout: float,
                        rid: str) -> Tuple[int, Dict[str, Any]]:
        with self._dedup_lock:
            hit = self._completed.get(rid)
            if hit is not None:
                # an answered id is never re-generated: the recorded
                # response IS the answer (idempotency contract)
                self._completed.move_to_end(rid)
                return hit[0], dict(hit[1], deduplicated=True)
            ev = self._inflight.get(rid)
            if ev is None:
                ev = threading.Event()
                self._inflight[rid] = ev
                owner = True
            else:
                owner = False
        if not owner:
            # a duplicate of an in-flight request waits for the original
            # instead of racing a second generation
            ev.wait(timeout=timeout + self.cfg.probe_timeout_s)
            with self._dedup_lock:
                hit = self._completed.get(rid)
            if hit is not None:
                return hit[0], dict(hit[1], deduplicated=True)
            return 504, {"error": "duplicate waited out its original",
                         "request_id": rid}
        try:
            code, payload = self._dispatch_phased(body, timeout, rid)
        finally:
            with self._dedup_lock:
                self._inflight.pop(rid, None)
                ev.set()
        return code, payload

    def _record(self, rid: str, code: int,
                payload: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        payload = dict(payload, request_id=rid)
        with self._dedup_lock:
            self._completed[rid] = (code, payload)
            while len(self._completed) > self.cfg.dedup_capacity:
                self._completed.popitem(last=False)
        return code, payload

    def _dispatch_phased(self, body, timeout: float, rid: str):
        """Disaggregated dispatch with the degrade-never-drop rule: try
        prefill-replica -> KV handoff -> decode-replica; ANY phase
        failure falls through to classic colocated dispatch
        (:meth:`_dispatch_inner` picks from every ready replica — roles
        are routing policy, not capability — and only the FINAL response
        is recorded, so idempotency + failover semantics are intact)."""
        if self.disaggregated:
            result = self._dispatch_disagg(body, timeout, rid)
            if result is not None:
                return self._record(rid, *result)
        return self._dispatch_inner(body, timeout, rid)

    def _dispatch_disagg(self, body, timeout: float, rid: str):
        """One phased attempt. Returns ``(code, payload)`` on success,
        ``None`` to signal colocated fallback (reason already counted in
        ``paddle_serve_disagg_fallback_total``)."""
        def fall_back(reason: str, detail: str = ""):
            smetrics.m_disagg_fallback.labels(reason).inc()
            self.disagg_fallbacks += 1
            sys.stderr.write(f"[gang] request {rid}: disagg {reason}"
                             f"{' (' + detail + ')' if detail else ''} — "
                             f"degrading to colocated\n")
            return None

        deadline = time.monotonic() + timeout
        pre = self._pick(set(), role="prefill")
        dec = self._pick(set(), role="decode")
        if pre is None or dec is None:
            return fall_back("no_phase_fleet")
        tid = f"{rid}-kv"
        pbody = {k: v for k, v in body.items()
                 if k not in ("request_id",)}
        pbody["transfer_id"] = tid
        if dec.kv_port:
            # real engines: page stream over the decode replica's KV
            # socket; the prefill replica pushes, /resume pops by id
            pbody["kv_target"] = {"host": "127.0.0.1",
                                  "port": dec.kv_port,
                                  "transfer_id": tid}
        pre.inflight += 1
        try:
            code, pay = pre.post_json(
                "/prefill", pbody, max(0.5, deadline - time.monotonic()))
        except Exception as e:
            return fall_back("transfer_fault",
                             f"prefill: {type(e).__name__}")
        finally:
            pre.inflight -= 1
        if code != 200:
            return fall_back("prefill_failed", f"HTTP {code}")
        rbody = {"first_token": pay["first_token"],
                 "max_new_tokens": body.get("max_new_tokens", 16),
                 "prompt": body.get("prompt") or body.get("tokens"),
                 "timeout_s": max(0.5, deadline - time.monotonic())}
        if _spans.WIRE_KEY in body:
            # decode joins the SAME trace the router minted (the staged
            # handoff also carries the prefill replica's context — both
            # share one trace id)
            rbody[_spans.WIRE_KEY] = body[_spans.WIRE_KEY]
        for k in ("temperature", "top_k", "top_p", "seed"):
            if k in body:
                rbody[k] = body[k]
        if pay.get("kv") is not None:
            rbody["kv"] = pay["kv"]          # inline channel (stubs)
        else:
            rbody["transfer_id"] = pay.get("transfer_id", tid)
        dec.inflight += 1
        try:
            code2, pay2 = dec.post_json(
                "/resume", rbody, max(0.5, deadline - time.monotonic()))
        except Exception as e:
            # mid-transfer decode death: the handoff dies with the
            # replica; the colocated retry re-prefills from the prompt
            return fall_back("transfer_fault",
                             f"resume: {type(e).__name__}")
        finally:
            dec.inflight -= 1
        if code2 != 200:
            return fall_back("decode_failed", f"HTTP {code2}")
        self.disagg_requests += 1
        return 200, {"tokens": pay2["tokens"],
                     "num_tokens": pay2.get("num_tokens",
                                            len(pay2["tokens"])),
                     "ttft_ms": pay.get("ttft_ms"),
                     "tpot_ms": pay2.get("tpot_ms"),
                     "disagg": True}

    def _dispatch_inner(self, body, timeout: float, rid: str):
        deadline = time.monotonic() + timeout + self.cfg.probe_timeout_s
        tried = set()
        shed_response = None
        attempts = 0
        while True:
            r = self._pick(tried)
            if r is None:
                if shed_response is not None:
                    # every replica shed (429/503): surface the shed —
                    # its Retry-After is the client's cue
                    return self._record(rid, *shed_response)
                # nothing healthy right now: a recycle may be in flight —
                # wait for a respawn (a recycled replica has a new
                # incarnation and re-enters the candidate set) rather
                # than failing a whole storm during one restart window
                if time.monotonic() < deadline and not self._stop.is_set():
                    time.sleep(self.cfg.probe_interval_s)
                    continue
                return self._record(rid, 503, {
                    "error": "no healthy replica", "retry_after_s": 1})
            tried.add((r.index, r.incarnation))
            remaining = max(0.5, deadline - time.monotonic())
            r.inflight += 1
            try:
                code, payload = r.post_generate(body, remaining)
            except Exception as e:
                # transport fault: the replica died (or was killed) with
                # this request in flight — its partial tokens die with
                # it; re-dispatch to a sibling, which re-prefills
                attempts += 1
                self.failovers += 1
                smetrics.m_failover.inc()
                sys.stderr.write(
                    f"[gang] request {rid}: replica {r.index} faulted "
                    f"mid-request ({type(e).__name__}) — failing over "
                    f"(attempt {attempts})\n")
                if attempts > self.cfg.max_failover_attempts:
                    return self._record(rid, 503, {
                        "error": f"replica fault after {attempts} "
                                 f"attempts: {type(e).__name__}: {e}",
                        "retry_after_s": 1})
                continue
            finally:
                r.inflight -= 1
            if code == 500:
                # engine-loop fault aborted it server-side: safe to
                # retry on a sibling (nothing was returned)
                attempts += 1
                self.failovers += 1
                smetrics.m_failover.inc()
                if attempts > self.cfg.max_failover_attempts:
                    return self._record(rid, code, payload)
                continue
            if code in (429, 503):
                # overloaded/draining replica: try a sibling; if every
                # replica sheds, surface the shed (with its Retry-After)
                shed_response = (code, payload)
                continue
            return self._record(rid, code, payload)

    # -- introspection -----------------------------------------------------
    def health(self) -> Dict[str, Any]:
        reps = []
        for r in self.replicas:
            reps.append({
                "index": r.index, "alive": r.alive,
                "ready": r.port is not None, "port": r.port,
                "role": r.role, "kv_port": r.kv_port,
                "incarnation": r.incarnation, "restarts": r.restarts,
                "last_cause": r.last_cause,
                "restored_prefix_records": r.restored_prefix_records,
            })
        n_ready = len(self.ready_replicas())
        return {
            "status": ("ok" if n_ready == len(self.replicas) else
                       "degraded" if n_ready else "down"),
            "replicas": reps,
            "ready": n_ready,
            "disaggregated": self.disaggregated,
            "disagg_requests": self.disagg_requests,
            "disagg_fallbacks": self.disagg_fallbacks,
            "restarts": dict(self.restart_causes),
            "failovers": self.failovers,
            "trace_dir": self.trace_dir,
        }


class _GangHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, obj: Dict[str, Any]) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        if "retry_after_s" in obj:
            self.send_header("Retry-After", str(int(obj["retry_after_s"])))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass
        smetrics.request_code(code)

    def do_GET(self):
        front: "GangFrontDoor" = self.server.front
        if self.path == "/health":
            return self._json(200, front.gang.health())
        if self.path == "/metrics":
            from ..observability import prom

            text = prom.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            self.wfile.write(text)
            return
        if self.path in ("/fleet", "/fleet/metrics"):
            # ISSUE 18: the live fleet view — FLEET.json document (with
            # per-role rollups + SLO status) or the merged per-replica
            # exposition (replica/role labels preserved)
            fp = front.gang.fleet
            doc = fp.fleet_doc()
            if not doc:
                doc = fp.tick()
            if self.path == "/fleet":
                return self._json(200, doc)
            text = fp.exposition().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            self.wfile.write(text)
            return
        self._json(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):
        front: "GangFrontDoor" = self.server.front
        if self.path != "/generate":
            return self._json(404, {"error": f"unknown path {self.path!r}"})
        n = int(self.headers.get("Content-Length", 0))
        try:
            body = json.loads(self.rfile.read(n).decode() or "{}")
        except (ValueError, UnicodeDecodeError) as e:
            return self._json(400, {"error": f"malformed JSON body: {e}"})
        timeout_s = body.get("timeout_s")
        code, payload = front.gang.dispatch(
            body, None if timeout_s is None else float(timeout_s))
        self._json(code, payload)


class GangFrontDoor:
    """The gang's public HTTP face: ``/generate`` routes through
    :meth:`ReplicaGang.dispatch` (failover + idempotency), ``/health``
    reports the gang view, ``/metrics`` serves the SUPERVISOR process's
    registry (replica restarts, failovers; each replica's own serving
    metrics live behind its own ``/metrics``)."""

    def __init__(self, gang: ReplicaGang, host: str = "127.0.0.1",
                 port: int = 0):
        self.gang = gang
        from .server import _Server

        self.httpd = _Server((host, port), _GangHandler)
        self.httpd.daemon_threads = True
        self.httpd.front = self
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "GangFrontDoor":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="gang-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
