"""Block-granular paged KV cache + token-hash prefix cache (ISSUE 13,
docs/serving.md).

PR 9's :class:`~paddle_tpu.serving.kv_cache.KVCache` gives every slot a
private ``[max_seq]`` slab — 8 slots x 1024 positions of HBM even when
seven of them hold 12-token chats. This module replaces the slab with a
**page pool**: one preallocated ``[L, num_pages, page_size, nh, hd]``
K/V pair, fixed-size pages handed out from a host-side free list, and a
per-slot **page table** (``[max_pages_per_slot]`` int32 of physical page
ids) that rides into the decode/prefill executables as a plain device
array — so long-context and short-chat traffic share HBM at page
granularity and no shape ever changes (the zero-recompile contract is
untouched).

Layout rules:

- **page 0 is the scratch page** — reserved, never allocated, never
  read. Unmapped page-table entries point at it, so bucket-padding rows
  written past a slot's allocation land harmlessly there instead of
  needing dynamic shapes.
- A slot's pages are mapped in logical order; positions ``< length`` are
  always backed by real pages (``ensure_capacity`` maps the next page at
  the token boundary *before* the decode step that writes into it).
- **Sharing is append-safe by construction**: shared pages are full,
  page-aligned prompt-prefix pages; every write a slot ever performs
  lands at positions ``>= prefix_len``, i.e. in pages it owns alone —
  no copy-on-write machinery needed.

The **prefix cache** keys page-aligned token prefixes by content hash
(exact token match verified — hashes only narrow the lookup): after a
prompt prefill, its full pages are published under every page-boundary
prefix; a later prompt sharing the prefix attaches those pages by
refcount and prefills only its suffix through the continuation-prefill
executable. A shared system prompt therefore prefills ONCE per engine,
metered by ``paddle_serve_prefix_cache_total{hit|miss}``. Entries are
LRU; pool pressure reclaims cache-held pages before any allocation
fails.
"""
from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import metrics as smetrics
from .kv_cache import CacheFullError

__all__ = ["PagedKVCache", "PrefixCache", "PagePoolFullError",
           "TRANSFER_PAGE_BUCKET"]

# Gather/scatter width bucket for the host transfer path
# (:meth:`PagedKVCache.read_pages` / ``write_pages``). Page groups are
# padded up to a multiple of this with the scratch page so every
# ≤-bucket group reuses ONE compiled gather and ONE compiled scatter —
# without it each distinct group size costs a ~100ms XLA compile the
# first time it appears, which lands squarely on the KV-handoff TTFT
# path. KV handoffs chunk at DEFAULT_CHUNK_PAGES == this width, so the
# steady state is exactly one shape.
TRANSFER_PAGE_BUCKET = 4


# K and V move in ONE device call each way — on CPU the per-op dispatch
# overhead (~1ms) dominates these small transfers, so halving the call
# count roughly halves export/adopt latency on the handoff path.
@jax.jit
def _gather_pages_exec(k, v, idx):
    return k[:, idx], v[:, idx]


@jax.jit
def _scatter_pages_exec(k, v, idx, k_pages, v_pages):
    return k.at[:, idx].set(k_pages), v.at[:, idx].set(v_pages)


class PagePoolFullError(RuntimeError):
    """No free page available (after prefix-cache reclaim) — the
    scheduler should defer admission or preempt, not crash."""


@dataclasses.dataclass
class _SlotState:
    live: bool = False
    length: int = 0          # valid prefix length (tokens written)
    prefix_len: int = 0      # leading tokens backed by shared pages
    mapped: int = 0          # logical pages currently mapped
    generation: int = 0


class PagedKVCache:
    """Page-pool allocator + the two pooled cache slabs.

    Drop-in for the slab :class:`KVCache` from the engine's point of view
    (``k``/``v`` device values swapped wholesale per call; ``alloc`` /
    ``free`` / ``length`` / ``headroom`` / ``lengths_vector`` keep their
    contracts) plus the paged surface: per-slot page tables, page-budget
    queries for the scheduler, and refcounts shared with the prefix
    cache."""

    def __init__(self, num_layers: int, max_slots: int, max_seq: int,
                 num_heads: int, head_dim: int, dtype: Any = jnp.float32,
                 page_size: int = 8, num_pages: int = 0):
        if max_slots < 1 or max_seq < 1:
            raise ValueError("max_slots and max_seq must be >= 1")
        if page_size < 1 or max_seq % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq {max_seq}")
        self.num_layers = int(num_layers)
        self.max_slots = int(max_slots)
        self.max_seq = int(max_seq)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.page_size = int(page_size)
        self.max_pages_per_slot = self.max_seq // self.page_size
        # default pool = slab parity (+1 scratch page): same worst case,
        # but pages only bind to slots as sequences actually grow
        self.num_pages = int(num_pages) or (
            self.max_slots * self.max_pages_per_slot + 1)
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is scratch)")
        shape = (self.num_layers, self.num_pages, self.page_size,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self._tables = np.zeros((self.max_slots, self.max_pages_per_slot),
                                np.int32)           # 0 = scratch/unmapped
        self._slots = [_SlotState() for _ in range(self.max_slots)]
        self._free_slots: List[int] = list(range(self.max_slots))
        self._ref = np.zeros((self.num_pages,), np.int64)
        self._ref[0] = 1                             # scratch: pinned
        self._free_pages: List[int] = list(range(1, self.num_pages))
        self.reclaimer = None    # set by the engine: fn(n_pages) -> freed

    # -- geometry ----------------------------------------------------------
    @property
    def nbytes(self) -> int:
        return int(self.k.size + self.v.size) * jnp.dtype(self.dtype).itemsize

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to back ``n_tokens`` cache rows."""
        return -(-int(n_tokens) // self.page_size)

    # -- page plumbing -----------------------------------------------------
    def free_page_count(self) -> int:
        return len(self._free_pages)

    def _take_pages(self, n: int) -> List[int]:
        if n > len(self._free_pages) and self.reclaimer is not None:
            self.reclaimer(n - len(self._free_pages))
        if n > len(self._free_pages):
            raise PagePoolFullError(
                f"need {n} free page(s), have {len(self._free_pages)} "
                f"of {self.num_pages}")
        out = [self._free_pages.pop(0) for _ in range(n)]
        for p in out:
            assert self._ref[p] == 0, f"free page {p} had refs"
            self._ref[p] = 1
        return out

    def ref_pages(self, pages: Sequence[int]) -> None:
        for p in pages:
            assert p != 0 and self._ref[p] > 0, f"ref on dead page {p}"
            self._ref[p] += 1

    def deref_pages(self, pages: Sequence[int]) -> None:
        for p in pages:
            if p == 0:
                continue
            assert self._ref[p] > 0, f"double free of page {p}"
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free_pages.append(p)
        self._free_pages.sort()
        self._note_pool_metrics()

    # -- slot bookkeeping --------------------------------------------------
    def can_admit(self, prompt_len: int, prefix_len: int = 0) -> bool:
        """Would a prompt of ``prompt_len`` (with ``prefix_len`` tokens
        already cache-backed) fit right now? Counts reclaimable
        prefix-cache pages via the reclaimer's dry-run hook when set."""
        if not self._free_slots:
            return False
        need = self.pages_for(prompt_len) - prefix_len // self.page_size
        avail = len(self._free_pages)
        if self.reclaimer is not None:
            avail += getattr(self.reclaimer, "reclaimable", lambda: 0)()
        return need <= avail

    def alloc(self, length: int = 0,
              prefix_pages: Sequence[int] = ()) -> int:
        """Claim a slot; attach ``prefix_pages`` (shared, refcounted) and
        map fresh pages so every position ``< length`` is backed.

        Raises :class:`CacheFullError` when no slot is free and
        :class:`PagePoolFullError` when the pool is dry (the slot is NOT
        claimed in that case)."""
        if not self._free_slots:
            raise CacheFullError(
                f"all {self.max_slots} decode slots are live")
        if length > self.max_seq:
            raise ValueError(
                f"sequence length {length} exceeds max_seq {self.max_seq}")
        n_prefix = len(prefix_pages)
        if n_prefix * self.page_size > length:
            raise ValueError("prefix pages cover more than the sequence")
        n_own = self.pages_for(length) - n_prefix
        # pin the shared prefix FIRST: _take_pages may trigger the
        # prefix-cache reclaimer, which must not be able to free (and
        # recycle) the very pages this slot is about to attach
        self.ref_pages(prefix_pages)
        try:
            own = self._take_pages(n_own)    # may raise PagePoolFullError
        except PagePoolFullError:
            self.deref_pages(prefix_pages)
            raise
        slot = self._free_slots.pop(0)
        st = self._slots[slot]
        st.live = True
        st.length = int(length)
        st.prefix_len = n_prefix * self.page_size
        st.mapped = n_prefix + n_own
        st.generation += 1
        row = self._tables[slot]
        row[:] = 0
        row[:n_prefix] = prefix_pages
        row[n_prefix:st.mapped] = own
        self._note_pool_metrics()
        return slot

    def ensure_capacity(self, slot: int, upto_len: int) -> bool:
        """Map pages so positions ``< upto_len`` are write-backed.
        Returns False (mapping nothing) when the pool cannot cover it —
        the scheduler's cue to preempt."""
        st = self._slots[slot]
        if not st.live:
            raise ValueError(f"slot {slot} is not live")
        if upto_len > self.max_seq:
            return False
        need = self.pages_for(upto_len) - st.mapped
        if need <= 0:
            return True
        try:
            pages = self._take_pages(need)
        except PagePoolFullError:
            return False
        self._tables[slot][st.mapped:st.mapped + need] = pages
        st.mapped += need
        self._note_pool_metrics()
        return True

    def free(self, slot: int) -> None:
        st = self._slots[slot]
        if not st.live:
            raise ValueError(f"slot {slot} is not live")
        row = self._tables[slot]
        self.deref_pages([int(p) for p in row[:st.mapped]])
        row[:] = 0
        st.live = False
        st.length = 0
        st.prefix_len = 0
        st.mapped = 0
        self._free_slots.append(slot)
        self._free_slots.sort()

    def set_length(self, slot: int, length: int) -> None:
        st = self._slots[slot]
        if length > self.max_seq:
            raise ValueError(
                f"slot {slot}: length {length} exceeds max_seq "
                f"{self.max_seq}")
        if self.pages_for(length) > st.mapped:
            raise ValueError(
                f"slot {slot}: length {length} beyond mapped pages "
                f"({st.mapped} x {self.page_size})")
        st.length = int(length)

    def length(self, slot: int) -> int:
        return self._slots[slot].length

    def prefix_len(self, slot: int) -> int:
        return self._slots[slot].prefix_len

    def generation(self, slot: int) -> int:
        return self._slots[slot].generation

    def is_live(self, slot: int) -> bool:
        return self._slots[slot].live

    def live_slots(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s.live]

    def free_slot_count(self) -> int:
        return len(self._free_slots)

    @property
    def occupancy(self) -> float:
        return (self.max_slots - len(self._free_slots)) / self.max_slots

    def lengths_vector(self) -> np.ndarray:
        return np.array([s.length if s.live else 0 for s in self._slots],
                        np.int32)

    def headroom(self, slot: int) -> int:
        return self.max_seq - self._slots[slot].length

    # -- page content I/O (serving/prefix_store.py warm restart) -----------
    def claim_pages(self, n: int) -> List[int]:
        """Take ``n`` pages off the free list with ONE reference each —
        the prefix cache's reference when the pages are adopted as a
        restored cache entry. Raises :class:`PagePoolFullError` (after
        the reclaimer hook) when the pool cannot cover it."""
        return self._take_pages(int(n))

    def read_pages(self, pages: Sequence[int]
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """Host copies of the K/V contents of ``pages``:
        ``([L, n, page_size, nh, hd] k, same v)`` — what the prefix
        store persists at publish time."""
        idx = np.asarray(list(pages), np.int32)
        n = idx.size
        pad = -n % TRANSFER_PAGE_BUCKET
        if pad:
            # pad the gather with scratch-page reads so every group in a
            # bucket shares one compiled shape (zero-recompile contract)
            idx = np.concatenate([idx, np.zeros(pad, np.int32)])
        k, v = _gather_pages_exec(self.k, self.v, idx)
        k = np.asarray(k)
        v = np.asarray(v)
        return (k[:, :n], v[:, :n]) if pad else (k, v)

    def write_pages(self, pages: Sequence[int], k_pages: np.ndarray,
                    v_pages: np.ndarray) -> None:
        """Write restored K/V contents into ``pages`` (boot-time only:
        the arrays are replaced wholesale, which is exactly how the
        engine treats them between executable calls)."""
        idx = np.asarray(list(pages), np.int32)
        n = idx.size
        pad = -n % TRANSFER_PAGE_BUCKET
        k_pages = np.asarray(k_pages)
        v_pages = np.asarray(v_pages)
        if pad:
            # pad the scatter with writes to the scratch page (whose
            # contents are garbage by contract) so every group in a
            # bucket shares one compiled shape
            idx = np.concatenate([idx, np.zeros(pad, np.int32)])
            zeros = np.zeros(
                k_pages.shape[:1] + (pad,) + k_pages.shape[2:],
                k_pages.dtype)
            k_pages = np.concatenate([k_pages, zeros], axis=1)
            v_pages = np.concatenate([v_pages, zeros], axis=1)
        self.k, self.v = _scatter_pages_exec(
            self.k, self.v, idx,
            jnp.asarray(k_pages, self.dtype),
            jnp.asarray(v_pages, self.dtype))

    def adopt_slot(self, length: int, pages: Sequence[int]) -> int:
        """Bind already-claimed, already-written ``pages`` to a fresh
        slot with ``length`` valid positions — the receiving half of a
        KV handoff (serving/kv_transfer.py). The pages must carry the
        single reference :meth:`claim_pages` gave them; that reference
        becomes the slot's, so :meth:`free` returns them to the pool.
        Raises :class:`CacheFullError` when no slot is free (the caller
        still owns the pages and must deref them)."""
        pages = [int(p) for p in pages]
        if length > self.max_seq:
            raise ValueError(
                f"sequence length {length} exceeds max_seq {self.max_seq}")
        if len(pages) != self.pages_for(length):
            raise ValueError(
                f"adopting {len(pages)} page(s) for length {length}; "
                f"need {self.pages_for(length)}")
        for p in pages:
            if p == 0 or self._ref[p] <= 0:
                raise ValueError(f"adopting unclaimed page {p}")
        if not self._free_slots:
            raise CacheFullError(
                f"all {self.max_slots} decode slots are live")
        slot = self._free_slots.pop(0)
        st = self._slots[slot]
        st.live = True
        st.length = int(length)
        st.prefix_len = 0
        st.mapped = len(pages)
        st.generation += 1
        row = self._tables[slot]
        row[:] = 0
        row[:len(pages)] = pages
        self._note_pool_metrics()
        return slot

    # -- executable feeds --------------------------------------------------
    def table_row(self, slot: int) -> np.ndarray:
        """[max_pages_per_slot] int32 page table for one slot (copy)."""
        return self._tables[slot].copy()

    def tables(self) -> np.ndarray:
        """[max_slots, max_pages_per_slot] int32 — the decode feed."""
        return self._tables.copy()

    # -- pool metrics ------------------------------------------------------
    def pool_occupancy(self) -> float:
        """Allocated pages / allocatable pages (scratch excluded)."""
        total = self.num_pages - 1
        return (total - len(self._free_pages)) / total

    def fragmentation(self) -> float:
        """Internal waste: 1 - used_rows / allocated_rows (0 when every
        allocated page is full of valid tokens; pages are fixed-size so
        there is no external fragmentation)."""
        mapped = sum(s.mapped for s in self._slots if s.live)
        cache_held = int(np.sum(self._ref[1:] > 0)) - sum(
            s.mapped for s in self._slots if s.live)
        # cache-held shared pages are full by construction; count them in
        allocated_rows = (mapped + max(cache_held, 0)) * self.page_size
        used_rows = sum(s.length for s in self._slots if s.live) + \
            max(cache_held, 0) * self.page_size
        if allocated_rows <= 0:
            return 0.0
        return 1.0 - used_rows / allocated_rows

    def _note_pool_metrics(self) -> None:
        smetrics.m_page_occupancy.set(self.pool_occupancy())
        smetrics.m_page_fragmentation.set(self.fragmentation())


class PrefixCache:
    """Token-hash keyed, refcounted, LRU prefix cache over a page pool.

    Entries are page-aligned prompt prefixes; the cache holds ONE ref on
    every page of every entry (slots using the pages hold their own).
    ``capacity_pages`` bounds distinct cache-held pages; LRU entries are
    dropped on overflow and under pool pressure (:meth:`reclaim` — wired
    as the pool's ``reclaimer`` by the engine)."""

    def __init__(self, pool: PagedKVCache, capacity_pages: int = 0):
        self.pool = pool
        self.capacity_pages = int(capacity_pages) or pool.num_pages
        # insertion/use-ordered: key -> (tokens tuple, pages tuple)
        self._entries: "OrderedDict[bytes, Tuple[Tuple[int, ...], Tuple[int, ...]]]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(tokens: Sequence[int]) -> bytes:
        return hashlib.sha1(
            np.asarray(tokens, np.int64).tobytes()).digest()

    def _held_pages(self) -> set:
        held = set()
        for _, pages in self._entries.values():
            held.update(pages)
        return held

    def held_page_count(self) -> int:
        return len(self._held_pages())

    def reclaimable(self) -> int:
        """Pages that a full reclaim could hand back to the pool (those
        only the cache still holds)."""
        n = 0
        for p in self._held_pages():
            if self.pool._ref[p] == 1:
                n += 1
        return n

    def has(self, tokens: Sequence[int]) -> bool:
        """Exact-entry probe WITHOUT metric counts or LRU freshening —
        the disagg prefix-index's "is it already local?" check."""
        key = self._key(tuple(int(t) for t in tokens))
        ent = self._entries.get(key)
        return ent is not None and ent[0] == tuple(int(t) for t in tokens)

    def lookup(self, tokens: Sequence[int]
               ) -> Tuple[int, Tuple[int, ...]]:
        """Longest cached page-aligned prefix of ``tokens`` that still
        leaves at least one suffix token to prefill. Returns
        ``(prefix_len, pages)`` — (0, ()) on miss. Counts the
        hit/miss metric and freshens LRU order on hit."""
        ps = self.pool.page_size
        max_j = (len(tokens) - 1) // ps
        for j in range(max_j, 0, -1):
            prefix = tuple(int(t) for t in tokens[:j * ps])
            key = self._key(prefix)
            ent = self._entries.get(key)
            if ent is not None and ent[0] == prefix:
                self._entries.move_to_end(key)
                self.hits += 1
                smetrics.m_prefix_cache.labels("hit").inc()
                return j * ps, ent[1]
        self.misses += 1
        smetrics.m_prefix_cache.labels("miss").inc()
        return 0, ()

    def adopt_nested(self, tokens: Sequence[int],
                     pages: Sequence[int]) -> int:
        """Register a RESTORED page-aligned prefix (warm restart,
        serving/prefix_store.py): ``pages`` already hold their single
        cache reference (:meth:`PagedKVCache.claim_pages`) and their
        contents are already written into the pool. Mirrors
        :meth:`insert`'s nested publication — every page-boundary prefix
        of ``tokens`` becomes an entry sharing the same pages. Returns
        how many entries were registered (existing keys are skipped)."""
        ps = self.pool.page_size
        pages = tuple(int(p) for p in pages)
        if len(tokens) < len(pages) * ps:
            raise ValueError("adopted pages cover more than the tokens")
        registered = 0
        for j in range(1, len(pages) + 1):
            prefix = tuple(int(t) for t in tokens[:j * ps])
            key = self._key(prefix)
            if key in self._entries:
                continue
            self._entries[key] = (prefix, pages[:j])
            registered += 1
        self._evict_over_capacity()
        return registered

    def insert(self, tokens: Sequence[int], table_row: np.ndarray) -> int:
        """Publish every page-boundary prefix of ``tokens`` whose pages
        are in ``table_row`` (the slot's mapping after prefill). Returns
        how many NEW entries were added. New pages get one cache ref."""
        ps = self.pool.page_size
        full = len(tokens) // ps
        added = 0
        newly_held = []
        held = self._held_pages()
        for j in range(1, full + 1):
            prefix = tuple(int(t) for t in tokens[:j * ps])
            key = self._key(prefix)
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            pages = tuple(int(p) for p in table_row[:j])
            if any(p == 0 for p in pages):
                break                      # unmapped — nothing cacheable
            self._entries[key] = (prefix, pages)
            added += 1
            for p in pages:
                if p not in held:
                    held.add(p)
                    newly_held.append(p)
        if newly_held:
            self.pool.ref_pages(newly_held)
        self._evict_over_capacity()
        return added

    def _drop_entry(self, key: bytes) -> None:
        _tokens, pages = self._entries.pop(key)
        still_held = self._held_pages()
        self.pool.deref_pages([p for p in pages if p not in still_held])

    def _evict_over_capacity(self) -> None:
        while (self._entries
               and self.held_page_count() > self.capacity_pages):
            self._drop_entry(next(iter(self._entries)))

    def reclaim(self, n_pages: int) -> int:
        """Pool-pressure hook: drop LRU entries until ``n_pages`` pages
        returned to the free list (or the cache is empty). Returns pages
        actually freed."""
        freed0 = self.pool.free_page_count()
        while (self._entries
               and self.pool.free_page_count() - freed0 < n_pages):
            self._drop_entry(next(iter(self._entries)))
        return self.pool.free_page_count() - freed0

    def clear(self) -> None:
        while self._entries:
            self._drop_entry(next(iter(self._entries)))

    def __len__(self) -> int:
        return len(self._entries)
