"""paddle.compat — py2/py3 compatibility helpers (reference
python/paddle/compat.py:18-248). The framework is py3-only, so the text
helpers are straightforward, but the public contract (in-place list/set
mutation, banker's-rounding-free ``round``) is kept.
"""
import math

__all__ = [
    "long_type", "to_text", "to_bytes", "round", "floor_division",
    "get_exception_message",
]

long_type = int  # py3: int subsumes py2 long (reference compat.py:24-33)


def _map_inplace(obj, fn, inplace):
    if isinstance(obj, list):
        if inplace:
            obj[:] = [fn(o) for o in obj]
            return obj
        return [fn(o) for o in obj]
    if isinstance(obj, set):
        new = {fn(o) for o in obj}
        if inplace:
            obj.clear()
            obj.update(new)
            return obj
        return new
    return fn(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """Convert bytes (or a list/set of them) to str (reference
    compat.py:36-117). None passes through; non-bytes are str()'d only if
    they are str already (parity: reference raises on other types)."""
    def one(x):
        if x is None or isinstance(x, str):
            return x
        if isinstance(x, (bytes, bytearray)):
            return x.decode(encoding)
        raise TypeError(f"unsupported type {type(x)} for to_text")
    return _map_inplace(obj, one, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Convert str (or a list/set of them) to bytes (reference
    compat.py:120-190)."""
    def one(x):
        if x is None or isinstance(x, bytes):
            return x
        if isinstance(x, str):
            return x.encode(encoding)
        raise TypeError(f"unsupported type {type(x)} for to_bytes")
    return _map_inplace(obj, one, inplace)


def round(x, d=0):
    """Half-away-from-zero rounding — python2 semantics, NOT py3 banker's
    rounding (reference compat.py:193-216)."""
    if x is None:
        raise TypeError("round() does not accept None")
    x = float(x)
    p = 10 ** d
    if x >= 0.0:
        return float(math.floor(x * p + 0.5)) / p
    return float(math.ceil(x * p - 0.5)) / p


def floor_division(x, y):
    """Explicit // (reference compat.py:219-233)."""
    return x // y


def get_exception_message(exc):
    """Uniform message accessor (reference compat.py:236-248)."""
    if exc is None:
        raise TypeError("get_exception_message() does not accept None")
    return str(exc)
