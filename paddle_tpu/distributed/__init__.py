"""Distributed runtime: parameter-server transport + host ops.

The collective (mesh/pjit) stack lives in paddle_tpu/parallel/; this package
is the PS capability (reference operators/distributed/ + distributed_ops/):
a socket transport over the native C++ table core, surfaced as host ops
(send/recv/listen_and_serv/...) that the Executor runs between jitted device
segments.
"""
from . import ps_ops  # noqa: F401  (registers host ops)
from .ps_client import PSClient  # noqa: F401
from .ps_server import ParameterServer  # noqa: F401
from .table import DenseTable, SparseTable  # noqa: F401
from . import cloud_utils, fs_wrapper  # noqa: F401
# launch_ps is NOT pre-imported: `python -m paddle_tpu.distributed.launch_ps`
# would hit runpy's already-in-sys.modules warning
from .fs_wrapper import FS, LocalFS  # noqa: F401
