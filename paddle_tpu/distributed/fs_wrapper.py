"""paddle.distributed.fs_wrapper — parity with
python/paddle/distributed/fs_wrapper.py (FS/LocalFS/BDFS): thin aliases
over the fleet FS implementations (incubate/fleet/utils/fs.py)."""
from ..incubate.fleet.utils.fs import FS, LocalFS  # noqa: F401
from ..incubate.fleet.utils.fs import HDFSClient as BDFS  # noqa: F401

__all__ = ["FS", "LocalFS", "BDFS"]
