"""Host-op implementations for the PS program surface — parity with
operators/distributed_ops/ (send, recv, send_barrier, fetch_barrier,
listen_and_serv, checkpoint_notify) and distributed_lookup_table.

These run Python-side between jitted device segments (see
framework/executor.py host-op segmentation); the server arithmetic is the
native C++ table (native/ps_table.cpp).
"""
from __future__ import annotations

import numpy as np

from ..framework.executor import register_host_op
from .ps_client import PSClient

__all__ = ["PSClient"]


def _scope_np(scope, name):
    v = scope.find_var(name)
    if v is None:
        raise RuntimeError(f"host op: var {name!r} not in scope")
    return np.asarray(v)


def _set_scope(scope, name, arr):
    import jax.numpy as jnp
    scope.set_var(name, jnp.asarray(arr))


@register_host_op("send")
def send_op(scope, op, exe):
    """send_op.cc: push one grad (or GEO delta) to the param's pserver."""
    eps = op.attr("epmap")
    param = op.attr("param")
    tid = int(op.attr("trainer_id", 0))
    mode = int(op.attr("mode", 0))
    client = PSClient.instance(tid)
    grad_name = op.input("X")[0]
    grad = _scope_np(scope, grad_name)
    lr_var = op.attr("lr_var", None)
    lr = None
    if lr_var and scope.has_var(lr_var):
        lr = float(np.asarray(scope.find_var(lr_var)).ravel()[0])
    ep = eps[0]
    # first-touch server init from the trainer's local startup value
    if scope.has_var(param):
        client.ensure_init(ep, param, _scope_np(scope, param))
    if mode == 3:  # GEO pushes param deltas
        client.push_delta(ep, param, grad)
    elif mode == 2:  # HALF_ASYNC: merge-queue via the communicator
        from .communicator import HalfAsyncCommunicator

        HalfAsyncCommunicator.instance(tid).push(ep, param, grad, lr=lr)
    else:
        client.push(ep, param, grad, lr=lr)


@register_host_op("send_barrier")
def send_barrier_op(scope, op, exe):
    eps = op.attr("endpoints")
    tid = int(op.attr("trainer_id", 0))
    PSClient.instance(tid).barrier(eps, "send")


@register_host_op("fetch_barrier")
def fetch_barrier_op(scope, op, exe):
    eps = op.attr("endpoints")
    tid = int(op.attr("trainer_id", 0))
    PSClient.instance(tid).barrier(eps, "fetch")


@register_host_op("recv")
def recv_op(scope, op, exe):
    """recv_op.cc: pull a param from its pserver into scope."""
    eps = op.attr("epmap")
    param = op.attr("param")
    tid = int(op.attr("trainer_id", 0))
    if int(op.attr("mode", 0)) == 2:
        # half-async: make sure this trainer's queued grads are on the wire
        # before pulling (the reference's per-batch communicator flush)
        from .communicator import HalfAsyncCommunicator

        HalfAsyncCommunicator.instance(tid).flush()
    client = PSClient.instance(tid)
    out_name = op.output("Out")[0]
    if scope.has_var(param):
        client.ensure_init(eps[0], param, _scope_np(scope, param))
    value = client.pull(eps[0], param)
    local = scope.find_var(out_name)
    if local is not None:
        value = value.reshape(np.asarray(local).shape)
    _set_scope(scope, out_name, value)


@register_host_op("distributed_lookup_table")
def distributed_lookup_table_op(scope, op, exe):
    """distributed_lookup_table_op.cc + parameter_prefetch.cc: remote sparse
    embedding lookup — ids -> rows from the pserver's sparse table."""
    eps = op.attr("epmap")
    table = op.attr("table_name")
    tid = int(op.attr("trainer_id", 0))
    client = PSClient.instance(tid)
    ids = _scope_np(scope, op.input("Ids")[0])
    shape = ids.shape
    rows = client.pull_sparse(eps[0], table, ids.reshape(-1).astype(np.uint64))
    out = rows.reshape(*shape, -1)
    if out.shape[-2] == 1 and len(shape) >= 2 and shape[-1] == 1:
        out = out.reshape(*shape[:-1], -1)  # ids [..., 1] -> emb [..., dim]
    _set_scope(scope, op.output("Out")[0], out)


@register_host_op("distributed_push_sparse")
def distributed_push_sparse_op(scope, op, exe):
    """Sparse grad push (the send-side of distributed_lookup_table)."""
    eps = op.attr("epmap")
    table = op.attr("table_name")
    tid = int(op.attr("trainer_id", 0))
    client = PSClient.instance(tid)
    ids = _scope_np(scope, op.input("Ids")[0]).reshape(-1).astype(np.uint64)
    grads = _scope_np(scope, op.input("Grad")[0])
    grads = grads.reshape(ids.size, -1)
    lr_var = op.attr("lr_var", None)
    lr = None
    if lr_var and scope.has_var(lr_var):
        lr = float(np.asarray(scope.find_var(lr_var)).ravel()[0])
    client.push_sparse(eps[0], table, ids, grads, lr=lr)


def _box_pull(scope, op, extended):
    """pull_box_sparse(_extended) — reference pull_box_sparse_op.cc:20:
    N Ids tensors (last dim 1) -> N embedding tensors ids[:-1]+[size],
    looked up from the sparse PS table (the TPU-native stand-in for the
    BoxPS heterogeneous store: same table contract, served by
    native/ps_table.cpp through the framed wire)."""
    eps = op.attr("epmap", None) or []
    table = op.attr("table_name", "emb")
    size = int(op.attr("size", 1))
    tid = int(op.attr("trainer_id", 0))
    client = PSClient.instance(tid)
    outs = op.output("Out")
    ext_outs = op.output("OutExtend") if extended else []
    # ONE RPC for all slots (the reference does one BoxPS call): flatten
    # every Ids tensor, pull once, split the rows back per slot
    id_arrays = [_scope_np(scope, n) for n in op.input("Ids")]
    flat = np.concatenate([a.reshape(-1) for a in id_arrays]).astype(
        np.uint64)
    rows = client.pull_sparse(eps[0], table, flat)
    off = 0
    for i, ids in enumerate(id_arrays):
        n = ids.reshape(-1).size
        slot_rows = rows[off:off + n].reshape(*ids.shape[:-1], -1)
        off += n
        _set_scope(scope, outs[i],
                   np.ascontiguousarray(slot_rows[..., :size]))
        if extended and i < len(ext_outs):
            _set_scope(scope, ext_outs[i],
                       np.ascontiguousarray(slot_rows[..., size:]))


def _box_push(scope, op, extended):
    """push_box_sparse(_extended) — the grad path of the box lookup. The
    extended variant concatenates Out@GRAD with OutExtend@GRAD to the
    full row width (reference pull_box_extended_sparse_op.h:63)."""
    eps = op.attr("epmap", None) or []
    table = op.attr("table_name", "emb")
    tid = int(op.attr("trainer_id", 0))
    client = PSClient.instance(tid)
    grads = op.input("Out@GRAD") or op.input("Grad")
    ext_grads = (op.input("OutExtend@GRAD") or op.input("GradExtend")) \
        if extended else []
    all_ids, all_g = [], []
    for i, (ids_name, g_name) in enumerate(zip(op.input("Ids"), grads)):
        ids = _scope_np(scope, ids_name).reshape(-1).astype(np.uint64)
        g = _scope_np(scope, g_name).reshape(ids.size, -1)
        if extended and i < len(ext_grads):
            ge = _scope_np(scope, ext_grads[i]).reshape(ids.size, -1)
            g = np.concatenate([g, ge], axis=1)
        all_ids.append(ids)
        all_g.append(g)
    client.push_sparse(eps[0], table, np.concatenate(all_ids),
                       np.concatenate(all_g, axis=0))


@register_host_op("pull_box_sparse")
def pull_box_sparse_op(scope, op, exe):
    _box_pull(scope, op, extended=False)


@register_host_op("pull_box_extended_sparse")
def pull_box_extended_sparse_op(scope, op, exe):
    _box_pull(scope, op, extended=True)


@register_host_op("push_box_sparse")
def push_box_sparse_op(scope, op, exe):
    _box_push(scope, op, extended=False)


@register_host_op("push_box_extended_sparse")
def push_box_extended_sparse_op(scope, op, exe):
    _box_push(scope, op, extended=True)


def _build_and_serve(op, trainer_num, default_lr, mode, sync_mode):
    """Shared pserver bring-up for listen_and_serv / fl_listen_and_serv:
    construct from the transpiler table configs, start (native wire when
    available), optionally block."""
    from .ps_server import ParameterServer

    server = ParameterServer(
        op.attr("endpoint"),
        trainer_num=trainer_num,
        sync_mode=sync_mode,
        mode=mode,
    )
    for tbl in op.attr("tables", []):
        if tbl.get("is_sparse"):
            server.register_sparse(tbl["name"], tbl["dim"],
                                   tbl.get("optimizer", "sgd"),
                                   tbl.get("lr", default_lr),
                                   **tbl.get("hparams", {}))
        else:
            server.register_dense(tbl["name"], tbl["shape"],
                                  tbl.get("optimizer", "sgd"),
                                  tbl.get("lr", default_lr),
                                  **tbl.get("hparams", {}))
    server.start()
    op._server = server  # for in-process tests / graceful shutdown
    if op.attr("blocking", True):
        server.serve_forever()
    return server


@register_host_op("listen_and_serv")
def listen_and_serv_op(scope, op, exe):
    """listen_and_serv_op.cc: the pserver main loop.  Builds tables from the
    transpiler-provided configs and serves until a stop RPC arrives."""
    _build_and_serve(op, trainer_num=int(op.attr("trainer_num", 1)),
                     default_lr=0.01, mode=int(op.attr("mode", 0)),
                     sync_mode=bool(op.attr("sync_mode", True)))


@register_host_op("fl_listen_and_serv")
def fl_listen_and_serv_op(scope, op, exe):
    """fl_listen_and_serv_op.cc:246 — the federated-learning server loop.

    The reference variant runs per-round barriers: clients fetch the
    global model (get barrier), train locally, send updates (send
    barrier), the server aggregates once per round over ``Fanin``
    clients. That is exactly the sync accumulation-round machinery of
    ParameterServer with trainer_num=Fanin: FedAvg emerges from clients
    pushing (w_global - w_local) with lr=1 — the server applies
    w -= mean(w_global - w_local) = mean(w_local)."""
    _build_and_serve(op,
                     trainer_num=int(op.attr("Fanin", op.attr("fanin", 1))),
                     default_lr=1.0, mode=0,
                     sync_mode=bool(op.attr("sync_mode", True)))


@register_host_op("checkpoint_notify")
def checkpoint_notify_op(scope, op, exe):
    eps = op.attr("epmap")
    dirname = op.attr("dirname")
    tid = int(op.attr("trainer_id", 0))
    client = PSClient.instance(tid)
    for ep in eps:
        client.checkpoint_notify(ep, dirname)


@register_host_op("prefetch")
def prefetch_op(scope, op, exe):
    """distributed_ops/prefetch_op.cc — block-fetch remote sparse rows for
    the given ids (same wire path as distributed_lookup_table; the
    reference splits ids across servers, here the table client does)."""
    eps = op.attr("epmap")
    table = op.attr("table_names")
    tid = int(op.attr("trainer_id", 0))
    client = PSClient.instance(tid)
    tables = table if isinstance(table, (list, tuple)) else [table]
    in_names = op.input("X")
    out_names = op.output("Out")
    for i, (inn, outn) in enumerate(zip(in_names, out_names)):
        ids = _scope_np(scope, inn).reshape(-1).astype(np.uint64)
        rows = client.pull_sparse(eps[0], tables[min(i, len(tables) - 1)],
                                  ids)
        _set_scope(scope, outn, rows)


@register_host_op("push_dense")
def push_dense_op(scope, op, exe):
    """distributed_ops/push_dense_op.cc (fleet a-sync dense push): send
    dense grads to the pserver (send-op path with averaged scale)."""
    eps = op.attr("epmap", ["127.0.0.1:0"])
    tid = int(op.attr("trainer_id", 0))
    client = PSClient.instance(tid)
    for name in op.input("Ids") or op.input("X"):
        val = _scope_np(scope, name)
        client.push(eps[0], name, val)


@register_host_op("lookup_sparse_table")
def lookup_sparse_table_op(scope, op, exe):
    """distributed_ops/lookup_sparse_table_op.cc — server-side sparse
    table lookup with auto-grown rows (init with uniform random when the
    id is new). Local form: W is the dense table var in scope."""
    w_name = op.input("W")[0]
    ids = _scope_np(scope, op.input("Ids")[0]).reshape(-1).astype(np.int64)
    w = _scope_np(scope, w_name)
    init_value = float(op.attr("init_value", 0.0))
    max_id = int(ids.max()) + 1 if ids.size else 0
    if max_id > w.shape[0]:  # auto-grow like the reference's sparse table
        grown = np.full((max_id, w.shape[1]), init_value, w.dtype)
        grown[: w.shape[0]] = w
        w = grown
        _set_scope(scope, w_name, w)
    _set_scope(scope, op.output("Out")[0], w[ids])
