"""PS-cluster launcher — parity with
python/paddle/distributed/launch_ps.py (parse_args:24, start_procs:81,
launch:157): spawn N pserver + M trainer processes of a user training
script, wiring the PADDLE_* environment contract that
fleet.PaddleCloudRoleMaker reads (incubate/fleet/base/role_maker.py).

Usage (reference CLI shape):
    python -m paddle_tpu.distributed.launch_ps \
        --worker_num 2 --server_num 2 train.py [script args...]
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
from typing import List, Optional, Tuple


def _free_ports(n: int) -> List[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def parse_args(argv=None):
    p = argparse.ArgumentParser("launch_ps")
    p.add_argument("--worker_num", type=int, default=2)
    p.add_argument("--server_num", type=int, default=2)
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def start_procs(worker_num: int, server_num: int, training_script: str,
                script_args: Optional[List[str]] = None, log_dir=None,
                env=None) -> Tuple[list, list]:
    """Spawn pservers then trainers; returns (server_procs,
    trainer_procs). Pair with wait_procs (reference start_procs spawns
    and waits in one call)."""
    script_args = script_args or []
    ports = _free_ports(server_num)
    endpoints = ",".join(f"127.0.0.1:{p}" for p in ports)
    base = dict(env if env is not None else os.environ)
    base["PADDLE_PSERVERS_IP_PORT_LIST"] = endpoints
    base["PADDLE_TRAINERS_NUM"] = str(worker_num)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)

    def spawn(role, idx, extra):
        e = dict(base)
        e["TRAINING_ROLE"] = role
        e.update(extra)
        out = None
        if log_dir:
            out = open(os.path.join(
                log_dir, f"{role.lower()}.{idx}.log"), "w")
        try:
            return subprocess.Popen(
                [sys.executable, training_script] + list(script_args),
                env=e, stdout=out,
                stderr=subprocess.STDOUT if out else None)
        finally:
            if out is not None:
                out.close()     # Popen dup'd the fd; the parent copy leaks

    servers = [spawn("PSERVER", i, {"PADDLE_PORT": str(port),
                                    "POD_IP": "127.0.0.1"})
               for i, port in enumerate(ports)]
    trainers = [spawn("TRAINER", i, {"PADDLE_TRAINER_ID": str(i)})
                for i in range(worker_num)]
    return servers, trainers


def wait_procs(servers, trainers, timeout=None) -> int:
    """Wait for every trainer (``timeout`` bounds EACH wait), then stop
    the pservers (they serve until told otherwise — the reference's wait
    loop does the same). Servers and unfinished trainers are torn down
    even when a trainer hangs past the timeout."""
    rc = 0
    try:
        for p in trainers:
            rc |= p.wait(timeout=timeout) or 0
    finally:
        leftovers = servers + [t for t in trainers if t.poll() is None]
        for p in leftovers:
            p.terminate()
        for p in leftovers:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass    # D-state survivor: keep reaping the rest
    return rc


def launch(argv=None) -> int:
    args = parse_args(argv)
    servers, trainers = start_procs(
        args.worker_num, args.server_num, args.training_script,
        args.training_script_args, log_dir=args.log_dir)
    return wait_procs(servers, trainers)


if __name__ == "__main__":
    sys.exit(launch())
