"""Client-side gradient communicator — parity with the reference's
Communicator stack (operators/distributed/communicator.h: AsyncCommunicator
:237, HalfAsyncCommunicator :299).

The reference runs send threads that drain per-var queues, merging up to
``max_merge_var_num`` pending gradients into one RPC. Here the half-async
send op enqueues into this communicator instead of pushing directly; a
daemon thread merges (averages) whatever accumulated per (endpoint, param)
and issues one push — so trainers never block on the network, and the wire
carries merged rounds.
"""
from __future__ import annotations

import logging
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("paddle_tpu.communicator")


class HalfAsyncCommunicator:
    _instances: Dict[int, "HalfAsyncCommunicator"] = {}
    _lock = threading.Lock()

    def __init__(self, trainer_id: int, max_merge_var_num: int = 20,
                 send_wait_ms: float = 2.0):
        from .ps_client import PSClient  # local import: avoid cycle

        self.trainer_id = trainer_id
        self.max_merge = int(max_merge_var_num)
        self.wait_s = send_wait_ms / 1000.0
        self._client = PSClient.instance(trainer_id)
        self._queues: Dict[Tuple[str, str], List] = defaultdict(list)
        self._meta: Dict[Tuple[str, str], Optional[float]] = {}
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._inflight = 0
        self._error: Optional[Exception] = None
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()

    # -- api ----------------------------------------------------------------
    @classmethod
    def instance(cls, trainer_id: int, **kw) -> "HalfAsyncCommunicator":
        with cls._lock:
            if trainer_id not in cls._instances:
                cls._instances[trainer_id] = cls(trainer_id, **kw)
            return cls._instances[trainer_id]

    def push(self, ep: str, param: str, grad: np.ndarray,
             lr: Optional[float] = None):
        with self._cv:
            self._queues[(ep, param)].append(np.asarray(grad, np.float32))
            self._meta[(ep, param)] = lr
            self._cv.notify_all()

    def flush(self):
        """Block until every queued gradient has been merged and sent;
        raises the first send error instead of hanging on a dead wire.
        The error is cleared once surfaced: a transient push failure is
        reported exactly once and must not poison every later flush."""
        with self._cv:
            while any(self._queues.values()) or self._inflight:
                if self._error is not None:
                    err, self._error = self._error, None
                    raise RuntimeError(
                        "half-async communicator send failed") from err
                self._cv.wait(timeout=0.05)
            if self._error is not None:
                err, self._error = self._error, None
                raise RuntimeError(
                    "half-async communicator send failed") from err

    def stop(self):
        try:
            self.flush()
        finally:
            self._stop.set()
            with self._cv:
                self._cv.notify_all()
            self._thread.join(timeout=2.0)
            with self._lock:
                type(self)._instances.pop(self.trainer_id, None)

    # -- send thread ---------------------------------------------------------
    def _send_loop(self):
        while not self._stop.is_set():
            batch = []
            with self._cv:
                if not any(self._queues.values()):
                    self._cv.wait(timeout=self.wait_s)
                for key, q in self._queues.items():
                    if q:
                        take = q[:self.max_merge]
                        del q[:len(take)]
                        batch.append((key, take, self._meta.get(key)))
                self._inflight += len(batch)
            for (ep, param), grads, lr in batch:
                try:
                    merged = grads[0] if len(grads) == 1 else \
                        np.mean(np.stack(grads), axis=0)
                    self._client.push(ep, param, merged, lr=lr)
                except Exception as e:
                    # a dying send thread would strand queued grads and make
                    # flush() hang forever; record and surface at flush
                    self._error = e
                    logger.error("half-async push of %r to %s failed: %r",
                                 param, ep, e)
                finally:
                    with self._cv:
                        self._inflight -= 1
                        self._cv.notify_all()
