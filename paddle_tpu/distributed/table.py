"""ctypes binding over the native PS table (native/ps_table.cpp)."""
from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

OPTIMIZERS = {"sgd": 0, "adagrad": 1, "adam": 2, "momentum": 3}

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is None:
            from .. import native
            lib = native.load_library("ps_table")
            lib.pt_create.restype = ctypes.c_void_p
            lib.pt_create.argtypes = [ctypes.c_int, ctypes.c_int64,
                                      ctypes.c_int, ctypes.c_float,
                                      ctypes.c_float, ctypes.c_float,
                                      ctypes.c_float]
            pf = ctypes.POINTER(ctypes.c_float)
            pu = ctypes.POINTER(ctypes.c_uint64)
            for name, argtypes in [
                ("pt_set_lr", [ctypes.c_void_p, ctypes.c_float]),
                ("pt_set_dense", [ctypes.c_void_p, pf, ctypes.c_int64]),
                ("pt_pull_dense", [ctypes.c_void_p, pf, ctypes.c_int64]),
                ("pt_push_dense", [ctypes.c_void_p, pf, ctypes.c_int64]),
                ("pt_add_dense", [ctypes.c_void_p, pf, ctypes.c_int64]),
                ("pt_pull_sparse", [ctypes.c_void_p, pu, ctypes.c_int64, pf]),
                ("pt_push_sparse", [ctypes.c_void_p, pu, ctypes.c_int64, pf]),
                ("pt_set_sparse", [ctypes.c_void_p, pu, ctypes.c_int64, pf]),
                ("pt_dump_sparse", [ctypes.c_void_p, pu, pf]),
                ("pt_free", [ctypes.c_void_p]),
            ]:
                getattr(lib, name).argtypes = argtypes
            lib.pt_sparse_size.restype = ctypes.c_int64
            lib.pt_sparse_size.argtypes = [ctypes.c_void_p]
            _lib = lib
    return _lib


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _uptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


class DenseTable:
    """Server-side dense parameter + optimizer state."""

    def __init__(self, shape, optimizer: str = "sgd", lr: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8):
        self.shape = tuple(int(d) for d in shape)
        self.size = int(np.prod(self.shape)) if self.shape else 1
        self.optimizer = optimizer
        lib = _load()
        self._h = lib.pt_create(0, self.size, OPTIMIZERS[optimizer],
                                lr, beta1, beta2, eps)
        self._lib = lib
        self.initialized = False

    def set(self, value: np.ndarray):
        v = np.ascontiguousarray(value, dtype=np.float32).reshape(-1)
        assert v.size == self.size, (v.size, self.size)
        self._lib.pt_set_dense(self._h, _fptr(v), self.size)
        self.initialized = True

    def pull(self) -> np.ndarray:
        out = np.empty((self.size,), np.float32)
        self._lib.pt_pull_dense(self._h, _fptr(out), self.size)
        return out.reshape(self.shape)

    def push(self, grad: np.ndarray, lr: Optional[float] = None):
        g = np.ascontiguousarray(grad, dtype=np.float32).reshape(-1)
        if lr is not None:
            self._lib.pt_set_lr(self._h, float(lr))
        self._lib.pt_push_dense(self._h, _fptr(g), self.size)

    def add(self, delta: np.ndarray):
        d = np.ascontiguousarray(delta, dtype=np.float32).reshape(-1)
        self._lib.pt_add_dense(self._h, _fptr(d), self.size)

    def __del__(self):
        try:
            self._lib.pt_free(self._h)
        except Exception:
            pass


class SparseTable:
    """Server-side uint64 -> float[dim] embedding table."""

    def __init__(self, dim: int, optimizer: str = "sgd", lr: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8):
        self.dim = int(dim)
        self.optimizer = optimizer
        lib = _load()
        self._h = lib.pt_create(1, self.dim, OPTIMIZERS[optimizer],
                                lr, beta1, beta2, eps)
        self._lib = lib
        self.initialized = True  # rows lazily zero-init

    def pull(self, keys: np.ndarray) -> np.ndarray:
        k = np.ascontiguousarray(keys, dtype=np.uint64).reshape(-1)
        out = np.empty((k.size, self.dim), np.float32)
        self._lib.pt_pull_sparse(self._h, _uptr(k), k.size, _fptr(out))
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray,
             lr: Optional[float] = None):
        k = np.ascontiguousarray(keys, dtype=np.uint64).reshape(-1)
        g = np.ascontiguousarray(grads, dtype=np.float32).reshape(k.size,
                                                                  self.dim)
        if lr is not None:
            self._lib.pt_set_lr(self._h, float(lr))
        self._lib.pt_push_sparse(self._h, _uptr(k), k.size, _fptr(g))

    def set(self, keys: np.ndarray, vals: np.ndarray):
        k = np.ascontiguousarray(keys, dtype=np.uint64).reshape(-1)
        v = np.ascontiguousarray(vals, dtype=np.float32).reshape(k.size,
                                                                 self.dim)
        self._lib.pt_set_sparse(self._h, _uptr(k), k.size, _fptr(v))

    def dump(self):
        n = self._lib.pt_sparse_size(self._h)
        keys = np.empty((n,), np.uint64)
        vals = np.empty((n, self.dim), np.float32)
        if n:
            self._lib.pt_dump_sparse(self._h, _uptr(keys), _fptr(vals))
        return keys, vals

    def __len__(self):
        return int(self._lib.pt_sparse_size(self._h))

    def __del__(self):
        try:
            self._lib.pt_free(self._h)
        except Exception:
            pass
