"""paddle.distributed.cloud_utils — parity with
python/paddle/distributed/cloud_utils.py (get_cloud_cluster:20,
get_trainers_num:79): derive the trainer cluster from the PaddleCloud
environment contract."""
from __future__ import annotations

import os

__all__ = ["get_cloud_cluster", "get_trainers_num"]


def get_trainers_num() -> int:
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def get_cloud_cluster(args_node_ips=None, args_node_ip=None,
                      args_port=None, selected_devices=None):
    """Cluster endpoints from the cloud env (PADDLE_TRAINERS /
    POD_IP / PADDLE_PORT), falling back to the explicit args."""
    node_ips = (os.getenv("PADDLE_TRAINERS") or args_node_ips
                or "127.0.0.1")
    if isinstance(node_ips, str):
        node_ips = [ip.strip() for ip in node_ips.replace(" ", ",").split(",")
                    if ip.strip()]
    node_ip = os.getenv("POD_IP", args_node_ip or node_ips[0])
    port = int(os.getenv("PADDLE_PORT", args_port or 6170))
    n_dev = len(selected_devices) if selected_devices else 1
    endpoints = [f"{ip}:{port + d}" for ip in node_ips
                 for d in range(n_dev)]
    cur = f"{node_ip}:{port}"
    if cur not in endpoints:
        # fail fast (the reference's node_ips.index raises too): a silent
        # rank-0 default would duplicate the coordinator
        raise ValueError(
            f"current endpoint {cur} is not in the cluster list "
            f"{endpoints} — check POD_IP/PADDLE_TRAINERS")
    return {
        "trainer_endpoints": endpoints,
        "current_endpoint": cur,
        "nranks": len(endpoints),
        "rank": endpoints.index(cur),
    }
