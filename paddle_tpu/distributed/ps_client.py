"""PS client — parity with the reference RPCClient
(operators/distributed/grpc/grpc_client.cc async_send/get semantics, used by
the send/recv ops and the Communicator)."""
from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional

import numpy as np

from .ps_server import recv_msg, send_msg


class PSClient:
    """One connection per (client, endpoint); thread-safe via a lock per
    connection (trainer host ops run sequentially anyway)."""

    _instances: Dict[int, "PSClient"] = {}
    _instances_lock = threading.Lock()

    def __init__(self, trainer_id: int = 0):
        self.trainer_id = trainer_id
        self._conns: Dict[str, socket.socket] = {}
        self._lock = threading.Lock()
        self._inited_params = set()

    @classmethod
    def instance(cls, trainer_id: int = 0) -> "PSClient":
        with cls._instances_lock:
            if trainer_id not in cls._instances:
                cls._instances[trainer_id] = cls(trainer_id)
            return cls._instances[trainer_id]

    @classmethod
    def reset_all(cls):
        with cls._instances_lock:
            for c in cls._instances.values():
                c.close()
            cls._instances.clear()

    # ------------------------------------------------------------------
    def _conn(self, endpoint: str) -> socket.socket:
        with self._lock:
            s = self._conns.get(endpoint)
            if s is None:
                host, port = endpoint.rsplit(":", 1)
                s = self._wait_connect(host or "127.0.0.1", int(port))
                self._conns[endpoint] = s
            return s

    @staticmethod
    def _wait_connect(host, port, timeout: float = 30.0):
        """wait_port parity (distribute_transpiler config wait_port)."""
        deadline = time.time() + timeout
        while True:
            try:
                s = socket.create_connection((host, port), timeout=5)
                # RPC-style request/response framing: Nagle would hold the
                # frame header back waiting for the server's delayed ACK
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return s
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)

    # cmds safe to resend after a transport error: reads with no
    # server-side state change. push/push_delta/barrier must NOT be
    # auto-resent — the server may have applied the request and only the
    # reply was lost (double-applied grads / double-counted barriers).
    _IDEMPOTENT = frozenset({"pull", "pull_sparse"})

    def _rpc(self, endpoint: str, msg: dict, _retries: int = 3) -> dict:
        """One request/response, with reconnect-and-backoff on transport
        errors (grpc_client.cc channel reconnection parity) for
        idempotent commands; non-idempotent commands fail fast after
        cleaning up the dead connection."""
        if msg.get("cmd") not in self._IDEMPOTENT:
            _retries = 0
        delay = 0.2
        for attempt in range(_retries + 1):
            try:
                sock = self._conn(endpoint)
                with self._lock:
                    send_msg(sock, msg)
                    reply = recv_msg(sock)
                if reply is None:
                    raise ConnectionError(
                        f"pserver {endpoint} closed connection")
                break
            except (ConnectionError, OSError):
                with self._lock:
                    s = self._conns.pop(endpoint, None)
                    if s is not None:
                        try:
                            s.close()
                        except OSError:
                            pass
                if attempt == _retries:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 2.0)
        if reply.get("status") == "error":
            raise RuntimeError(f"pserver {endpoint}: {reply['error']}")
        return reply

    # -- op-facing API ------------------------------------------------------
    def ensure_init(self, endpoint: str, param: str, value: np.ndarray):
        """First-touch init: server keeps the first value it sees
        (pserver startup-program initialization parity)."""
        if (endpoint, param) in self._inited_params:
            return
        self._rpc(endpoint, {"cmd": "init_param", "param": param,
                             "value": np.asarray(value, np.float32)})
        self._inited_params.add((endpoint, param))

    def push(self, endpoint: str, param: str, grad: np.ndarray,
             lr: Optional[float] = None):
        self._rpc(endpoint, {"cmd": "push", "param": param,
                             "value": np.asarray(grad, np.float32),
                             "lr": lr, "trainer_id": self.trainer_id})

    def push_delta(self, endpoint: str, param: str, delta: np.ndarray):
        self._rpc(endpoint, {"cmd": "push_delta", "param": param,
                             "value": np.asarray(delta, np.float32)})

    def pull(self, endpoint: str, param: str) -> np.ndarray:
        return self._rpc(endpoint, {"cmd": "pull", "param": param,
                                    "trainer_id": self.trainer_id})["value"]

    def pull_sparse(self, endpoint: str, param: str,
                    keys: np.ndarray) -> np.ndarray:
        return self._rpc(endpoint, {"cmd": "pull_sparse", "param": param,
                                    "keys": np.asarray(keys, np.uint64)})["value"]

    def push_sparse(self, endpoint: str, param: str, keys: np.ndarray,
                    grads: np.ndarray, lr: Optional[float] = None):
        self._rpc(endpoint, {"cmd": "push_sparse", "param": param,
                             "keys": np.asarray(keys, np.uint64),
                             "value": np.asarray(grads, np.float32),
                             "lr": lr})

    def barrier(self, endpoints, name: str):
        for ep in endpoints:
            self._rpc(ep, {"cmd": "barrier", "name": name,
                           "trainer_id": self.trainer_id})

    def complete(self, endpoints):
        for ep in endpoints:
            try:
                self._rpc(ep, {"cmd": "complete",
                               "trainer_id": self.trainer_id})
            except (OSError, ConnectionError):
                pass

    def checkpoint_notify(self, endpoint: str, dirname: str):
        self._rpc(endpoint, {"cmd": "save", "dirname": dirname})

    def stop_server(self, endpoint: str):
        try:
            self._rpc(endpoint, {"cmd": "stop"})
        except (OSError, ConnectionError, EOFError):
            pass

    def close(self):
        with self._lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
            self._inited_params.clear()
