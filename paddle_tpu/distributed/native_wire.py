"""ctypes binding over the native PS wire loop (native/ps_wire.cpp).

The C++ library owns the listen socket and the connection threads; hot
commands run GIL-free against the ps_table.cpp handles, control commands
come back into Python through the deferred callback (ctypes re-acquires
the GIL per call; blocking waits inside the handler — sync rounds,
barriers — release it again through the usual lock waits).
"""
from __future__ import annotations

import ctypes
import os

from .. import native
from . import table as _table

_DEFER_CB = ctypes.CFUNCTYPE(
    ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64)

_wire_lib = None


def enabled() -> bool:
    return os.environ.get("PADDLE_TPU_PS_NATIVE_WIRE", "1") not in (
        "0", "false", "off")


def _load():
    global _wire_lib
    if _wire_lib is None:
        lib = native.load_library("ps_wire")
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.pt_wire_create.restype = ctypes.c_void_p
        lib.pt_wire_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_int)]
        lib.pt_wire_set_table_fns.argtypes = [ctypes.c_void_p] + \
            [ctypes.c_void_p] * 6
        lib.pt_wire_set_deferred.argtypes = [ctypes.c_void_p, _DEFER_CB]
        lib.pt_wire_register.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int64, i64p, ctypes.c_int, ctypes.c_int]
        lib.pt_wire_mark_initialized.restype = ctypes.c_int
        lib.pt_wire_mark_initialized.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_char_p]
        for n in ("pt_wire_start", "pt_wire_stop", "pt_wire_destroy"):
            getattr(lib, n).argtypes = [ctypes.c_void_p]
        lib.pt_wire_port.restype = ctypes.c_int
        lib.pt_wire_port.argtypes = [ctypes.c_void_p]
        _wire_lib = lib
    return _wire_lib


class NativeWire:
    def __init__(self, server):
        self._srv = server
        self._lib = _load()
        tl = _table._load()
        port_out = ctypes.c_int(0)
        # dense pushes run natively ONLY in pure-async mode; sync (0),
        # half-async (2) and GEO (3) defer to the Python round machinery
        async_dense = (not server.sync_mode) and server.mode == 1
        self._h = self._lib.pt_wire_create(
            server.host.encode(), int(server.port),
            1 if async_dense else 0, ctypes.byref(port_out))
        if not self._h:
            raise RuntimeError(
                f"native wire bind failed on {server.host}:{server.port}")
        server.port = port_out.value
        self._lib.pt_wire_set_table_fns(self._h, *[
            ctypes.cast(getattr(tl, n), ctypes.c_void_p)
            for n in ("pt_set_lr", "pt_pull_dense", "pt_push_dense",
                      "pt_set_dense", "pt_pull_sparse", "pt_push_sparse")])
        # the callback object must outlive the server: C++ threads call it
        self._cb = _DEFER_CB(self._deferred)
        self._lib.pt_wire_set_deferred(self._h, self._cb)
        self._stopped = False

    def register(self, name: str, st) -> None:
        t = st.table
        if isinstance(t, _table.DenseTable):
            shape = (ctypes.c_int64 * max(len(t.shape), 1))(*(t.shape
                                                              or (1,)))
            self._lib.pt_wire_register(
                self._h, name.encode(), ctypes.c_void_p(t._h), 0, t.size,
                shape, len(t.shape) or 1, 1 if t.initialized else 0)
        else:
            shape = (ctypes.c_int64 * 1)(0)
            self._lib.pt_wire_register(
                self._h, name.encode(), ctypes.c_void_p(t._h), 1, t.dim,
                shape, 0, 1)

    def mark_initialized(self, name: str) -> bool:
        return bool(self._lib.pt_wire_mark_initialized(self._h,
                                                       name.encode()))

    def start(self) -> None:
        self._lib.pt_wire_start(self._h)

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._lib.pt_wire_stop(self._h)

    def _deferred(self, frame_ptr, frame_len, resp_ptr, cap) -> int:
        from . import ps_server as W

        try:
            raw = ctypes.string_at(frame_ptr, frame_len)
            msg = W.decode_msg(raw)
            if msg is None:
                raise ConnectionError("truncated deferred frame")
            reply = self._srv._handle_deferred(msg)
            out = W.encode_msg(reply)
            if len(out) > cap:
                out = W.encode_msg({"status": "error",
                                    "error": "deferred reply too large"})
            ctypes.memmove(resp_ptr, out, len(out))
            return len(out)
        except Exception as e:  # the C++ thread cannot take an exception
            try:
                out = W.encode_msg({"status": "error", "error": repr(e)})
                if len(out) <= cap:
                    ctypes.memmove(resp_ptr, out, len(out))
                    return len(out)
            except Exception:
                pass
            return -1
