"""Parameter server — host-side service replacing the reference's gRPC
listen_and_serv runtime (operators/distributed_ops/listen_and_serv_op.cc +
operators/distributed/request_handler_impl.cc).

Transport: a framed binary protocol over TCP sockets (one thread per
connection, like the reference's gRPC thread pool), mirroring the
reference's VariableMessage shape (send_recv.proto.in:19-34): a JSON
header for scalar fields + raw dtype/shape-prefixed tensor buffers.  No
pickle touches network bytes — a hostile peer can at worst inject data,
not code — and ndarray payloads move as single memoryview writes instead
of whole-object pickling.  The arithmetic hot path — optimizer updates on
dense params and sparse embedding rows — is native C++
(native/ps_table.cpp) behind the Table classes.

Sync semantics (reference `Communicator` Sync / request_handler barriers):
pushes to a param accumulate until `trainer_num` arrived, then the averaged
gradient is applied once and the param version advances; `barrier` gives the
trainer-side send/fetch barriers.  Async: every push applies immediately.
GEO: trainers push param deltas which are added raw.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Dict, Optional

import numpy as np

from .table import DenseTable, SparseTable

_MAGIC = b"PT"
_VERSION = 1
# frame: magic(2) ver(1) ntensor(1) | json_len(u32) | total_len(u64)
_FRAME = struct.Struct("<2sBBIQ")
# per tensor: name_len(u16) | dtype_len(u8) | ndim(u8) | data_len(u64)
_THDR = struct.Struct("<HBBQ")
_MAX_FRAME = 1 << 34            # 16 GiB sanity bound on declared lengths


def _build_frame(obj: dict):
    """Shared serializer: returns (frame_header, json_header, parts) where
    parts holds tensor metas (bytes) and zero-copy array views."""
    scalars, tensors = {}, []
    for k, v in obj.items():
        if isinstance(v, np.ndarray):
            tensors.append((k, np.ascontiguousarray(v)))
        elif hasattr(v, "shape") and hasattr(v, "dtype"):   # jax array etc.
            tensors.append((k, np.ascontiguousarray(np.asarray(v))))
        else:
            scalars[k] = v
    hdr = json.dumps(scalars, separators=(",", ":")).encode()
    parts = []
    total = 0
    for name, arr in tensors:
        nb = name.encode()
        dt = np.lib.format.dtype_to_descr(arr.dtype).encode()
        meta = _THDR.pack(len(nb), len(dt), arr.ndim, arr.nbytes) + nb + dt
        meta += struct.pack(f"<{arr.ndim}q", *arr.shape)
        parts.append(meta)
        if arr.nbytes:      # zero-size tensors have no payload bytes (and
            parts.append(memoryview(arr).cast("B"))  # cast() rejects them)
        total += len(meta) + arr.nbytes
    frame = _FRAME.pack(_MAGIC, _VERSION, len(tensors), len(hdr),
                        len(hdr) + total)
    return frame, hdr, parts


def send_msg(sock: socket.socket, obj: dict) -> None:
    """Serialize a flat dict of JSON scalars + ndarrays (VariableMessage
    framing: header describes, raw buffers follow)."""
    frame, hdr, parts = _build_frame(obj)
    # ONE gather-send for the whole message: the old frame/header/meta
    # sendall sequence emitted several tiny TCP segments before the bulk
    # buffers, and Nagle + delayed ACK stalled each message ~40 ms (found
    # by tools/ps_bench.py). sendmsg writes the iovec zero-copy.
    _sendall_vec(sock, [frame, hdr] + parts)


def _sendall_vec(sock: socket.socket, parts) -> None:
    bufs = [p if isinstance(p, memoryview) else memoryview(p) for p in parts]
    # drop zero-length views: sendmsg reports 0 bytes for them and the
    # advance loop below could never retire them
    bufs = [b.cast("B") for b in bufs if len(b)]
    while bufs:
        sent = sock.sendmsg(bufs[:64])      # stay far under IOV_MAX
        while sent:
            if sent >= len(bufs[0]):
                sent -= len(bufs[0])
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][sent:]
                sent = 0


def recv_msg(sock: socket.socket):
    raw = _recv_exact(sock, _FRAME.size)
    if raw is None:
        return None
    magic, ver, ntensor, json_len, total_len = _FRAME.unpack(raw)
    if magic != _MAGIC or ver != _VERSION:
        raise ConnectionError("bad PS frame (wrong protocol or version)")
    if json_len > _MAX_FRAME or total_len > _MAX_FRAME:
        raise ConnectionError("PS frame length out of bounds")
    hdr = _recv_exact(sock, json_len)
    if hdr is None:
        return None
    obj = json.loads(hdr.decode())
    consumed = json_len
    for _ in range(ntensor):
        meta = _recv_exact(sock, _THDR.size)
        if meta is None:
            return None
        name_len, dt_len, ndim, data_len = _THDR.unpack(meta)
        if data_len > _MAX_FRAME:
            raise ConnectionError("PS tensor length out of bounds")
        rest = _recv_exact(sock, name_len + dt_len + 8 * ndim)
        if rest is None:
            return None
        name = rest[:name_len].decode()
        descr = rest[name_len:name_len + dt_len].decode()
        shape = struct.unpack(f"<{ndim}q", rest[name_len + dt_len:])
        data = _recv_exact(sock, data_len)
        if data is None:
            return None
        # tensors merge into the same dict as the JSON scalars: a peer that
        # names a tensor after a control field ('status', 'cmd', ...) could
        # shadow it with an ndarray — refuse the collision outright
        if name in obj:
            raise ConnectionError(
                f"PS tensor name {name!r} collides with a header field")
        arr = np.frombuffer(data, dtype=np.lib.format.descr_to_dtype(descr))
        obj[name] = arr.reshape(shape)
        consumed += _THDR.size + name_len + dt_len + 8 * ndim + data_len
    # the frame declared json_len + tensor-section bytes up front; a mismatch
    # means a corrupt or lying peer and would desync every later frame
    if consumed != total_len:
        raise ConnectionError(
            f"PS frame length mismatch: declared {total_len}, read {consumed}")
    return obj


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            return None
        got += r
    return buf          # writable: np.frombuffer views stay mutable


class _BytesConn:
    """recv_into-compatible reader over a captured frame (the native wire
    hands deferred messages to Python as raw bytes)."""

    def __init__(self, data):
        self._d = memoryview(data)
        self._o = 0

    def recv_into(self, view, n):
        n = min(n, len(self._d) - self._o)
        view[:n] = self._d[self._o:self._o + n]
        self._o += n
        return n


def decode_msg(data) -> Optional[dict]:
    """Parse one complete frame from bytes (same checks as recv_msg)."""
    return recv_msg(_BytesConn(data))


def encode_msg(obj: dict) -> bytes:
    """Serialize one frame to bytes (same layout send_msg writes)."""
    frame, hdr, parts = _build_frame(obj)
    return frame + hdr + b"".join(bytes(p) for p in parts)


class _ParamState:
    def __init__(self, table):
        self.table = table
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.accum: Optional[np.ndarray] = None
        self.accum_lr: Optional[float] = None
        self.push_count = 0
        self.contributors: set = set()
        self.version = 0


class ParameterServer:
    """One PS endpoint.  Construct from table configs, then serve()."""

    def __init__(self, endpoint: str, trainer_num: int = 1,
                 sync_mode: bool = True, mode: int = 0):
        host, port = endpoint.rsplit(":", 1)
        self.host, self.port = host or "127.0.0.1", int(port)
        self.trainer_num = trainer_num
        self.sync_mode = sync_mode
        # DistributedMode: 0 sync / 1 async / 2 half-async / 3 geo
        self.mode = mode
        self.params: Dict[str, _ParamState] = {}
        self._barriers: Dict[str, tuple] = {}
        self._barrier_lock = threading.Lock()
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._threads = []
        self._completed_trainers = set()  # HeartBeatMonitor-style liveness
        self._native = None               # native wire loop, when built

    # -- table config -------------------------------------------------------
    def register_dense(self, name: str, shape, optimizer="sgd", lr=0.01,
                       **hparams):
        if name not in self.params:
            self.params[name] = _ParamState(
                DenseTable(shape, optimizer, lr, **hparams))
            if self._native is not None:
                self._native.register(name, self.params[name])

    def register_sparse(self, name: str, dim: int, optimizer="sgd", lr=0.01,
                        **hparams):
        if name not in self.params:
            self.params[name] = _ParamState(
                SparseTable(dim, optimizer, lr, **hparams))
            if self._native is not None:
                self._native.register(name, self.params[name])

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        """Bind + serve; returns once listening.

        Transport: the native C++ wire loop (native/ps_wire.cpp — hot
        commands GIL-free against the C++ tables, control commands
        deferred back here) when it builds, else the Python
        thread-per-connection loop."""
        from . import native_wire

        if native_wire.enabled():
            try:
                self._native = native_wire.NativeWire(self)
                for name, st in self.params.items():
                    self._native.register(name, st)
                self._native.start()
                return self
            except Exception as e:
                print(f"[ps_server] native wire unavailable "
                      f"({type(e).__name__}: {e}); Python transport")
                self._native = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        if self.port == 0:
            self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def serve_forever(self):
        """Blocking serve — what the listen_and_serv host op calls."""
        if self._native is None and self._sock is None:
            self.start()
        self._stop.wait()

    def stop(self):
        self._stop.set()
        if self._native is not None:
            self._native.stop()
            return
        try:
            if self._sock is not None:
                # unblock accept
                poke = socket.create_connection((self.host, self.port),
                                                timeout=1)
                poke.close()
        except OSError:
            pass
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    # -- serving ------------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                break
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                msg = recv_msg(conn)
                if msg is None:
                    return
                try:
                    reply = self._handle(msg)
                except Exception as e:  # surface handler errors to client
                    reply = {"status": "error", "error": repr(e)}
                send_msg(conn, reply)
                if msg.get("cmd") == "stop":
                    return
        finally:
            conn.close()

    def _handle_deferred(self, msg):
        """Entry point for control frames the native wire hands back.

        init_param only defers on a dtype/size mismatch; the first-value-
        wins flag lives in the native table registry, so consult it before
        writing (a racing native init may have won already)."""
        try:
            if msg.get("cmd") == "init_param" and self._native is not None:
                name = msg.get("param")
                st = self.params.get(name)
                if st is None:
                    return {"status": "error",
                            "error": f"unknown param {name!r}"}
                if self._native.mark_initialized(name):
                    with st.lock:
                        st.table.set(msg["value"])
                return {"status": "ok", "initialized": True}
            return self._handle(msg)
        except Exception as e:
            return {"status": "error", "error": repr(e)}

    # -- request handlers (request_handler_impl.cc parity) -----------------
    def _handle(self, msg):
        cmd = msg["cmd"]
        if cmd == "ping":
            return {"status": "ok"}
        if cmd == "stop":
            self.stop()
            return {"status": "ok"}
        if cmd == "barrier":
            self._barrier(msg["name"], msg["trainer_id"])
            return {"status": "ok"}
        if cmd == "complete":  # trainer finished (HeartBeatMonitor COMPLETED)
            self._completed_trainers.add(msg["trainer_id"])
            self._on_membership_change()
            return {"status": "ok"}
        if cmd == "save":
            return self._save(msg.get("dirname"))

        name = msg.get("param")
        st = self.params.get(name)
        if st is None:
            return {"status": "error", "error": f"unknown param {name!r}"}

        if cmd == "init_param":
            with st.lock:
                if not st.table.initialized:
                    st.table.set(msg["value"])
                return {"status": "ok", "initialized": True}
        if cmd == "pull":
            with st.lock:
                if self.sync_mode:
                    # serve the freshest applied version; trainers order
                    # pulls behind their send barrier so no wait needed
                    return {"status": "ok", "value": st.table.pull(),
                            "version": st.version}
                return {"status": "ok", "value": st.table.pull(),
                        "version": st.version}
        if cmd == "push":
            self._push_dense(st, msg)
            return {"status": "ok"}
        if cmd == "push_delta":  # GEO
            with st.lock:
                st.table.add(msg["value"])
                st.version += 1
            return {"status": "ok"}
        if cmd == "pull_sparse":
            with st.lock:
                return {"status": "ok", "value": st.table.pull(msg["keys"])}
        if cmd == "push_sparse":
            with st.lock:
                st.table.push(msg["keys"], msg["value"], msg.get("lr"))
            return {"status": "ok"}
        return {"status": "error", "error": f"unknown cmd {cmd!r}"}

    def _apply_round_locked(self, st: _ParamState):
        """Apply the accumulated sync round (caller holds st.cond)."""
        st.table.push((st.accum / st.push_count).astype(np.float32),
                      st.accum_lr)
        st.accum = None
        st.push_count = 0
        st.contributors.clear()
        st.version += 1
        st.cond.notify_all()

    def _accumulate_locked(self, st: _ParamState, grad, lr, trainer_id):
        """Add one contribution to the open round (caller holds st.cond);
        returns True when every live trainer has contributed. Distinct
        trainers are tracked so a fast pusher cannot complete a round
        alone (half-async pushes never block)."""
        if st.accum is None:
            st.accum = grad.astype(np.float64)
        else:
            st.accum += grad
        st.accum_lr = lr if lr is not None else st.accum_lr
        st.push_count += 1
        if trainer_id is not None:
            st.contributors.add(trainer_id)
        live = self._live_trainers()
        done = (len(st.contributors) >= live if st.contributors
                else st.push_count >= live)
        return done

    def _live_trainers(self) -> int:
        return max(self.trainer_num - len(self._completed_trainers), 1)

    def _on_membership_change(self):
        """A trainer completed: waiters must recompute `need` — a round that
        is now fully contributed by the remaining live trainers applies, and
        barriers that are now satisfied release (HeartBeatMonitor eviction
        semantics)."""
        for st in self.params.values():
            with st.cond:
                if st.push_count >= self._live_trainers() and st.accum is not None:
                    self._apply_round_locked(st)
                else:
                    st.cond.notify_all()  # let waiters re-evaluate
        with self._barrier_lock:
            for count_gen in self._barriers.values():
                if count_gen[0] >= self._live_trainers() and count_gen[0] > 0:
                    count_gen[0] = 0
                    count_gen[2] += 1
                count_gen[1].notify_all()

    def _push_dense(self, st: _ParamState, msg):
        grad = np.asarray(msg["value"], np.float32)
        lr = msg.get("lr")
        tid = msg.get("trainer_id")
        with st.cond:
            if self.mode == 2:
                # HALF_ASYNC (communicator.h:299): aggregate a full round
                # from all live trainers before applying — like sync — but
                # pushers never block on the applied version
                if self._accumulate_locked(st, grad, lr, tid):
                    self._apply_round_locked(st)
                return
            if not self.sync_mode:
                st.table.push(grad, lr)
                st.version += 1
                return
            # sync: accumulate until all live trainers contributed
            if self._accumulate_locked(st, grad, lr, tid):
                self._apply_round_locked(st)
            else:
                target = st.version + 1
                while st.version < target and not self._stop.is_set():
                    st.cond.wait(timeout=0.5)
                    # membership may have shrunk while we waited
                    if (st.version < target and st.accum is not None
                            and st.push_count >= self._live_trainers()):
                        self._apply_round_locked(st)

    def _barrier(self, name: str, trainer_id: int):
        with self._barrier_lock:
            if name not in self._barriers:
                self._barriers[name] = [0, threading.Condition(
                    self._barrier_lock), 0]
        count_gen = self._barriers[name]
        with count_gen[1]:
            count_gen[0] += 1
            if count_gen[0] >= self._live_trainers():
                count_gen[0] = 0
                count_gen[2] += 1  # generation
                count_gen[1].notify_all()
            else:
                gen = count_gen[2]
                while count_gen[2] == gen and not self._stop.is_set():
                    count_gen[1].wait(timeout=0.5)
                    if (count_gen[2] == gen
                            and count_gen[0] >= self._live_trainers()):
                        count_gen[0] = 0
                        count_gen[2] += 1
                        count_gen[1].notify_all()

    def _save(self, dirname):
        import os
        if not dirname:
            return {"status": "error", "error": "no dirname"}
        os.makedirs(dirname, exist_ok=True)
        for name, st in self.params.items():
            with st.lock:
                if isinstance(st.table, DenseTable):
                    np.save(os.path.join(dirname, name.replace("/", "_")),
                            st.table.pull())
                else:
                    keys, vals = st.table.dump()
                    np.savez(os.path.join(dirname,
                                          name.replace("/", "_") + ".sparse"),
                             keys=keys, vals=vals)
        return {"status": "ok"}
