"""Program IR static-analysis framework: one walker, a checker registry,
and typed findings.

The reference Paddle validates programs op-by-op at build time in C++
(``InferShape`` / ``InferVarType`` inside OpDesc construction,
framework/op_desc.cc, plus graph passes under framework/ir/). Our
trace-to-XLA design has no per-op kernel boundary to hang those checks on
— a malformed ProgramDesc surfaces as a cryptic trace-time exception, and
an inconsistent collective lowering as a multi-rank hang. This package is
the replacement: a pure-metadata pass over the Program IR that runs in
milliseconds, BEFORE anything is traced or compiled.

Three entry points share it (docs/static_analysis.md):

- ``tools/paddle_lint.py`` — CLI; ``--all-models`` runs every built-in
  model program (``analysis/model_corpus.py``) and exits non-zero on
  error-severity findings;
- ``Executor.run`` — pre-compile hook behind ``FLAGS_check_program``
  (checked once per program version, never on the dispatch fast path);
- ``tests/test_static_analysis.py`` — the pytest gate: built-in programs
  must be error-clean, and each seeded bad-program fixture must fire its
  checker.

Severity policy:

- **error** — the program is wrong: it will crash at trace time, hang a
  multi-rank job, or silently compute the wrong thing. Gates exit
  non-zero; the executor hook raises.
- **warning** — legal but almost certainly not what you meant (sub-f32
  accumulation, recompile churn, donated-state aliasing). Logged, counted.
- **info** — observations that feed other tooling (dead vars, inference
  coverage gaps). Hidden by default in the CLI.

Every finding increments ``paddle_lint_findings_total{severity}`` in the
observability registry, so lint noise shows up in the same Prometheus /
JSONL pipeline as the runtime telemetry (tools/metrics_check.py gates it).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

from ..observability import metrics as _obs_metrics

__all__ = [
    "ERROR", "WARNING", "INFO", "SEVERITIES",
    "Finding", "AnalysisContext", "AnalysisResult",
    "register_checker", "all_checkers", "get_checker", "analyze_program",
    "op_reads", "op_writes", "iter_block_ops",
]

# severities, ordered: gates compare with SEVERITIES.index
ERROR = "error"
WARNING = "warning"
INFO = "info"
SEVERITIES = (INFO, WARNING, ERROR)

_m_findings = _obs_metrics.default_registry().counter(
    "paddle_lint_findings_total",
    "Static-analysis findings by severity (paddle_tpu.analysis)",
    ("severity",))


@dataclasses.dataclass
class Finding:
    """One static-analysis finding, anchored to an op and/or var."""

    checker: str                    # registered checker name
    code: str                       # stable machine code, e.g. "use_before_def"
    severity: str                   # error | warning | info
    message: str                    # human-readable, self-contained
    block_idx: int = 0
    op_idx: Optional[int] = None    # index into block.ops (None = whole block)
    op_type: Optional[str] = None
    var: Optional[str] = None       # offending variable name, if any

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity {self.severity!r}")

    @property
    def location(self) -> str:
        loc = f"block {self.block_idx}"
        if self.op_idx is not None:
            loc += f" op {self.op_idx}"
            if self.op_type:
                loc += f" ({self.op_type})"
        if self.var:
            loc += f" var {self.var!r}"
        return loc

    def format(self) -> str:
        return (f"[{self.severity.upper():7s}] {self.checker}:{self.code} "
                f"@ {self.location} — {self.message}")

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class AnalysisContext:
    """Everything a checker may consult beyond the program itself.

    ``peer_programs`` holds the OTHER ranks' programs for SPMD order
    matching (transpiler output is one program per rank); ``donated`` is
    the executable's donation map when the caller has one (PR 4 program
    reports carry it) — otherwise checkers re-derive it from the IR the
    same way the executor does; ``bucket_layouts`` are per-rank
    ``comm_opt.BucketLayout`` plans for the bucket-consistency check.
    """

    def __init__(self, program, feed_names: Sequence[str] = (),
                 fetch_names: Sequence[str] = (),
                 peer_programs: Sequence[Any] = (),
                 donated: Optional[Sequence[str]] = None,
                 bucket_layouts: Sequence[Any] = (),
                 live_mesh: Optional[Dict[str, int]] = None,
                 flags: Optional[Dict[str, Any]] = None):
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.peer_programs = list(peer_programs)
        self.donated = list(donated) if donated is not None else None
        self.bucket_layouts = list(bucket_layouts)
        # {axis: size} of the mesh the caller is ABOUT to run/restore on;
        # the sharding checker diffs it against the program's annotated
        # mesh (mesh_mismatch_at_restore)
        self.live_mesh = dict(live_mesh) if live_mesh is not None else None
        if flags is None:
            from ..framework.core import flags_snapshot

            flags = flags_snapshot()
        self.flags = flags


class AnalysisResult:
    """Ordered findings + convenience filters/summary."""

    def __init__(self, findings: List[Finding]):
        self.findings = list(findings)

    def _sev(self, sev: str) -> List[Finding]:
        return [f for f in self.findings if f.severity == sev]

    @property
    def errors(self) -> List[Finding]:
        return self._sev(ERROR)

    @property
    def warnings(self) -> List[Finding]:
        return self._sev(WARNING)

    @property
    def infos(self) -> List[Finding]:
        return self._sev(INFO)

    @property
    def ok(self) -> bool:
        return not self.errors

    def by_checker(self, name: str) -> List[Finding]:
        return [f for f in self.findings if f.checker == name]

    def counts(self) -> Dict[str, int]:
        return {sev: len(self._sev(sev)) for sev in SEVERITIES}

    def summary(self) -> str:
        c = self.counts()
        return (f"{c[ERROR]} error(s), {c[WARNING]} warning(s), "
                f"{c[INFO]} info")

    def format(self, min_severity: str = WARNING) -> str:
        floor = SEVERITIES.index(min_severity)
        lines = [f.format() for f in self.findings
                 if SEVERITIES.index(f.severity) >= floor]
        return "\n".join(lines + [self.summary()])

    def __repr__(self):
        return f"AnalysisResult({self.summary()})"


# ---------------------------------------------------------------------------
# Checker registry
# ---------------------------------------------------------------------------

CheckerFn = Callable[[AnalysisContext], Iterable[Finding]]

_CHECKERS: "Dict[str, CheckerFn]" = {}


def register_checker(name: str):
    """Decorator: ``@register_checker("program_verifier")``.  A checker is
    ``fn(ctx: AnalysisContext) -> Iterable[Finding]`` and must never
    mutate the program (restore anything it touches)."""

    def deco(fn: CheckerFn):
        _CHECKERS[name] = fn
        fn.checker_name = name
        return fn

    return deco


def all_checkers() -> List[str]:
    _load_builtin_checkers()
    return sorted(_CHECKERS)


def get_checker(name: str) -> CheckerFn:
    _load_builtin_checkers()
    return _CHECKERS[name]


def _load_builtin_checkers():
    # import for side effect (registration); idempotent
    from . import (collectives, donation, precision,  # noqa: F401
                   recompile, shapes, sharding, verifier)


def analyze_program(program, feed_names: Sequence[str] = (),
                    fetch_names: Sequence[str] = (),
                    checkers: Optional[Sequence[str]] = None,
                    **ctx_kwargs) -> AnalysisResult:
    """Run ``checkers`` (default: all registered) over one program.

    Findings are ordered (checker registration order, then program order)
    and counted into ``paddle_lint_findings_total{severity}``. A checker
    that raises is reported as an error-severity ``checker_crash`` finding
    instead of taking the analysis down — the linter must stay usable on
    programs weirder than its authors imagined.
    """
    _load_builtin_checkers()
    ctx = AnalysisContext(program, feed_names=feed_names,
                          fetch_names=fetch_names, **ctx_kwargs)
    names = list(checkers) if checkers is not None else all_checkers()
    findings: List[Finding] = []
    for name in names:
        fn = _CHECKERS[name]
        try:
            findings.extend(fn(ctx))
        except Exception as e:  # pragma: no cover - defensive
            findings.append(Finding(
                checker=name, code="checker_crash", severity=ERROR,
                message=f"checker raised {type(e).__name__}: {e}"))
    for f in findings:
        _m_findings.labels(f.severity).inc()
    return AnalysisResult(findings)


# ---------------------------------------------------------------------------
# Walker helpers shared by checkers
# ---------------------------------------------------------------------------

def op_reads(op) -> List[str]:
    return [n for names in op.inputs.values() for n in names
            if n and n != "@EMPTY@"]


def op_writes(op) -> List[str]:
    return [n for names in op.outputs.values() for n in names
            if n and n != "@EMPTY@"]


def iter_block_ops(program):
    """Yield (block, op_idx, op) over every block in index order."""
    for block in program.blocks:
        for i, op in enumerate(block.ops):
            yield block, i, op
