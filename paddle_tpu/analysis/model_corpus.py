"""Built-in model programs for the lint gate.

Every program family the framework ships is built here in a small
configuration and handed to the checkers: the CLI (``tools/paddle_lint.py
--all-models``) and the pytest gate (tests/test_static_analysis.py) both
demand zero error-severity findings on each of them, so any checker
regression or program-builder regression trips tier-1.

Builders construct under fresh ``Program``/``unique_name`` guards and
never execute anything — transpiled PS programs include
``listen_and_serv``/``send``/``recv`` host ops but no server is started.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["MODEL_BUILDERS", "build_model_program", "model_names",
           "ModelProgram"]


class ModelProgram:
    """One built program + the feed/fetch context the checkers need."""

    def __init__(self, name, main, startup=None, feed_names=(),
                 fetch_names=(), peer_programs=(), extra=None):
        self.name = name
        self.main = main
        self.startup = startup
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.peer_programs = list(peer_programs)
        self.extra = extra or {}


def _fluid():
    import paddle_tpu as fluid

    return fluid


def _guarded(build):
    """Run a builder under fresh program + unique-name guards."""
    fluid = _fluid()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.unique_name.guard():
        with fluid.program_guard(main, startup):
            out = build(fluid)
    return main, startup, out


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def build_mlp() -> ModelProgram:
    def b(fluid):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        h = fluid.layers.fc(x, 32, act="relu")
        logits = fluid.layers.fc(h, 4)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        return loss

    main, startup, loss = _guarded(b)
    return ModelProgram("mlp", main, startup, ["x", "y"], [loss.name])


def build_gpt() -> ModelProgram:
    """Static-graph GPT-style LM head: embedding -> fc stack -> tied
    vocab projection -> softmax CE (the flagship decoder itself is the
    pure-JAX models/gpt.py; this is its fluid-program counterpart at lint
    scale)."""
    def b(fluid):
        V, T, D = 64, 8, 32
        tok = fluid.layers.data("tokens", [T], dtype="int64")
        lbl = fluid.layers.data("labels", [T, 1], dtype="int64")
        emb = fluid.layers.embedding(tok, size=[V, D],
                                     param_attr=fluid.ParamAttr("wte"))
        h = fluid.layers.fc(emb, D, num_flatten_dims=2, act="relu")
        h = fluid.layers.fc(h, D, num_flatten_dims=2, act="relu")
        logits = fluid.layers.fc(h, V, num_flatten_dims=2)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.Adam(1e-3).minimize(loss)
        return loss

    main, startup, loss = _guarded(b)
    return ModelProgram("gpt", main, startup, ["tokens", "labels"],
                        [loss.name])


def build_ernie() -> ModelProgram:
    """The ERNIE program shape: the fluid transformer encoder classifier
    (models/transformer_encoder.py — the static counterpart of
    models/ernie.py)."""
    def b(fluid):
        from paddle_tpu.models.transformer_encoder import (
            transformer_encoder_classifier)

        V, T = 32, 8
        src = fluid.layers.data("src", [T], dtype="int64")
        pos = fluid.layers.data("pos", [T], dtype="int64")
        label = fluid.layers.data("label", [1], dtype="int64")
        loss, _logits = transformer_encoder_classifier(
            src, pos, label, vocab_size=V, max_pos=T, num_layers=2,
            num_heads=4, d_model=32, d_ff=64, num_classes=2)
        fluid.optimizer.Adam(2e-3).minimize(loss)
        return loss

    main, startup, loss = _guarded(b)
    return ModelProgram("ernie", main, startup, ["src", "pos", "label"],
                        [loss.name])


def build_resnet() -> ModelProgram:
    def b(fluid):
        from paddle_tpu.models.resnet import resnet

        img = fluid.layers.data("image", [3, 32, 32], dtype="float32")
        lbl = fluid.layers.data("label", [1], dtype="int64")
        logits = resnet(img, class_dim=10, depth=18)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.Momentum(0.1, 0.9).minimize(loss)
        return loss

    main, startup, loss = _guarded(b)
    return ModelProgram("resnet", main, startup, ["image", "label"],
                        [loss.name])


def build_pipeline() -> ModelProgram:
    def b(fluid):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h1 = fluid.layers.fc(x, 16, act="relu")
        h2 = fluid.layers.fc(h1, 16, act="relu")
        pred = fluid.layers.fc(h2, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(0.05), num_stages=2,
            num_microbatches=2).minimize(loss)
        return loss

    main, startup, loss = _guarded(b)
    return ModelProgram("pipeline", main, startup, ["x", "y"], [loss.name])


def build_grad_merge() -> ModelProgram:
    def b(fluid):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        h = fluid.layers.fc(x, 16, act="relu")
        logits = fluid.layers.fc(h, 4)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.GradientMergeOptimizer(
            fluid.optimizer.MomentumOptimizer(0.1, 0.9),
            k_steps=2).minimize(loss)
        return loss

    main, startup, loss = _guarded(b)
    return ModelProgram("grad_merge", main, startup, ["x", "y"],
                        [loss.name])


def build_ps_transpiled() -> ModelProgram:
    """DistributeTranspiler output: the trainer program (send/recv host
    ops) is the primary; the pserver program rides in ``extra`` and is
    linted separately by the gate."""
    from paddle_tpu.transpiler.distribute_transpiler import (
        DistributeTranspiler)

    def b(fluid):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
        return loss

    main, startup, loss = _guarded(b)
    t = DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, pservers="127.0.0.1:0",
                trainers=1, sync_mode=True)
    trainer = t.get_trainer_program(wait_port=False)
    pserver = t.get_pserver_program("127.0.0.1:0")
    return ModelProgram("ps_transpiled", trainer, startup, ["x", "y"],
                        [loss.name], extra={"pserver": pserver})


def build_serving_prefill() -> ModelProgram:
    """The serving prefill program shape (docs/serving.md): a FIXED-length
    bucket slice of a decoder — tokens [1, T] in, last-position logits
    out. Every dim static (``append_batch_size=False``) on purpose: the
    recompile_risk checker should find NOTHING to flag, mirroring the
    zero-recompile contract the real engine (paddle_tpu/serving/engine.py)
    enforces at runtime."""
    def b(fluid):
        V, T, D = 64, 16, 32
        tok = fluid.layers.data("tokens", [1, T], dtype="int64",
                                append_batch_size=False)
        emb = fluid.layers.embedding(tok, size=[V, D],
                                     param_attr=fluid.ParamAttr("srv_wte"))
        h = fluid.layers.fc(emb, D, num_flatten_dims=2, act="relu")
        h = fluid.layers.fc(h, D, num_flatten_dims=2, act="relu")
        last = fluid.layers.slice(h, axes=[1], starts=[T - 1], ends=[T])
        logits = fluid.layers.fc(
            fluid.layers.reshape(last, [1, D]), V)
        return fluid.layers.softmax(logits)

    main, startup, prob = _guarded(b)
    return ModelProgram("serving_prefill", main, startup, ["tokens"],
                        [prob.name])


def build_serving_decode() -> ModelProgram:
    """The serving decode program shape: one token per slot over a static
    [max_batch] layout plus a fixed-shape cache feed that is shifted
    ring-buffer style and fetched back — the IR-level model of the
    donate-in/donate-out KV slabs. Donation + recompile_risk are the
    checkers this program exists for: fixed shapes end to end, no
    persistable writes, the updated cache is an explicit fetch."""
    def b(fluid):
        V, B, S, D = 64, 4, 8, 32
        tok = fluid.layers.data("token", [B, 1], dtype="int64",
                                append_batch_size=False)
        cache = fluid.layers.data("cache_k", [B, S, D], dtype="float32",
                                  append_batch_size=False)
        emb = fluid.layers.embedding(tok, size=[V, D],
                                     param_attr=fluid.ParamAttr("srv_wte2"))
        # ring shift: drop the oldest cache row, append this token's slab
        tail = fluid.layers.slice(cache, axes=[1], starts=[1], ends=[S])
        new_cache = fluid.layers.concat([tail, emb], axis=1)
        pooled = fluid.layers.reduce_mean(new_cache, dim=1)    # [B, D]
        logits = fluid.layers.fc(pooled, V)
        return fluid.layers.softmax(logits), new_cache

    main, startup, (prob, new_cache) = _guarded(b)
    return ModelProgram("serving_decode", main, startup,
                        ["token", "cache_k"],
                        [prob.name, new_cache.name])


def build_serving_prefill_tp2() -> ModelProgram:
    """The serving prefill shape under a Megatron tp=2 annotation set
    (ISSUE 13): first fc column-split, second fc row-split — the
    IR-level model of the tensor-parallel prefill executable
    (``EngineConfig(sharding="tp")``). Propagation must derive the
    column-split bias, record the row-parallel partial-sum as an info
    edge, and find ZERO errors — the static twin of the engine's
    tp-logits-match-single-chip parity bar."""
    from paddle_tpu import sharding

    mp = build_serving_prefill()
    params = {p.name for p in mp.main.all_parameters()}
    fc_w = sorted(p for p in params if p.endswith(".w_0"))
    sharding.annotate_program(
        mp.main,
        {"srv_wte": (), fc_w[0]: (None, "tp"), fc_w[1]: ("tp", None)},
        mesh_axes=[("tp", 2)])
    return ModelProgram("serving_prefill_tp2", mp.main, mp.startup,
                        mp.feed_names, mp.fetch_names)


def build_serving_decode_tp2() -> ModelProgram:
    """The serving decode shape with the KV-HEAD SPLIT the tp engine
    runs: the cache feed is [B, S, nh, hd] annotated ``tp`` on the head
    dim (exactly how the engine shards its slab/pool at dim 3), the
    up-projection is column-split, the logits head row-split. The
    sharding checker must see the head split ride through the ring
    shift (slice+concat) and the pooled reduction with zero errors."""
    def b(fluid):
        V, B, S, NH, HD = 64, 4, 8, 4, 8
        D = NH * HD
        tok = fluid.layers.data("token", [B, 1], dtype="int64",
                                append_batch_size=False)
        cache = fluid.layers.data("cache_k", [B, S, NH, HD],
                                  dtype="float32",
                                  append_batch_size=False)
        emb = fluid.layers.embedding(
            tok, size=[V, D], param_attr=fluid.ParamAttr("srv_wte_tp"))
        h = fluid.layers.fc(emb, D, num_flatten_dims=2)     # column-par
        hr = fluid.layers.reshape(h, [B, 1, NH, HD])
        # ring shift on the head-split cache: drop the oldest row,
        # append this token's head-split slab
        tail = fluid.layers.slice(cache, axes=[1], starts=[1], ends=[S])
        new_cache = fluid.layers.concat([tail, hr], axis=1)
        pooled = fluid.layers.reduce_mean(new_cache, dim=1)  # [B,NH,HD]
        flat = fluid.layers.reshape(pooled, [B, D])
        logits = fluid.layers.fc(flat, V)                    # row-par
        return fluid.layers.softmax(logits), new_cache

    from paddle_tpu import sharding

    main, startup, (prob, new_cache) = _guarded(b)
    params = {p.name for p in main.all_parameters()}
    fc_w = sorted(p for p in params if p.endswith(".w_0"))
    sharding.annotate_program(
        main,
        {"cache_k": (None, None, "tp", None),
         fc_w[0]: (None, "tp"), fc_w[1]: ("tp", None)},
        mesh_axes=[("tp", 2)])
    return ModelProgram("serving_decode_tp2", main, startup,
                        ["token", "cache_k"],
                        [prob.name, new_cache.name])


def build_mlp_dp() -> ModelProgram:
    """The mlp with GSPMD-style dp annotations (ISSUE 12): ONLY the two
    data feeds are annotated batch-sharded; propagation derives every
    activation/grad spec, weights replicate, and the loss reduction
    surfaces as the one implied psum edge — the sharding checker must
    find zero errors."""
    from paddle_tpu import sharding

    mp = build_mlp()
    sharding.annotate_program(
        mp.main, {"x": ("dp", None), "y": ("dp", None)},
        mesh_axes=[("dp", 8)], data_axis="dp")
    return ModelProgram("mlp_dp", mp.main, mp.startup, mp.feed_names,
                        mp.fetch_names)


def build_gpt_tp2() -> ModelProgram:
    """The fluid gpt with a Megatron tp=2 annotation set: embedding
    replicated, first fc column-split, second fc row-split — propagation
    derives the column-split bias, detects the partial-sum pair, and
    records the implied psum edge (info), with zero errors."""
    from paddle_tpu import sharding

    mp = build_gpt()
    sharding.annotate_program(
        mp.main,
        {"wte": (), "fc_0.w_0": (None, "tp"), "fc_1.w_0": ("tp", None)},
        mesh_axes=[("tp", 2)])
    return ModelProgram("gpt_tp2", mp.main, mp.startup, mp.feed_names,
                        mp.fetch_names)


def build_gpt_fsdp() -> ModelProgram:
    """The fluid gpt with fsdp-style annotations: every weight matrix
    (embedding included) sharded dim-0 over dp — propagation records the
    implied gathers (fsdp's all-gather-for-compute) as info edges, zero
    errors."""
    from paddle_tpu import sharding

    mp = build_gpt()
    mesh = [("dp", 8)]
    ann = {"wte": ("dp", None)}
    for p in mp.main.all_parameters():
        if p.ndim == 2 and p.name != "wte" and p.shape[0] % 8 == 0:
            ann[p.name] = ("dp", None)
    sharding.annotate_program(mp.main, ann, mesh_axes=mesh,
                              data_axis="dp")
    return ModelProgram("gpt_fsdp", mp.main, mp.startup, mp.feed_names,
                        mp.fetch_names)


MODEL_BUILDERS: "Dict[str, Callable[[], ModelProgram]]" = {
    "mlp": build_mlp,
    "gpt": build_gpt,
    "ernie": build_ernie,
    "resnet": build_resnet,
    "pipeline": build_pipeline,
    "grad_merge": build_grad_merge,
    "ps_transpiled": build_ps_transpiled,
    "serving_prefill": build_serving_prefill,
    "serving_decode": build_serving_decode,
    "serving_prefill_tp2": build_serving_prefill_tp2,
    "serving_decode_tp2": build_serving_decode_tp2,
    "mlp_dp": build_mlp_dp,
    "gpt_tp2": build_gpt_tp2,
    "gpt_fsdp": build_gpt_fsdp,
}


def model_names() -> List[str]:
    return sorted(MODEL_BUILDERS)


def build_model_program(name: str) -> ModelProgram:
    return MODEL_BUILDERS[name]()
