"""Checker 1 — program verifier: def-before-use, dangling/duplicate
reads and writes, feed/fetch/persistable consistency, dead vars.

The reference enforces most of this in C++ at OpDesc build time
(op_desc.cc CheckGuards + InferVarType); here it is one metadata pass.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from .core import (ERROR, INFO, WARNING, AnalysisContext, Finding,
                   op_reads, op_writes, register_checker)

# ops that legitimately produce values from nothing (no data inputs) or
# whose declared inputs are optional bootstrap state
_SOURCE_OPS = {
    "fill_constant", "uniform_random", "gaussian_random", "randint",
    "truncated_gaussian_random", "assign_value", "read", "feed",
    "fill_constant_batch_size_like", "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like", "listen_and_serv", "recv", "seed",
}

# grad-op input slots that reference FORWARD outputs (available at grad
# time via the recorded __fwd__ replay even when the var itself was pruned)
_GRAD_SUFFIX = "@GRAD"


def _is_host_op(op_type: str) -> bool:
    from ..framework.executor import is_host_op_type

    return is_host_op_type(op_type)


def _initial_defined(block, feed_names) -> Set[str]:
    """Names defined before any op runs: persistables, declared feed slots
    (is_data), explicit feeds, and — for sub-blocks — everything visible in
    the parent chain (a sub-block op executes inside its parent op, which
    the parent-block walk validates in program order)."""
    defined: Set[str] = set(feed_names)
    for name, var in block.vars.items():
        if var.persistable or var.is_data:
            defined.add(name)
    parent = block.parent_block
    while parent is not None:
        defined.update(parent.vars.keys())
        parent = parent.parent_block
    return defined


@register_checker("program_verifier")
def check_program(ctx: AnalysisContext):
    program = ctx.program
    findings: List[Finding] = []
    fetch_names = set(ctx.fetch_names)

    # names read anywhere / written anywhere (for dead-var + fetch checks)
    read_anywhere: Set[str] = set()
    written_anywhere: Set[str] = set()
    for block in program.blocks:
        for op in block.ops:
            read_anywhere.update(op_reads(op))
            written_anywhere.update(op_writes(op))

    for block in program.blocks:
        defined = _initial_defined(block, ctx.feed_names)
        writers: Dict[str, int] = {}
        for i, op in enumerate(block.ops):
            if op.type in _SOURCE_OPS or _is_host_op(op.type):
                # host ops read/write scope directly; source ops have no
                # data dependencies worth ordering
                defined.update(op_writes(op))
                for n in op_writes(op):
                    writers.setdefault(n, i)
                continue
            for name in op_reads(op):
                if not block._has_var_recursive(name):
                    findings.append(Finding(
                        checker="program_verifier", code="undeclared_var",
                        severity=ERROR, block_idx=block.idx, op_idx=i,
                        op_type=op.type, var=name,
                        message=f"op reads {name!r} but no Variable with "
                                "that name exists in the block hierarchy "
                                "(dangling read)"))
                    continue
                if name not in defined:
                    findings.append(Finding(
                        checker="program_verifier", code="use_before_def",
                        severity=ERROR, block_idx=block.idx, op_idx=i,
                        op_type=op.type, var=name,
                        message=f"op reads {name!r} before any earlier op "
                                "produces it (and it is neither persistable "
                                "nor a feed slot) — the trace will fail with "
                                "a missing-binding KeyError"))
            # duplicate names inside ONE op's output slots: binding order
            # is undefined (dict zip in _bind_outputs keeps the last)
            outs = op_writes(op)
            dupes = {n for n in outs if outs.count(n) > 1}
            for name in sorted(dupes):
                findings.append(Finding(
                    checker="program_verifier", code="duplicate_output",
                    severity=WARNING, block_idx=block.idx, op_idx=i,
                    op_type=op.type, var=name,
                    message=f"op lists output {name!r} more than once — "
                            "which binding wins is undefined"))
            for name in outs:
                var = (block._var_recursive(name)
                       if block._has_var_recursive(name) else None)
                prev = writers.get(name)
                if (prev is not None and var is not None
                        and not var.persistable
                        and not name.endswith(_GRAD_SUFFIX)):
                    # re-definition of a temp (persistables are state — ok;
                    # @GRAD vars legitimately accumulate across grad ops)
                    findings.append(Finding(
                        checker="program_verifier", code="var_redefined",
                        severity=INFO, block_idx=block.idx, op_idx=i,
                        op_type=op.type, var=name,
                        message=f"non-persistable {name!r} already written "
                                f"by op {prev}; later reads see only this "
                                "newest value"))
                writers.setdefault(name, i)
                defined.add(name)

    gb = program.global_block()

    # feed consistency: declared feed slots that nothing reads, and ops
    # overwriting a feed slot (the fed value is silently shadowed)
    for name, var in gb.vars.items():
        if not var.is_data:
            continue
        if name not in read_anywhere and name not in fetch_names:
            findings.append(Finding(
                checker="program_verifier", code="unused_feed",
                severity=WARNING, block_idx=0, var=name,
                message=f"feed slot {name!r} is never read by any op"))
        if name in written_anywhere:
            findings.append(Finding(
                checker="program_verifier", code="feed_overwritten",
                severity=WARNING, block_idx=0, var=name,
                message=f"feed slot {name!r} is written by an op — the fed "
                        "value is shadowed inside the program"))

    # fetch consistency: every fetch must be produced or persistable
    for name in ctx.fetch_names:
        if not gb._has_var_recursive(name):
            findings.append(Finding(
                checker="program_verifier", code="bad_fetch",
                severity=ERROR, block_idx=0, var=name,
                message=f"fetch target {name!r} is not a variable of this "
                        "program"))
            continue
        var = gb._var_recursive(name)
        if (name not in written_anywhere and not var.persistable
                and not var.is_data):
            findings.append(Finding(
                checker="program_verifier", code="fetch_never_produced",
                severity=ERROR, block_idx=0, var=name,
                message=f"fetch target {name!r} is neither produced by any "
                        "op nor persistable — the run would fail"))

    # dead vars: produced, non-persistable, never read / fetched. INFO:
    # many ops emit auxiliary outputs (softmax cache, batch-norm saved
    # stats) by contract.
    for block in program.blocks:
        for name, var in block.vars.items():
            if var.persistable or var.is_data:
                continue
            if (name in written_anywhere and name not in read_anywhere
                    and name not in fetch_names):
                findings.append(Finding(
                    checker="program_verifier", code="dead_var",
                    severity=INFO, block_idx=block.idx, var=name,
                    message=f"{name!r} is produced but never read or "
                            "fetched (dead value; XLA DCE removes it)"))
    return findings
