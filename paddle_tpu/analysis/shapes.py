"""Checker 2 — shape/dtype propagation: re-run the registry's build-time
inference (``infer_shape`` specs, ``jax.eval_shape`` fallback) over every
op and report outputs whose DECLARED shape/dtype contradicts what
propagation yields.

This is the trace-to-XLA stand-in for the reference's per-op C++
``InferShape`` pass: the same machinery ``Block.append_op`` runs at build
time (framework/registry.py:infer_shape_for_op), replayed over the
finished program so post-build mutations (transpilers, hand-edited descs,
deserialized programs) are validated too. The block is restored exactly —
the checker never mutates declared metadata.

Ops where NEITHER path can infer (no ``infer_shape`` spec and the
eval_shape fallback raises) are surfaced as ``no_inference`` INFO
findings — that list is precisely the coverage gap the per-op ``infer``
column in tools/OP_DESC.spec tracks.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .core import (ERROR, INFO, WARNING, AnalysisContext, Finding,
                   op_writes, register_checker)


def _compatible(declared: Tuple[int, ...], inferred: Tuple[int, ...]) -> bool:
    """-1 is a wildcard on either side; otherwise dims must match exactly.
    A rank change is always a contradiction — except scalar () vs (1,),
    which the fluid surface treats interchangeably."""
    if tuple(declared) == tuple(inferred):
        return True
    if {tuple(declared), tuple(inferred)} <= {(), (1,)}:
        return True
    if len(declared) != len(inferred):
        return False
    return all(d == -1 or i == -1 or d == i
               for d, i in zip(declared, inferred))


def _snapshot(block, names) -> Dict[str, Tuple[tuple, str]]:
    out = {}
    for n in names:
        if block._has_var_recursive(n):
            v = block._var_recursive(n)
            out[n] = (tuple(v.shape), v.dtype)
    return out


def _restore(block, snap: Dict[str, Tuple[tuple, str]]):
    for n, (shape, dtype) in snap.items():
        v = block._var_recursive(n)
        v.shape = shape
        v.dtype = dtype


def propagate_op(block, op):
    """Re-run build-time inference for one op WITHOUT mutating the block.

    Returns ``(inferred, available)`` where ``inferred`` maps output var
    name -> (shape, dtype) as propagation sees it, and ``available`` says
    whether any inference path ran at all."""
    from ..framework import registry

    names = op_writes(op)
    snap = _snapshot(block, names)
    try:
        spec = registry.get_op_spec(op.type)
    except NotImplementedError:
        return {}, False
    ran = True
    try:
        if spec.infer_shape is not None or op.type.endswith("_grad"):
            registry.infer_shape_for_op(block, op)
        else:
            # the eval_shape fallback swallows failures by design; probe
            # it the same way but learn whether it actually produced avals
            before = dict(snap)
            registry.infer_shape_for_op(block, op)
            after = _snapshot(block, names)
            if after == before:
                # no mutation: either already-consistent or inference
                # failed. Disambiguate by re-running eval_shape directly.
                ran = _eval_shape_ran(block, op, spec)
        inferred = _snapshot(block, names)
    finally:
        _restore(block, snap)
    return inferred, ran


def _eval_shape_ran(block, op, spec) -> bool:
    """True when the eval_shape fallback can produce output avals for this
    op (mirrors registry.infer_shape_for_op's try body)."""
    import jax

    from ..framework.core import dtype_to_jax
    from ..framework.registry import _DYN, LowerCtx

    try:
        slots, flat = [], []
        for slot, names in op.inputs.items():
            for n in names:
                v = block._var_recursive(n)
                shape = tuple(_DYN if d == -1 else d for d in v.shape)
                slots.append(slot)
                flat.append(jax.ShapeDtypeStruct(shape,
                                                 dtype_to_jax(v.dtype)))

        def f(*args):
            ins = {}
            for slot, val in zip(slots, args):
                ins.setdefault(slot, []).append(val)
            return spec.lower(LowerCtx(block.program, block, {}), op, ins)

        jax.eval_shape(f, *flat)
        return True
    except Exception:
        return False


def propagate_block(block) -> Dict[str, Tuple[tuple, str]]:
    """Propagated (shape, dtype) per var of one block — feeds/persistables
    seed from declared metadata, op outputs from re-run inference. Used by
    ``paddle_tpu.debugger`` to annotate renderings."""
    env: Dict[str, Tuple[tuple, str]] = {}
    for name, var in block.vars.items():
        if var.is_data or var.persistable:
            env[name] = (tuple(var.shape), var.dtype)
    for op in block.ops:
        inferred, ran = propagate_op(block, op)
        if ran:
            env.update(inferred)
    return env


@register_checker("shape_dtype")
def check_shapes(ctx: AnalysisContext):
    from ..framework.executor import is_host_op_type
    from ..framework import registry

    findings: List[Finding] = []
    no_inference_types = set()
    for block in ctx.program.blocks:
        for i, op in enumerate(block.ops):
            if is_host_op_type(op.type):
                continue
            if not registry.has_op(op.type):
                findings.append(Finding(
                    checker="shape_dtype", code="no_lowering",
                    severity=ERROR, block_idx=block.idx, op_idx=i,
                    op_type=op.type,
                    message=f"op type {op.type!r} has no registered "
                            "lowering — the program cannot compile"))
                continue
            declared = _snapshot(block, op_writes(op))
            inferred, ran = propagate_op(block, op)
            if not ran:
                if op.type not in no_inference_types:
                    no_inference_types.add(op.type)
                    findings.append(Finding(
                        checker="shape_dtype", code="no_inference",
                        severity=INFO, block_idx=block.idx, op_idx=i,
                        op_type=op.type,
                        message=f"op type {op.type!r} has no infer_shape "
                                "spec and the eval_shape fallback cannot "
                                "abstract it — declared output metadata is "
                                "unverified (fill the registry gap)"))
                continue
            for name, (shape, dtype) in inferred.items():
                if name not in declared:
                    continue
                d_shape, d_dtype = declared[name]
                if not _compatible(d_shape, shape):
                    findings.append(Finding(
                        checker="shape_dtype", code="shape_mismatch",
                        severity=ERROR, block_idx=block.idx, op_idx=i,
                        op_type=op.type, var=name,
                        message=f"declared shape {list(d_shape)} of "
                                f"{name!r} contradicts propagated "
                                f"{list(shape)}"))
                elif d_dtype != dtype:
                    findings.append(Finding(
                        checker="shape_dtype", code="dtype_mismatch",
                        severity=ERROR, block_idx=block.idx, op_idx=i,
                        op_type=op.type, var=name,
                        message=f"declared dtype {d_dtype!r} of {name!r} "
                                f"contradicts propagated {dtype!r}"))
    return findings
