"""Program IR static analysis: verifier + comm-safety linter (ISSUE 6).

See docs/static_analysis.md for the checker catalog and severity policy.

    from paddle_tpu import analysis
    result = analysis.analyze_program(program, fetch_names=["loss"])
    assert result.ok, result.format()

Checkers (all registered on import):

- ``program_verifier`` — def-before-use, dangling reads, feed/fetch/
  persistable consistency, dead vars;
- ``shape_dtype``     — declared vs propagated output avals (registry
  ``infer_shape`` specs, ``jax.eval_shape`` fallback);
- ``comm_safety``     — cross-rank collective order/axis/dtype matching,
  conditional collectives, unmapped rings, bucket-plan divergence;
- ``donation``        — use-after-donate against the executor/AOT
  donation maps;
- ``precision``       — sub-f32 reductions/accumulations without opt-in;
- ``recompile_risk``  — static prediction of the PR 4 recompile causes.
"""
from .core import (ERROR, INFO, SEVERITIES, WARNING,  # noqa: F401
                   AnalysisContext, AnalysisResult, Finding,
                   all_checkers, analyze_program, get_checker,
                   register_checker)
from .collectives import check_bucket_layouts  # noqa: F401
from .donation import derive_donated  # noqa: F401
from .lint import (format_model_results, lint_all_models,  # noqa: F401
                   lint_model, lint_program)
from .model_corpus import (MODEL_BUILDERS, build_model_program,  # noqa: F401
                           model_names)
from .precision import check_comm_config  # noqa: F401
from .shapes import propagate_block  # noqa: F401

__all__ = [
    "ERROR", "WARNING", "INFO", "SEVERITIES",
    "Finding", "AnalysisContext", "AnalysisResult",
    "analyze_program", "register_checker", "all_checkers", "get_checker",
    "lint_program", "lint_model", "lint_all_models",
    "format_model_results", "model_names", "build_model_program",
    "MODEL_BUILDERS", "check_bucket_layouts", "check_comm_config",
    "derive_donated", "propagate_block",
]
