"""Checker 3 — collective comm-safety: prove all ranks issue collectives
in the same order with matched axes/ring-ids/dtypes.

Deadlocks in SPMD programs are ordering bugs: rank 0 enters allreduce A
while rank 1 enters allreduce B and both wait forever (the reference hits
this as NCCL hangs; on TPU it is an ICI stall with no error). Statically,
a fluid multi-rank job is a set of per-rank transpiled programs
(transpiler/collective.py emits one per rank) — so the checker extracts
each rank's ordered collective signature and diffs them. Three more
silent-failure modes ride along:

- a collective under data-dependent control flow (``conditional_block`` /
  ``while`` sub-blocks): rank-divergent predicates deadlock;
- a ``ring_id`` with no mesh-axis mapping: ops/collective.py lowers it to
  IDENTITY (1-rank-ring semantics) — gradients silently stop syncing;
- rank-divergent ``comm_opt`` bucket plans: the flat reduce-scatter
  exchanges raw buffers, so two ranks disagreeing on bucket boundaries
  accumulate garbage without any shape error
  (:func:`check_bucket_layouts`).
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from .core import (ERROR, INFO, WARNING, AnalysisContext, Finding,
                   register_checker)

# ops with communication semantics, in-order-matched across ranks.
# Bootstrap/no-op types (c_comm_init, c_gen_nccl_id, c_sync_*) exchange
# nothing and are excluded from order matching.
COMM_OPS = {
    "c_allreduce_sum", "c_allreduce_avg", "c_allreduce_max",
    "c_allreduce_min", "c_allreduce_prod", "c_allgather",
    "c_reducescatter", "c_broadcast", "c_concat", "c_split",
    "allreduce", "broadcast", "dgc_momentum",
}

_CTRL_FLOW_OPS = {"while", "conditional_block", "conditional_block_infer",
                  "recurrent", "dynamic_rnn"}

# restore-time resharding collectives (parallel/checkpoint.py's
# build_restore_broadcast_program tags them): they naturally sit under a
# found-checkpoint conditional, but the predicate is rank-UNIFORM by
# construction — every rank selects the same latest COMMITTED step from
# the shared store's atomic manifest, so the rank-divergent-predicate
# deadlock cannot occur.  Downgraded to INFO instead of silenced: the
# annotation is a declaration, and reviewers should still see it.
RESTORE_RESHARD_ATTR = "__restore_reshard__"


def _collective_sig(program) -> List[Tuple[int, int, str, str, str, tuple]]:
    """Ordered (block_idx, op_idx, type, ring_id, dtype, shape) of every
    comm op in program order (block 0 then sub-blocks in index order —
    matching execution order for straight-line block-0 programs, which is
    what the transpilers emit)."""
    sig = []
    for block in program.blocks:
        for i, op in enumerate(block.ops):
            if op.type not in COMM_OPS:
                continue
            ring = op.attr("ring_id", 0)
            names = op.input("X") or [n for ns in op.inputs.values()
                                      for n in ns]
            dtype, shape = "?", ()
            if names and block._has_var_recursive(names[0]):
                v = block._var_recursive(names[0])
                dtype, shape = v.dtype, tuple(v.shape)
            sig.append((block.idx, i, op.type, int(ring), dtype, shape))
    return sig


def check_bucket_layouts(layouts: Sequence[Any],
                         checker: str = "comm_safety") -> List[Finding]:
    """Cross-rank consistency of ``comm_opt.BucketLayout`` plans: the
    flat reduce-scatter path exchanges raw flat buffers, so every rank
    must agree on bucket count, per-bucket dtype/size, and entry order."""
    findings: List[Finding] = []
    if len(layouts) < 2:
        return findings
    ref = layouts[0]
    for r, lay in enumerate(layouts[1:], start=1):
        if len(lay.buckets) != len(ref.buckets):
            findings.append(Finding(
                checker=checker, code="bucket_count_divergence",
                severity=ERROR,
                message=f"rank {r} builds {len(lay.buckets)} comm buckets "
                        f"vs rank 0's {len(ref.buckets)} — the flat "
                        "reduce-scatter would exchange misaligned buffers"))
            continue
        for bi, (a, b) in enumerate(zip(ref.buckets, lay.buckets)):
            if (a.dtype, a.size) != (b.dtype, b.size):
                findings.append(Finding(
                    checker=checker, code="bucket_layout_divergence",
                    severity=ERROR,
                    message=f"bucket {bi} diverges between rank 0 "
                            f"({a.dtype}[{a.size}]) and rank {r} "
                            f"({b.dtype}[{b.size}]) — rank-divergent "
                            "bucket layout accumulates garbage silently"))
            elif a.entries != b.entries:
                findings.append(Finding(
                    checker=checker, code="bucket_entry_divergence",
                    severity=ERROR,
                    message=f"bucket {bi} packs leaves in a different "
                            f"order on rank {r} than on rank 0 — "
                            "gradients would be summed against the wrong "
                            "parameters"))
    return findings


@register_checker("comm_safety")
def check_collectives(ctx: AnalysisContext):
    program = ctx.program
    findings: List[Finding] = []

    # ring_id -> axis mapping the executor would use for this program
    ring_axes = {}
    ann = program._annotations.get("mesh")
    if isinstance(ann, dict):
        ring_axes = dict(ann.get("ring_axes", {}) or {})
    has_mesh = ann is not None or bool(ring_axes)

    # sub-blocks owned by control-flow ops (conditional collectives)
    ctrl_blocks = set()
    for block in program.blocks:
        for op in block.ops:
            if op.type in _CTRL_FLOW_OPS:
                sb = op.attr("sub_block")
                if sb is not None:
                    ctrl_blocks.add(int(sb))
    # transitively: a sub-block of a conditional sub-block is conditional
    changed = True
    while changed:
        changed = False
        for block in program.blocks:
            if block.idx in ctrl_blocks:
                for op in block.ops:
                    sb = op.attr("sub_block")
                    if sb is not None and int(sb) not in ctrl_blocks:
                        ctrl_blocks.add(int(sb))
                        changed = True

    for block in program.blocks:
        for i, op in enumerate(block.ops):
            if op.type not in COMM_OPS:
                continue
            if block.idx in ctrl_blocks:
                if op.attr(RESTORE_RESHARD_ATTR):
                    findings.append(Finding(
                        checker="comm_safety",
                        code="restore_conditional_collective",
                        severity=INFO, block_idx=block.idx, op_idx=i,
                        op_type=op.type,
                        message=f"restore-reshard collective {op.type!r} "
                                "under the found-checkpoint conditional: "
                                "accepted — the predicate is rank-uniform "
                                "(all ranks select the same committed "
                                "step, docs/elastic.md)"))
                else:
                    findings.append(Finding(
                        checker="comm_safety", code="conditional_collective",
                        severity=ERROR, block_idx=block.idx, op_idx=i,
                        op_type=op.type,
                        message=f"collective {op.type!r} sits under "
                                "data-dependent control flow — a rank-"
                                "divergent predicate deadlocks the mesh"))
            ring = int(op.attr("ring_id", 0))
            if has_mesh and ring_axes and ring not in ring_axes:
                findings.append(Finding(
                    checker="comm_safety", code="unmapped_ring",
                    severity=WARNING, block_idx=block.idx, op_idx=i,
                    op_type=op.type,
                    message=f"ring_id {ring} has no mesh-axis mapping "
                            f"(known rings: {sorted(ring_axes)}) — the "
                            "lowering degrades to identity and this "
                            "collective silently stops communicating"))

    # cross-rank order matching against peer programs
    if ctx.peer_programs:
        ref_sig = _collective_sig(program)
        for r, peer in enumerate(ctx.peer_programs, start=1):
            peer_sig = _collective_sig(peer)
            n = min(len(ref_sig), len(peer_sig))
            diverged = False
            for k in range(n):
                (_, op_idx, t0, ring0, dt0, _s0) = ref_sig[k]
                (_, _, t1, ring1, dt1, _s1) = peer_sig[k]
                if t0 != t1:
                    findings.append(Finding(
                        checker="comm_safety",
                        code="collective_order_divergence",
                        severity=ERROR, op_idx=op_idx, op_type=t0,
                        message=f"collective #{k} is {t0!r} on rank 0 but "
                                f"{t1!r} on rank {r} — mismatched order "
                                "deadlocks the mesh"))
                    diverged = True
                    break
                if ring0 != ring1:
                    findings.append(Finding(
                        checker="comm_safety",
                        code="collective_axis_divergence",
                        severity=ERROR, op_idx=op_idx, op_type=t0,
                        message=f"collective #{k} ({t0}) uses ring_id "
                                f"{ring0} on rank 0 but {ring1} on rank "
                                f"{r} — ranks would wait on different "
                                "rings"))
                    diverged = True
                    break
                if dt0 != dt1:
                    findings.append(Finding(
                        checker="comm_safety",
                        code="collective_dtype_divergence",
                        severity=ERROR, op_idx=op_idx, op_type=t0,
                        message=f"collective #{k} ({t0}) exchanges {dt0} "
                                f"on rank 0 but {dt1} on rank {r} — "
                                "byte counts differ across ranks"))
                    diverged = True
                    break
            if not diverged and len(ref_sig) != len(peer_sig):
                findings.append(Finding(
                    checker="comm_safety",
                    code="collective_count_divergence",
                    severity=ERROR,
                    message=f"rank 0 issues {len(ref_sig)} collectives but "
                            f"rank {r} issues {len(peer_sig)} — the excess "
                            "ranks hang waiting for peers that already "
                            "returned"))

    findings.extend(check_bucket_layouts(ctx.bucket_layouts))
    return findings
