"""Checker 6 — recompile-risk lint: statically predict signature
instability using the PR 4 recompile-explainer cause taxonomy.

The runtime explainer (observability/program_report.py) names a recompile
AFTER it happened: ``feed_shape | feed_dtype | feed_set | fetch_list |
flags | program_mutation | mesh``. This checker predicts the same causes
from the IR alone, so shape-churn workloads are flagged before the first
step instead of after the hundredth compile:

- ``feed_shape``: a feed slot with -1 in a NON-batch dim compiles once
  per distinct extent (WARNING — pad or bucket); a -1 batch dim alone is
  the normal one-compile-per-batch-size pattern (INFO);
- ``feed_dtype``: float64/int64 feed slots — NumPy defaults — hit the
  per-step cast path and recompile when a caller's dtype drifts;
- ``flags``: ops whose lowering reads a compile flag recompile when the
  flag toggles mid-run;
- ``program_mutation``: host ops make the executor slice per-segment view
  programs, each with its own compile key;
- ``mesh``: a mesh annotation with unresolved (-1) axis sizes binds at
  run time — every distinct world size is a fresh signature.

Codes are ``risk_<cause>`` so dashboards can join the prediction against
``paddle_recompiles_total{cause=}``.
"""
from __future__ import annotations

from typing import List

from .core import (INFO, WARNING, AnalysisContext, Finding,
                   register_checker)

# op type -> flags its lowering consults (executor._COMPILE_FLAGS family)
_FLAG_SENSITIVE_OPS = {
    "roi_align": ("FLAGS_roi_align_exact", "FLAGS_roi_align_exact_scale"),
    "c_allreduce_sum": ("FLAGS_collective_comm_dtype",),
    "c_allreduce_avg": ("FLAGS_collective_comm_dtype",),
    "c_reducescatter": ("FLAGS_collective_comm_dtype",),
}


@register_checker("recompile_risk")
def check_recompile_risk(ctx: AnalysisContext):
    from ..framework.executor import is_host_op_type

    program = ctx.program
    gb = program.global_block()
    findings: List[Finding] = []

    for name, var in gb.vars.items():
        if not var.is_data:
            continue
        shape = tuple(var.shape)
        inner_dyn = [d for d, s in enumerate(shape) if s == -1 and d > 0]
        if inner_dyn:
            findings.append(Finding(
                checker="recompile_risk", code="risk_feed_shape",
                severity=WARNING, block_idx=0, var=name,
                message=f"feed slot {name!r} declares -1 in non-batch "
                        f"dim(s) {inner_dyn} of {list(shape)} — every "
                        "distinct extent is a fresh XLA compile "
                        "(cause=feed_shape); pad to a fixed length or "
                        "bucket the shapes"))
        elif shape and shape[0] == -1:
            findings.append(Finding(
                checker="recompile_risk", code="risk_feed_shape",
                severity=INFO, block_idx=0, var=name,
                message=f"feed slot {name!r} has a dynamic batch dim — "
                        "one compile per distinct batch size "
                        "(cause=feed_shape); keep batch sizes bucketed"))
        if var.dtype in ("float64", "int64"):
            findings.append(Finding(
                checker="recompile_risk", code="risk_feed_dtype",
                severity=INFO, block_idx=0, var=name,
                message=f"feed slot {name!r} is {var.dtype} (a NumPy "
                        "default dtype) — callers feeding the x64-widened "
                        "twin trigger the cast path, and a drifting feed "
                        "dtype recompiles (cause=feed_dtype)"))

    flag_ops = {}
    host_ops = []
    for block in program.blocks:
        for i, op in enumerate(block.ops):
            if op.type in _FLAG_SENSITIVE_OPS and op.type not in flag_ops:
                flag_ops[op.type] = (block.idx, i)
            if is_host_op_type(op.type):
                host_ops.append((block.idx, i, op.type))
    for op_type, (bidx, i) in sorted(flag_ops.items()):
        findings.append(Finding(
            checker="recompile_risk", code="risk_flags",
            severity=INFO, block_idx=bidx, op_idx=i, op_type=op_type,
            message=f"{op_type!r} lowers differently under "
                    f"{'/'.join(_FLAG_SENSITIVE_OPS[op_type])} — toggling "
                    "them mid-run recompiles (cause=flags)"))
    if host_ops:
        bidx, i, t = host_ops[0]
        findings.append(Finding(
            checker="recompile_risk", code="risk_program_mutation",
            severity=INFO, block_idx=bidx, op_idx=i, op_type=t,
            message=f"program contains {len(host_ops)} host op(s) — the "
                    "executor slices per-segment view programs, each a "
                    "separate compile key (cause=program_mutation)"))

    mesh = program._annotations.get("mesh")
    if isinstance(mesh, dict):
        unsized = [a for a in mesh.get("axes", ()) if tuple(a)[1] in (-1,)]
        if unsized:
            findings.append(Finding(
                checker="recompile_risk", code="risk_mesh",
                severity=INFO, block_idx=0,
                message=f"mesh annotation leaves axis size(s) unresolved "
                        f"({[tuple(a)[0] for a in unsized]}=-1) — the plan "
                        "binds at run time, and each world size is a "
                        "fresh signature (cause=mesh)"))
    return findings
