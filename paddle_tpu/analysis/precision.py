"""Checker 5 — mixed-precision hygiene: reductions and accumulations
running below f32 without an explicit opt-in.

bf16 has an 8-bit mantissa: summing N terms in bf16 loses ~log2(N) bits,
which is why every serious recipe keeps loss/grad ACCUMULATION in f32
even when compute is bf16 (grad-merge defaults ``acc_dtype="float32"``;
comm_opt's quantized collectives accumulate in f32 and offer error
feedback). The statically visible violations:

- a reduction op (sum/mean/softmax-CE/...) whose floating inputs are all
  sub-f32 — the accumulator inherits the input dtype;
- a SUM-collective (``c_allreduce_sum/avg``, ``c_reducescatter``) on a
  sub-f32 var: the on-wire ring accumulation happens in that dtype
  (unlike comm_opt's quantized exchange, which is wire-only);
- ``FLAGS_collective_comm_dtype=int8`` without error feedback anywhere in
  the program's comm path — the quantization error is biased and
  compounds across steps (EQuARX, arXiv:2506.17615);
- grad-merge annotations with ``acc_dtype`` below f32: the k-microbatch
  gradient sum drifts (tests/test_comm_opt.py measured it).
"""
from __future__ import annotations

from typing import List, Optional

from .core import (ERROR, INFO, WARNING, AnalysisContext, Finding,
                   register_checker)

_SUB_F32 = {"bfloat16", "float16", "bf16", "fp16"}

# ops whose lowering accumulates over many elements in the input dtype.
# Matmuls are deliberately absent: XLA gives bf16 dots f32 MXU
# accumulation, so they are not a hazard — elementwise sums/means/CE are.
_REDUCTION_OPS = {
    "sum", "reduce_sum", "reduce_mean", "mean",
    "softmax_with_cross_entropy", "cross_entropy", "cross_entropy2",
}

# SUM-semantics collectives: the ring reduction runs in the wire dtype
_SUM_COLLECTIVES = {"c_allreduce_sum", "c_allreduce_avg", "c_reducescatter",
                    "allreduce", "dgc_momentum"}

# attrs that mark a deliberate low-precision choice on the op itself
_OPT_IN_ATTRS = ("use_fp32_acc", "acc_dtype", "__amp_opt_in__")

# restore-time resharding collectives (parallel/checkpoint.py tags them):
# they REDISTRIBUTE committed checkpoint state verbatim — single-writer
# data movement with no multi-term accumulation, so the sub-f32 ring-
# accumulation hazard does not apply whatever the var dtype (bf16 moments
# restore through c_broadcast/c_allgather losslessly)
RESTORE_RESHARD_ATTR = "__restore_reshard__"


def _floating_sub_f32(block, names) -> Optional[str]:
    """First input var whose dtype is a sub-f32 float; None when any input
    is f32-or-wider (mixed inputs promote) or none are floating."""
    worst = None
    for n in names:
        if not n or n == "@EMPTY@" or not block._has_var_recursive(n):
            continue
        dt = block._var_recursive(n).dtype
        if dt in ("float32", "float64"):
            return None
        if dt in _SUB_F32:
            worst = worst or n
    return worst


@register_checker("precision")
def check_precision(ctx: AnalysisContext):
    program = ctx.program
    findings: List[Finding] = []
    flag_dtype = (ctx.flags or {}).get("FLAGS_collective_comm_dtype") or ""

    has_sum_collective = False
    for block in program.blocks:
        for i, op in enumerate(block.ops):
            names = [n for ns in op.inputs.values() for n in ns]
            if op.type in _SUM_COLLECTIVES:
                if op.attr(RESTORE_RESHARD_ATTR):
                    continue
                has_sum_collective = True
                var = _floating_sub_f32(block, op.input("X") or names)
                if var is not None:
                    findings.append(Finding(
                        checker="precision", code="subf32_collective",
                        severity=WARNING, block_idx=block.idx, op_idx=i,
                        op_type=op.type, var=var,
                        message=f"SUM-collective accumulates {var!r} in "
                                f"{block._var_recursive(var).dtype} — "
                                "ring accumulation below f32 loses "
                                "mantissa bits per hop; keep grads f32 on "
                                "the wire or use the quantized exchange "
                                "(f32 accumulation)"))
                continue
            if op.type in _REDUCTION_OPS:
                if any(op.attr(a) for a in _OPT_IN_ATTRS):
                    continue
                var = _floating_sub_f32(block, names)
                if var is not None:
                    findings.append(Finding(
                        checker="precision", code="subf32_accumulation",
                        severity=WARNING, block_idx=block.idx, op_idx=i,
                        op_type=op.type, var=var,
                        message=f"{op.type} accumulates over {var!r} in "
                                f"{block._var_recursive(var).dtype} with "
                                "no explicit opt-in — reductions below "
                                "f32 drift (~8-bit mantissa)"))

    gm = program._annotations.get("grad_merge")
    if isinstance(gm, dict):
        acc = str(gm.get("acc_dtype", "float32"))
        if acc in _SUB_F32:
            findings.append(Finding(
                checker="precision", code="grad_merge_subf32_acc",
                severity=WARNING, block_idx=0, var=None,
                message=f"grad-merge accumulates k={gm.get('k')} "
                        f"microbatch gradients in {acc} — the merged "
                        "gradient drifts vs the full-batch step; "
                        "acc_dtype='float32' is the safe default"))

    if flag_dtype == "int8" and has_sum_collective:
        findings.append(Finding(
            checker="precision", code="quantized_collective_no_ef",
            severity=WARNING, block_idx=0,
            message="FLAGS_collective_comm_dtype=int8 reroutes this "
                    "program's SUM-collectives through the chunk-scaled "
                    "int8 exchange, which has no error-feedback residual "
                    "on the fluid path — the biased quantization error "
                    "compounds across steps (use bf16, or the engine's "
                    "error_feedback=True reduce-scatter)"))
    return findings


def check_comm_config(ccfg) -> List[Finding]:
    """Standalone hygiene lint for a ``comm_opt.CommConfig`` (the pure-JAX
    engine path has no Program IR to walk): int8 wire payload without
    error feedback is a biased-accumulation risk."""
    findings: List[Finding] = []
    if ccfg.comm_dtype == "int8" and not ccfg.error_feedback:
        findings.append(Finding(
            checker="precision", code="quantized_collective_no_ef",
            severity=WARNING,
            message="CommConfig(comm_dtype='int8') without "
                    "error_feedback=True — the per-step quantization "
                    "error is biased and compounds; enable the residual "
                    "(it rides the sharded train state)"))
    return findings
