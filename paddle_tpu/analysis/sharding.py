"""Checker 7 — sharding annotations: the static guarantees of the GSPMD
propagation layer (ISSUE 12; paddle_tpu/sharding/, docs/sharding.md).

Skips programs with no sharding annotations (every legacy corpus model —
zero findings, zero cost). For annotated programs:

- **unknown_mesh_axis** (error): a spec names an axis the program's mesh
  annotation doesn't declare — the lowering would build the wrong mesh
  or die in NamedSharding construction;
- **indivisible_dim** (error): a statically-known dim is not divisible
  by the product of the axis sizes sharding it — XLA would pad or
  refuse; either way the layout is not the one annotated;
- **annotation_conflict** (error): propagation derived a spec that
  contradicts an explicit annotation — the user's layout and the
  program's dataflow disagree;
- **propagation_conflict** (error): two propagation sources disagree on
  an unannotated var (the acceptance bar: a complete propagation has
  zero of these);
- **mesh_mismatch_at_restore** (error): the caller passed the LIVE mesh
  (``analyze_program(..., live_mesh={axis: size})``) and the program's
  annotated mesh differs — restoring/executing this program on the live
  mesh misplaces every shard. The dynamic twin is
  ``parallel.checkpoint.MeshMismatchError``;
- **high_reshard_cost** (warning): the total implied-reshard wire bytes
  exceed ``RESHARD_WARN_BYTES`` — the annotations force heavy layout
  churn; the per-edge records ride as **reshard_edge** (info) findings
  so ``paddle_lint -v`` answers "why did this reshard".
"""
from __future__ import annotations

from typing import List

from .core import (ERROR, INFO, WARNING, AnalysisContext, Finding,
                   register_checker)

# total implied-reshard wire bytes above which the checker warns (64 MiB:
# roughly one full GPT_SMALL grad all-reduce — annotation sets implying
# more than that per step deserve a look)
RESHARD_WARN_BYTES = 64 << 20


@register_checker("sharding")
def check_sharding(ctx: AnalysisContext):
    from ..sharding import propagate_program, spec_str
    from ..sharding.spec import annotated_vars, mesh_axes_of

    program = ctx.program
    ann = annotated_vars(program)
    mesh_axes = mesh_axes_of(program)
    live_mesh = getattr(ctx, "live_mesh", None)
    if not ann and mesh_axes is None:
        return []

    findings: List[Finding] = []
    mesh_sizes = {a: int(s) for a, s in (mesh_axes or [])}

    if live_mesh is not None and mesh_axes is not None:
        live = {str(a): int(s) for a, s in dict(live_mesh).items()}
        if live != mesh_sizes:
            findings.append(Finding(
                checker="sharding", code="mesh_mismatch_at_restore",
                severity=ERROR,
                message=f"program is annotated for mesh {mesh_sizes} but "
                        f"the live mesh is {live} — executing/restoring "
                        "here would misplace every shard (reshard the "
                        "state first; see docs/sharding.md)"))

    # explicit-annotation hygiene: axes exist, dims divide
    explicit = program._annotations.get("sharding_annotated")
    check_named = {n: ann[n] for n in (explicit or ann) if n in ann}
    for name, spec in sorted(check_named.items()):
        var = None
        for block in program.blocks:
            if name in block.vars:
                var = block.vars[name]
                break
        if var is None:
            continue
        shape = tuple(getattr(var, "shape", ()) or ())
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            for a in axes:
                if mesh_sizes and a not in mesh_sizes:
                    findings.append(Finding(
                        checker="sharding", code="unknown_mesh_axis",
                        severity=ERROR, var=name,
                        message=f"spec {spec_str(spec)} on {name!r} names "
                                f"mesh axis {a!r}, but the annotated mesh "
                                f"only has {sorted(mesh_sizes)}"))
            div = 1
            for a in axes:
                div *= mesh_sizes.get(a, 1)
            if d < len(shape) and shape[d] > 0 and div > 1 \
                    and shape[d] % div:
                findings.append(Finding(
                    checker="sharding", code="indivisible_dim",
                    severity=ERROR, var=name,
                    message=f"dim {d} of {name!r} ({shape[d]}) is not "
                            f"divisible by mesh axes {entry!r} "
                            f"(x{div}) — the annotated layout cannot "
                            "exist"))
    if any(f.code == "unknown_mesh_axis" for f in findings):
        # propagation over unknown axes would only echo the same defect
        return findings

    result = propagate_program(program, mesh_axes=mesh_axes or [])
    for c in result.conflicts:
        findings.append(Finding(
            checker="sharding",
            code=("annotation_conflict" if c.annotated
                  else "propagation_conflict"),
            severity=ERROR, block_idx=c.block_idx, op_idx=c.op_idx,
            op_type=c.op_type, var=c.var, message=c.format()))
    for r in result.reshards:
        findings.append(Finding(
            checker="sharding", code="reshard_edge", severity=INFO,
            block_idx=r.block_idx, op_idx=r.op_idx, op_type=r.op_type,
            var=r.var, message=r.format()))
    total = result.total_reshard_bytes
    if total > RESHARD_WARN_BYTES:
        worst = max(result.reshards, key=lambda r: r.bytes_est)
        findings.append(Finding(
            checker="sharding", code="high_reshard_cost",
            severity=WARNING,
            message=f"annotations imply ~{total} wire bytes of "
                    f"resharding per run ({len(result.reshards)} "
                    f"edge(s); worst: {worst.edge} ~{worst.bytes_est} B) "
                    "— consider annotating the producers to match "
                    "(docs/sharding.md runbook)"))
    uncovered = result.uncovered_op_types()
    if uncovered:
        findings.append(Finding(
            checker="sharding", code="rule_coverage_gap", severity=INFO,
            message="op types with no sharding rule fell back to "
                    f"replication: {', '.join(uncovered)} (register via "
                    "framework.registry.set_sharding_rule)"))
    return findings
