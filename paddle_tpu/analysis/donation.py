"""Checker 4 — donation / use-after-donate.

The executor donates every written persistable's buffer into the compiled
call (``_CompiledBlock``: ``donate_argnums`` on the mutable-param dict),
so after an in-place update the PRE-update value is gone — the donated
HBM now holds the new state. Two hazards are statically visible in the
IR:

- an op ordered AFTER the optimizer's in-place update of a param reads
  that param while itself belonging to the forward/backward region
  (op_role bitmask): with donated buffers it silently consumes the
  POST-update value, i.e. gradients computed against the wrong weights
  (the reference caught this class with its SSA-graph dependency pass;
  here op order in the block IS the schedule);
- an AOT donation map (PR 4 program reports record ``donated``) listing a
  var the IR never writes back: the call would delete the scope array and
  produce no replacement — the next step crashes on a dead buffer.

Fetching donated state is legal but aliased (the executor inserts a
defensive device copy, executor.py ``_fetch_copy_idx``); it is reported
as INFO so AOT embedders know to do the same.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from .core import (ERROR, INFO, WARNING, AnalysisContext, Finding,
                   op_reads, op_writes, register_checker)


def derive_donated(program) -> List[str]:
    """The donation map the executor would build: persistables read from
    scope AND written back by block-0 ops (executor._analyze_persistables
    read ∩ written — exactly ``_CompiledBlock._mutable_names``)."""
    from ..framework.executor import _analyze_persistables

    read, written = _analyze_persistables(program)
    ws = set(written)
    return [n for n in read if n in ws]


def _role(op) -> int:
    try:
        return int(op.attr("op_role", 0) or 0)
    except (TypeError, ValueError):
        return 0


@register_checker("donation")
def check_donation(ctx: AnalysisContext):
    from ..framework.executor import _analyze_persistables
    from ..framework.program import Program

    program = ctx.program
    findings: List[Finding] = []
    read, written = _analyze_persistables(program)
    written_set = set(written)
    ir_donated = [n for n in read if n in written_set]

    # the AOT donation map (when the caller has one) must agree with the IR
    if ctx.donated is not None:
        for name in ctx.donated:
            gb = program.global_block()
            if not gb._has_var_recursive(name):
                # pure-JAX executables (parallelize.make_train_step) donate
                # pytree roots like "params" that are not IR vars — skip
                continue
            if name not in written_set:
                findings.append(Finding(
                    checker="donation", code="donated_never_rewritten",
                    severity=ERROR, block_idx=0, var=name,
                    message=f"executable donates {name!r} but no op writes "
                            "it back — after the call the scope holds a "
                            "deleted buffer and the next step crashes"))

    donated = set(ctx.donated) & written_set if ctx.donated is not None \
        else set(ir_donated)

    OPT_ROLES = Program.OP_ROLE_OPTIMIZE
    FWD_BWD_MASK = Program.OP_ROLE_BACKWARD

    block = program.global_block()
    first_opt_write: Dict[str, int] = {}
    for i, op in enumerate(block.ops):
        role = _role(op)
        if role & OPT_ROLES:
            for n in op_writes(op):
                if n in donated:
                    first_opt_write.setdefault(n, i)
            continue
        # forward/backward/unspecified op reading a param that an earlier
        # optimizer op already updated in place
        for n in op_reads(op):
            j = first_opt_write.get(n)
            if j is not None:
                sev = ERROR if role & FWD_BWD_MASK or role == 0 else WARNING
                findings.append(Finding(
                    checker="donation", code="use_after_donate",
                    severity=sev, block_idx=0, op_idx=i, op_type=op.type,
                    var=n,
                    message=f"op reads {n!r} after op {j} updated it in "
                            "place — the donated buffer holds the POST-"
                            "update value, the pre-update value is gone "
                            "(gradients/stats computed against the wrong "
                            "weights)"))

    # double in-place update of one donated buffer in a single step:
    # legal (env rebinds), but the intermediate state is unobservable and
    # usually indicates a transpile stacked two optimizers
    writers: Dict[str, List[int]] = {}
    for i, op in enumerate(block.ops):
        if _role(op) & OPT_ROLES:
            for n in op_writes(op):
                if n in donated:
                    writers.setdefault(n, []).append(i)
    for n, idxs in sorted(writers.items()):
        if len(idxs) > 1:
            findings.append(Finding(
                checker="donation", code="double_update",
                severity=WARNING, block_idx=0, op_idx=idxs[1],
                var=n,
                message=f"{n!r} is updated in place by ops {idxs} within "
                        "one step — stacked optimizer writes on one "
                        "donated buffer"))

    for name in ctx.fetch_names:
        if name in donated:
            findings.append(Finding(
                checker="donation", code="fetch_of_donated",
                severity=INFO, block_idx=0, var=name,
                message=f"fetch {name!r} aliases donated state; the "
                        "executor copies it defensively, AOT embedders "
                        "must do the same before the next step"))
    return findings
