"""Lint driver: run the full checker suite over one program or the whole
built-in model corpus. Shared by tools/paddle_lint.py, the Executor's
``FLAGS_check_program`` hook, and tests/test_static_analysis.py.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .core import (ERROR, SEVERITIES, WARNING, AnalysisResult,
                   analyze_program)
from .model_corpus import ModelProgram, build_model_program, model_names

__all__ = ["lint_program", "lint_model", "lint_all_models",
           "format_model_results"]


def lint_program(program, feed_names: Sequence[str] = (),
                 fetch_names: Sequence[str] = (), **kw) -> AnalysisResult:
    """All checkers over one program (thin alias of analyze_program)."""
    return analyze_program(program, feed_names=feed_names,
                           fetch_names=fetch_names, **kw)


def lint_model(mp: ModelProgram) -> Dict[str, AnalysisResult]:
    """Lint one built model: the main program (with its startup as
    context-free sibling) plus any extra programs (PS pserver side)."""
    out = {mp.name: analyze_program(
        mp.main, feed_names=mp.feed_names, fetch_names=mp.fetch_names,
        peer_programs=mp.peer_programs)}
    if mp.startup is not None:
        out[f"{mp.name}.startup"] = analyze_program(mp.startup)
    for key, prog in sorted(mp.extra.items()):
        out[f"{mp.name}.{key}"] = analyze_program(prog)
    return out


def lint_all_models(names: Optional[Sequence[str]] = None
                    ) -> Dict[str, AnalysisResult]:
    results: Dict[str, AnalysisResult] = {}
    for name in (names or model_names()):
        results.update(lint_model(build_model_program(name)))
    return results


def format_model_results(results: Dict[str, AnalysisResult],
                         min_severity: str = WARNING,
                         verbose: bool = False) -> str:
    lines: List[str] = []
    floor = SEVERITIES.index(min_severity)
    width = max((len(n) for n in results), default=8)
    for name in sorted(results):
        res = results[name]
        c = res.counts()
        verdict = "FAIL" if c[ERROR] else "ok"
        lines.append(f"{name:<{width}}  {verdict:>4}  "
                     f"errors={c['error']} warnings={c['warning']} "
                     f"info={c['info']}")
        for f in res.findings:
            if verbose or SEVERITIES.index(f.severity) >= floor:
                lines.append(f"  {f.format()}")
    total_err = sum(len(r.errors) for r in results.values())
    lines.append(f"linted {len(results)} program(s): "
                 f"{total_err} error(s) total")
    return "\n".join(lines)
