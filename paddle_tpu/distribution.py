"""Probability distributions — parity with fluid/distribution.py
(Uniform, Normal, Categorical, MultivariateNormalDiag: sample / entropy /
log_prob / kl_divergence).

Like the reference, methods build graph ops over Variables (static mode);
python floats/np arrays are accepted and lifted to constants.
"""
from __future__ import annotations

import math
from typing import Union

import numpy as np

from . import layers
from .framework.program import Variable
from .layers import tensor as ltensor

__all__ = ["Uniform", "Normal", "Categorical", "MultivariateNormalDiag"]


def _to_var(x, dtype="float32"):
    if isinstance(x, Variable):
        return x
    arr = np.asarray(x, dtype=dtype)
    return ltensor.assign(arr)


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) — fluid/distribution.py Uniform."""

    def __init__(self, low, high):
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        u = layers.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        span = layers.elementwise_sub(self.high, self.low)
        return layers.elementwise_add(
            layers.elementwise_mul(u, span), self.low)

    def entropy(self):
        return layers.log(layers.elementwise_sub(self.high, self.low))

    def log_prob(self, value):
        value = _to_var(value)
        span = layers.elementwise_sub(self.high, self.low)
        lb = layers.cast(layers.less_than(self.low, value), "float32")
        ub = layers.cast(layers.less_than(value, self.high), "float32")
        inside = layers.elementwise_mul(lb, ub)
        return layers.log(
            layers.elementwise_div(inside, span))

    def kl_divergence(self, other):
        raise NotImplementedError("uniform KL not in reference either")


class Normal(Distribution):
    """N(loc, scale) — fluid/distribution.py Normal."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        z = layers.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return layers.elementwise_add(
            layers.elementwise_mul(z, self.scale), self.loc)

    def entropy(self):
        # 0.5 + 0.5 log(2π) + log σ
        const = 0.5 + 0.5 * math.log(2 * math.pi)
        return layers.elementwise_add(
            ltensor.fill_constant([1], "float32", const),
            layers.log(self.scale))

    def log_prob(self, value):
        var = layers.elementwise_mul(self.scale, self.scale)
        diff = layers.elementwise_sub(_to_var(value), self.loc)
        quad = layers.elementwise_div(
            layers.elementwise_mul(diff, diff),
            layers.scale(var, scale=2.0))
        log_z = layers.elementwise_add(
            layers.log(self.scale),
            ltensor.fill_constant([1], "float32", 0.5 * math.log(2 * math.pi)))
        return layers.elementwise_sub(layers.scale(quad, scale=-1.0), log_z)

    def kl_divergence(self, other: "Normal"):
        # KL(N0||N1) = log σ1/σ0 + (σ0² + (μ0-μ1)²)/(2σ1²) - 1/2
        var0 = layers.elementwise_mul(self.scale, self.scale)
        var1 = layers.elementwise_mul(other.scale, other.scale)
        dmu = layers.elementwise_sub(self.loc, other.loc)
        t = layers.elementwise_div(
            layers.elementwise_add(var0, layers.elementwise_mul(dmu, dmu)),
            layers.scale(var1, scale=2.0))
        return layers.elementwise_add(
            layers.elementwise_sub(
                layers.log(layers.elementwise_div(other.scale, self.scale)),
                ltensor.fill_constant([1], "float32", 0.5)),
            t)


class Categorical(Distribution):
    """Categorical over unnormalized logits — fluid/distribution.py."""

    def __init__(self, logits):
        self.logits = _to_var(logits)

    def _log_pmf(self):
        return layers.log_softmax(self.logits)

    def entropy(self):
        logp = self._log_pmf()
        p = layers.softmax(self.logits)
        return layers.scale(
            layers.reduce_sum(layers.elementwise_mul(p, logp), dim=-1),
            scale=-1.0)

    def log_prob(self, value):
        logp = self._log_pmf()
        oh = layers.one_hot(_to_var(value, "int64"),
                            self.logits.shape[-1])
        return layers.reduce_sum(layers.elementwise_mul(logp, oh), dim=-1)

    def kl_divergence(self, other: "Categorical"):
        logp = self._log_pmf()
        logq = other._log_pmf()
        p = layers.softmax(self.logits)
        return layers.reduce_sum(
            layers.elementwise_mul(p, layers.elementwise_sub(logp, logq)),
            dim=-1)


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal — fluid/distribution.py."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)       # [..., d]
        self.scale = _to_var(scale)   # diagonal covariance matrix [d, d]

    def _det(self):
        # product of diagonal entries
        d = self.scale.shape[-1]
        diag = layers.reduce_sum(
            layers.elementwise_mul(
                self.scale,
                ltensor.assign(np.eye(d, dtype=np.float32))), dim=-1)
        return layers.reduce_prod(diag)

    def entropy(self):
        d = self.scale.shape[-1]
        const = 0.5 * d * (1.0 + math.log(2 * math.pi))
        return layers.elementwise_add(
            ltensor.fill_constant([1], "float32", const),
            layers.scale(layers.log(self._det()), scale=0.5))

    def kl_divergence(self, other: "MultivariateNormalDiag"):
        d = self.scale.shape[-1]
        eye = ltensor.assign(np.eye(d, dtype=np.float32))
        diag0 = layers.reduce_sum(layers.elementwise_mul(self.scale, eye),
                                  dim=-1)
        diag1 = layers.reduce_sum(layers.elementwise_mul(other.scale, eye),
                                  dim=-1)
        tr = layers.reduce_sum(layers.elementwise_div(diag0, diag1))
        dmu = layers.elementwise_sub(other.loc, self.loc)
        quad = layers.reduce_sum(
            layers.elementwise_div(layers.elementwise_mul(dmu, dmu), diag1))
        logdet = layers.elementwise_sub(
            layers.reduce_sum(layers.log(diag1)),
            layers.reduce_sum(layers.log(diag0)))
        return layers.scale(
            layers.elementwise_add(
                layers.elementwise_add(
                    layers.elementwise_sub(tr,
                                           ltensor.fill_constant(
                                               [1], "float32", float(d))),
                    quad),
                logdet),
            scale=0.5)
