"""fluid.ParallelExecutor — parity with
python/paddle/fluid/parallel_executor.py (:60): the pre-CompiledProgram
multi-device API. Thin adapter: construction builds
CompiledProgram.with_data_parallel over the device mesh; run() delegates
to the Executor (fetched values come back merged across the data axis,
matching the reference's fetch concatenation).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .framework.compiler import BuildStrategy, CompiledProgram, \
    ExecutionStrategy
from .framework.core import XLAPlace
from .framework.executor import Executor, Scope, global_scope
from .framework.program import Program, default_main_program

__all__ = ["ParallelExecutor"]

from .observability import metrics as _obs_metrics

_m_feed_merge_ms = _obs_metrics.default_registry().histogram(
    "paddle_pexe_feed_merge_ms",
    "ParallelExecutor per-device feed list merge wall time (ms)")


class ParallelExecutor:
    def __init__(self, use_cuda: bool, loss_name: Optional[str] = None,
                 main_program: Optional[Program] = None,
                 share_vars_from: Optional["ParallelExecutor"] = None,
                 exec_strategy: Optional[ExecutionStrategy] = None,
                 build_strategy: Optional[BuildStrategy] = None,
                 num_trainers: int = 1, trainer_id: int = 0,
                 scope: Optional[Scope] = None):
        self._program = main_program or default_main_program()
        self._scope = scope or (share_vars_from._scope
                                if share_vars_from else global_scope())
        self._exe = Executor(XLAPlace(0))
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy)
        # label this program's compile-time introspection records
        # (observability/program_report.py) so multi-device runs are
        # distinguishable from single-device runs of the same block
        self._program._annotations.setdefault(
            "report_name",
            f"pexe/{loss_name or 'main'}"
            f"#{len(self._program.global_block().ops)}ops")

    @property
    def device_count(self) -> int:
        import jax

        return len(jax.devices())

    def run(self, fetch_list: List, feed=None, feed_dict=None,
            return_numpy: bool = True):
        feed = feed if feed is not None else feed_dict
        if isinstance(feed, (list, tuple)):
            # per-device feed list: concatenate along the batch axis (the
            # compiled program re-splits across the mesh). Non-batched
            # entries — 0-d scalars like a fed learning rate — have no batch
            # axis to concatenate; they must be identical per device and
            # pass through unsplit. The merge cost is host-side per-step
            # work, so it self-reports (paddle_pexe_feed_merge_ms).
            with _m_feed_merge_ms.time():
                merged = {}
                for k in feed[0]:
                    vals = [np.asarray(f[k]) for f in feed]
                    if vals[0].ndim == 0:
                        for i, v in enumerate(vals[1:], 1):
                            if v != vals[0]:
                                raise ValueError(
                                    f"scalar feed {k!r} differs across "
                                    f"devices ({vals[0]!r} vs {v!r} at "
                                    f"device {i}); non-batched feeds must "
                                    "be replicated")
                        merged[k] = vals[0]
                    else:
                        merged[k] = np.concatenate(vals, axis=0)
            feed = merged
        outs = self._exe.run(self._compiled, feed=feed or {},
                             fetch_list=list(fetch_list),
                             scope=self._scope)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs
