"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle ~v1.8 (fluid) — static Program IR + IR autodiff +
whole-program XLA compilation, imperative mode, distributed training via
jax.sharding meshes, AMP, checkpointing, data pipelines.

Typical fluid-style use:

    import paddle_tpu as fluid
    x = fluid.layers.data("x", [784])
    y = fluid.layers.fc(x, 10, act="softmax")
    ...
    exe = fluid.Executor(fluid.XLAPlace(0))
    exe.run(fluid.default_startup_program())
    exe.run(feed={...}, fetch_list=[...])
"""
from __future__ import annotations

# op registrations (import for side effects)
from . import ops  # noqa: F401
# PS/distributed host ops (send/recv/listen_and_serv/...) must be present
# whenever a transpiled program runs, not only after an explicit
# `import paddle_tpu.distributed`
from .distributed import ps_ops as _ps_ops  # noqa: F401

from .framework.core import (  # noqa: F401
    CPUPlace,
    Place,
    TPUPlace,
    VarType,
    XLAPlace,
    convert_dtype,
    get_flags,
    is_compiled_with_tpu,
    set_flags,
)
from .framework import initializer  # noqa: F401
from .framework import unique_name  # noqa: F401
from .framework.backward import append_backward, gradients  # noqa: F401
from .framework.executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .framework.compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .framework.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .framework.program import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
    switch_main_program,
    switch_startup_program,
)

from . import clip  # noqa: F401
from . import nets  # noqa: F401
from . import contrib  # noqa: F401
from . import distribution  # noqa: F401
from . import reader  # noqa: F401
from .reader import DataLoader  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .dataset import DatasetFactory  # noqa: F401
from .reader import batch  # noqa: F401
from . import layers  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import io  # noqa: F401
from . import metrics  # noqa: F401
from . import dygraph  # noqa: F401
from . import profiler  # noqa: F401

# fluid-style aliases
CUDAPlace = XLAPlace  # reference scripts swap transparently
data = layers.data

__version__ = "0.1.0"
