"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle ~v1.8 (fluid) — static Program IR + IR autodiff +
whole-program XLA compilation, imperative mode, distributed training via
jax.sharding meshes, AMP, checkpointing, data pipelines.

Typical fluid-style use:

    import paddle_tpu as fluid
    x = fluid.layers.data("x", [784])
    y = fluid.layers.fc(x, 10, act="softmax")
    ...
    exe = fluid.Executor(fluid.XLAPlace(0))
    exe.run(fluid.default_startup_program())
    exe.run(feed={...}, fetch_list=[...])
"""
from __future__ import annotations

# op registrations (import for side effects)
from . import ops  # noqa: F401
# PS/distributed host ops (send/recv/listen_and_serv/...) must be present
# whenever a transpiled program runs, not only after an explicit
# `import paddle_tpu.distributed`
from .distributed import ps_ops as _ps_ops  # noqa: F401

from .framework.core import (  # noqa: F401
    CPUPlace,
    Place,
    TPUPlace,
    VarType,
    XLAPlace,
    convert_dtype,
    get_flags,
    is_compiled_with_tpu,
    set_flags,
)
from .framework import initializer  # noqa: F401
from .framework import unique_name  # noqa: F401
from .framework.backward import append_backward, gradients  # noqa: F401
from .framework.executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .framework.compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .framework.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .framework.program import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
    switch_main_program,
    switch_startup_program,
)

from . import clip  # noqa: F401
from . import nets  # noqa: F401
from . import contrib  # noqa: F401
from . import distribution  # noqa: F401
from . import reader  # noqa: F401
from .reader import DataLoader  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .dataset import DatasetFactory  # noqa: F401
from .reader import batch  # noqa: F401
from . import layers  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import io  # noqa: F401
from . import metrics  # noqa: F401
from . import evaluator  # noqa: F401
from . import average  # noqa: F401
from . import lod_tensor  # noqa: F401
from .lod_tensor import LoDTensor, create_lod_tensor, create_random_int_lodtensor  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from .data_feed_desc import DataFeedDesc  # noqa: F401
from . import dygraph  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401
from . import sharding  # noqa: F401

# fluid-style aliases
CUDAPlace = XLAPlace  # reference scripts swap transparently
data = layers.data

__version__ = "0.1.0"


# -- top-level namespace completion (reference fluid/__init__.py __all__) --
import numpy as _np

# runtime tensor types: device values are jax arrays; the LoD-carrying
# host-side type the reference exposes maps to numpy here
Tensor = _np.ndarray
LoDTensor = _np.ndarray
LoDTensorArray = list
from .framework.core import XLAPlace as CUDAPinnedPlace  # alias: pinned
# host staging is XLA-owned; accepted for API parity
from .framework import backward as backward  # noqa: F401
import sys as _sys

_sys.modules[__name__ + ".backward"] = backward
from .dygraph.varbase import VarBase  # noqa: F401
from .layers import embedding, one_hot  # noqa: F401
from .layers import learning_rate_scheduler as learning_rate_decay  # noqa: F401
from . import transpiler  # noqa: F401


def enable_dygraph(place=None):
    """paddle.fluid.enable_dygraph — enter global eager mode."""
    from .dygraph import base as _dybase

    _dybase.enable_dygraph(place)


def disable_dygraph():
    from .dygraph import base as _dybase

    _dybase.disable_dygraph()


enable_imperative = enable_dygraph
disable_imperative = disable_dygraph


def save(program, model_path):
    """paddle.fluid.save (fluid/io.py save): persistables + program."""
    from . import io as _io
    from .framework.executor import Executor
    from .framework.core import XLAPlace
    import os as _os

    d = _os.path.dirname(model_path) or "."
    _os.makedirs(d, exist_ok=True)
    exe = Executor(XLAPlace(0))
    _io.save_persistables(exe, d, main_program=program,
                          filename=_os.path.basename(model_path)
                          + ".pdparams")
    with open(model_path + ".pdmodel", "wb") as f:
        from .framework import paddle_pb
        from .framework.serialization import program_to_desc

        f.write(paddle_pb.desc_to_pb(program_to_desc(program)))


def load(program, model_path, executor=None, var_list=None):
    """paddle.fluid.load — inverse of fluid.save."""
    from . import io as _io
    from .framework.executor import Executor
    from .framework.core import XLAPlace
    import os as _os

    exe = executor or Executor(XLAPlace(0))
    _io.load_persistables(exe, _os.path.dirname(model_path) or ".",
                          main_program=program,
                          filename=_os.path.basename(model_path)
                          + ".pdparams")


def install_check():
    """paddle.fluid.install_check.run_check parity: one tiny train step."""
    import numpy as _np

    from .framework.core import XLAPlace
    from .framework.executor import Executor
    from .framework.program import Program, program_guard
    from . import layers as _l
    from . import optimizer as _opt

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = _l.data("install_check_x", [4], dtype="float32")
        y = _l.fc(x, 2)
        loss = _l.reduce_mean(y)
        _opt.SGD(0.01).minimize(loss)
    exe = Executor(XLAPlace(0))
    exe.run(startup)
    out = exe.run(main,
                  feed={"install_check_x":
                        _np.ones((2, 4), _np.float32)},
                  fetch_list=[loss])
    assert _np.isfinite(_np.asarray(out[0])).all()
    print("Your paddle_tpu works well on this machine.")
    return True


# ---------------------------------------------------------------------------
# paddle-2.0-preview namespaces + top-level aliases
# (reference python/paddle/__init__.py — the DEFINE_ALIAS block)
# ---------------------------------------------------------------------------
from . import tensor  # noqa: F401,E402
from . import nn  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import imperative  # noqa: F401,E402
from . import declarative  # noqa: F401,E402
from . import framework  # noqa: F401,E402
from .framework.random import manual_seed  # noqa: F401,E402

from .tensor.attribute import rank, shape  # noqa: F401,E402
from .tensor.creation import (  # noqa: F401,E402
    arange, create_tensor, crop_tensor, diag, eye, full, full_like,
    linspace, meshgrid, ones, ones_like, tril, triu, zeros, zeros_like,
)
from .tensor.creation import fill_constant  # noqa: F401,E402
from .tensor.linalg import (  # noqa: F401,E402
    bmm, cholesky, cross, dist, dot, histogram, matmul, norm, t, transpose,
)
from .tensor.logic import (  # noqa: F401,E402
    allclose, elementwise_equal, equal, greater_equal, greater_than,
    is_empty, isfinite, less_equal, less_than, logical_and, logical_not,
    logical_or, logical_xor, not_equal, reduce_all, reduce_any,
)
from .tensor.manipulation import (  # noqa: F401,E402
    cast, concat, expand, expand_as, flatten, flip, gather, gather_nd,
    reshape, reverse, roll, scatter, scatter_nd, scatter_nd_add,
    shard_index, slice, split, squeeze, stack, strided_slice, unbind,
    unique, unique_with_counts, unsqueeze, unstack,
)
from .tensor.math import (  # noqa: F401,E402
    abs, acos, add, addcmul, addmm, asin, atan, ceil, clamp, cos, cumsum,
    div, elementwise_add, elementwise_div, elementwise_floordiv,
    elementwise_max, elementwise_min, elementwise_mod, elementwise_mul,
    elementwise_pow, elementwise_sub, elementwise_sum, erf, exp, floor,
    increment, inverse, kron, log, log1p, logsumexp, max, min, mm, mul,
    multiplex, pow, reciprocal, reduce_max, reduce_min, reduce_prod,
    reduce_sum, round, rsqrt, scale, sign, sin, sqrt, square, stanh, sum,
    sums, tanh, trace,
)
from .tensor.random import rand, randint, randn, randperm, shuffle  # noqa: F401,E402
from .tensor.search import (  # noqa: F401,E402
    argmax, argmin, argsort, has_inf, has_nan, index_sample, index_select,
    nonzero, sort, topk, where,
)
from .tensor.stat import mean, reduce_mean, std, var  # noqa: F401,E402

from .framework import (  # noqa: F401,E402
    append_backward as append_backward,  # re-export parity
    create_global_var, create_parameter, name_scope,
)
from .dygraph.base import in_dygraph_mode as in_imperative_mode  # noqa: F401,E402

# remaining fluid top-level utilities (reference fluid/__init__.py __all__)
from . import compat  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401
from .layers.extras import Print  # noqa: E402,F401
from .layers.nn import py_func  # noqa: E402,F401
from .incubate import hapi  # noqa: E402,F401
from . import debugger  # noqa: E402,F401
from .dygraph.base import in_dygraph_mode  # noqa: E402,F401


def require_version(min_version, max_version=None):
    """reference framework.py:73 — version gate; this framework versions
    independently of the reference, so only malformed specs error."""
    import re as _re
    rx = _re.compile(r"^\d+(\.\d+){0,3}([.\-]?[a-zA-Z]+\d*)?$")
    for v in (min_version,) + ((max_version,) if max_version is not None
                               else ()):
        if not isinstance(v, str) or not rx.match(v):
            raise TypeError(f"invalid version spec {v!r}")
    return True


def cpu_places(device_count=None):
    """reference framework.py:352 — None (and only None) falls back to
    CPU_NUM."""
    import os as _os
    n = int(_os.environ.get("CPU_NUM", 1)) if device_count is None \
        else int(device_count)
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """reference framework.py:310 — accelerator places (TPU chips here)."""
    import jax as _jax
    if device_ids is None:
        device_ids = range(len(_jax.devices()))
    return [XLAPlace(int(i)) for i in device_ids]


def cuda_pinned_places(device_count=None):
    """Pinned-host staging is XLA-owned; places use the exported
    CUDAPinnedPlace alias so isinstance dispatch stays consistent."""
    import os as _os
    n = int(_os.environ.get("CPU_NUM", 1)) if device_count is None \
        else int(device_count)
    return [CUDAPinnedPlace(0) for _ in range(n)]


def is_compiled_with_cuda():
    """False by definition: this build's accelerator path is TPU/XLA
    (`is_compiled_with_tpu()` is the affirmative probe)."""
    return False


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    """Deprecated no-op in the reference since 1.6
    (memory_optimization_transpiler.py:18); XLA buffer assignment +
    donation own memory planning here."""
    import warnings as _w
    _w.warn("memory_optimize is deprecated and a no-op (XLA owns buffer "
            "planning)", DeprecationWarning, stacklevel=2)


def release_memory(input_program, skip_opt_set=None):
    import warnings as _w
    _w.warn("release_memory is deprecated and a no-op", DeprecationWarning,
            stacklevel=2)


def load_op_library(lib_filename):
    """reference fluid custom-op loader; native extensions load via ctypes
    in this build (native/__init__.py)."""
    raise NotImplementedError(
        "load_op_library loads CUDA .so op libraries; TPU custom kernels "
        "are Pallas/jax functions registered with register_op (see "
        "paddle_tpu/framework/registry.py)")


import contextlib as _contextlib  # noqa: E402


@_contextlib.contextmanager
def device_guard(device=None):
    """reference framework.py:5420 — per-op device placement hint. XLA
    schedules ops itself; host-pinned ops are the host-op segmentation in
    the executor, so the guard is accepted and ignored."""
    yield
