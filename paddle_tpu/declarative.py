"""paddle.declarative — parity with python/paddle/declarative/__init__.py
(aliases of the parameter-creating fluid layer functions)."""
from .layers import (  # noqa: F401
    batch_norm, bilinear_tensor_product, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose, create_parameter, crf_decoding, data_norm,
    deformable_conv, embedding, fc, group_norm, hsigmoid, instance_norm,
    layer_norm, multi_box_head, nce, prelu, row_conv, spectral_norm,
)

__all__ = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "conv2d",
    "conv2d_transpose", "conv3d", "conv3d_transpose", "create_parameter",
    "crf_decoding", "data_norm", "deformable_conv", "group_norm",
    "hsigmoid", "instance_norm", "layer_norm", "multi_box_head", "nce",
    "prelu", "row_conv", "spectral_norm",
]
