"""Model / checkpoint save-load — parity with python/paddle/fluid/io.py
(save_vars:224, save_persistables:598, load_vars:667, load_persistables:902,
save_inference_model:1093, load_inference_model:1303, save:1598, load:1662).

Artifacts use the reference's on-disk formats so models interchange with it:
`__model__` is a binary proto2 ProgramDesc (framework/framework.proto) with
feed/fetch ops appended exactly like the reference's save_inference_model;
params are LoDTensor streams (tensor_util.cc TensorToStream) — one file per
var, or one save_combine stream (program var-declaration order — positional,
no names in the stream) when a filename is given.
The codec lives in framework/paddle_pb.py; legacy JSON/.npz artifacts from
earlier versions of this repo still load (format is sniffed). Orbax-style
async sharded checkpointing for the distributed path lives in
parallel/checkpoint.py.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .framework import paddle_pb
from .framework.core import VarType
from .framework.executor import Executor, Scope, global_scope
from .framework.program import Program, Variable, default_main_program
from .framework.serialization import program_from_desc, program_to_desc

__all__ = [
    "save_vars", "load_vars", "save_persistables", "load_persistables",
    "save_params", "load_params", "save_inference_model", "load_inference_model",
    "save", "load", "set_program_state", "get_program_state",
]


def _scope_np(scope: Scope, name: str):
    v = scope.find_var(name)
    if v is None:
        return None
    arr = np.asarray(v)
    return arr


def _gather_payload(scope, vars):
    payload = {}
    for v in vars:
        name = v.name if isinstance(v, Variable) else v
        arr = _scope_np(scope, name)
        if arr is None:
            continue
        if str(arr.dtype) == "bfloat16":
            arr = arr.astype(np.float32)
        payload[name] = arr
    return payload


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    """filename=None saves one reference-format tensor file per var (the
    reference's per-var `save` ops); a filename saves one save_combine stream
    with vars in program var-declaration order (reference io.py save_vars —
    the stream is positional and carries no names, so save and load must
    iterate the same order; earlier repo revisions wrote sorted-name order,
    and combined files from those revisions will not load positionally)."""
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate is None or predicate(v)]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    payload = _gather_payload(scope, vars)
    if filename is None:
        for name, arr in payload.items():
            paddle_pb.save_tensor_file(os.path.join(dirname, name), arr)
    else:
        # program var-declaration order, matching the reference's
        # save_vars/load_vars contract (io.py:224 iterates list_vars()
        # unsorted; the combined stream carries no names). A var absent
        # from scope must be an error: silently skipping would desync the
        # positional stream from load_vars' name list (the reference's
        # save_combine op likewise rejects uninitialized inputs).
        names = [(v.name if isinstance(v, Variable) else v) for v in vars]
        missing = [n for n in names if n not in payload]
        if missing:
            raise RuntimeError(
                f"save_vars(filename=...): vars not initialized in scope: "
                f"{missing}")
        paddle_pb.save_combine(os.path.join(dirname, filename),
                               [(n, payload[n]) for n in names])


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate is None or predicate(v)]
    scope = global_scope()
    import jax.numpy as jnp

    by_name = {(v.name if isinstance(v, Variable) else v): v for v in vars}

    def _put(name, arr):
        var = by_name.get(name)
        if var is None:
            return
        if isinstance(var, Variable) and var.dtype == "bfloat16":
            arr = jnp.asarray(arr).astype(jnp.bfloat16)
        scope.set_var(name, jnp.asarray(arr))

    legacy = os.path.join(dirname, (filename or "__params__") + ".npz")
    if os.path.exists(legacy):
        data = np.load(legacy)
        for name in data.files:
            _put(name, data[name])
        return
    if filename is None:
        missing = []
        for name in by_name:
            path = os.path.join(dirname, name)
            if os.path.exists(path):
                _put(name, paddle_pb.load_tensor_file(path))
            else:
                missing.append(name)
        if missing and len(missing) == len(by_name):
            raise FileNotFoundError(
                f"no saved tensors for any of {sorted(by_name)} under {dirname}")
    else:
        path = os.path.join(dirname, filename)
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        names = [(v.name if isinstance(v, Variable) else v) for v in vars]
        for name, arr in paddle_pb.load_combine(path, names).items():
            _put(name, arr)


def _is_persistable(v: Variable) -> bool:
    return v.persistable and not v.is_data


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def save_params(executor, dirname, main_program=None, filename=None):
    from .framework.program import Parameter

    save_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def load_params(executor, dirname, main_program=None, filename=None):
    from .framework.program import Parameter

    load_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def save_inference_model(dirname, feeded_var_names: List[str], target_vars,
                         executor, main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Prune the program to the feed→fetch slice (reference framework/prune.cc)
    and save desc + params."""
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    pruned = prune_program(main_program, feeded_var_names,
                           [v.name for v in target_vars])
    os.makedirs(dirname, exist_ok=True)
    desc = program_to_desc(pruned)
    _append_feed_fetch_descs(desc, list(feeded_var_names),
                             [v.name for v in target_vars])
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "wb") as f:
        f.write(paddle_pb.desc_to_pb(desc))
    if not program_only:
        save_persistables(executor, dirname, pruned, filename=params_filename)
    return [v.name for v in target_vars]


def _append_feed_fetch_descs(desc, feed_names, fetch_names):
    """Mirror the reference save_inference_model (io.py:1093): prepend feed
    ops reading columns of the FEED_MINIBATCH var 'feed', append fetch ops
    writing columns of the FETCH_LIST var 'fetch'."""
    block = desc["blocks"][0]
    block["vars"].append({"name": "feed", "shape": [], "dtype": "float32",
                          "type": int(VarType.FEED_MINIBATCH),
                          "persistable": True, "stop_gradient": True,
                          "is_data": False})
    block["vars"].append({"name": "fetch", "shape": [], "dtype": "float32",
                          "type": int(VarType.FETCH_LIST),
                          "persistable": True, "stop_gradient": True,
                          "is_data": False})
    feed_ops = [{"type": "feed", "inputs": {"X": ["feed"]},
                 "outputs": {"Out": [name]}, "attrs": {"col": i}}
                for i, name in enumerate(feed_names)]
    fetch_ops = [{"type": "fetch", "inputs": {"X": [name]},
                  "outputs": {"Out": ["fetch"]}, "attrs": {"col": i}}
                 for i, name in enumerate(fetch_names)]
    block["ops"] = feed_ops + block["ops"] + fetch_ops


def _strip_feed_fetch_descs(desc):
    """Inverse of _append_feed_fetch_descs, applied on load (our executor
    feeds/fetches by name, without feed/fetch ops)."""
    feed_names, fetch_names = [], []
    for block in desc["blocks"]:
        kept = []
        for op in block["ops"]:
            if op["type"] == "feed":
                feed_names.append((op["attrs"].get("col", len(feed_names)),
                                   op["outputs"]["Out"][0]))
            elif op["type"] == "fetch":
                fetch_names.append((op["attrs"].get("col", len(fetch_names)),
                                    op["inputs"]["X"][0]))
            else:
                kept.append(op)
        block["ops"] = kept
        block["vars"] = [v for v in block["vars"]
                         if v["name"] not in ("feed", "fetch")]
    return ([n for _, n in sorted(feed_names)],
            [n for _, n in sorted(fetch_names)])


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "rb") as f:
        raw = f.read()
    if raw[:1] == b"{":  # legacy JSON artifact
        desc = json.loads(raw.decode("utf-8"))
        feed_names = desc.get("_feed_names", [])
        fetch_names = desc.get("_fetch_names", [])
    else:
        desc = paddle_pb.desc_from_pb(raw)
        feed_names, fetch_names = _strip_feed_fetch_descs(desc)
    program = program_from_desc(desc)
    try:
        load_persistables(executor, dirname, program, filename=params_filename)
    except FileNotFoundError:
        pass
    fetch_vars = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


def save(program: Program, model_path: str):
    """Single-file program+params save (fluid.io.save:1598): .pdmodel is the
    binary ProgramDesc, .pdparams a save_combine stream sorted by name."""
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(paddle_pb.desc_to_pb(program_to_desc(program)))
    scope = global_scope()
    payload = _gather_payload(scope, [v for v in program.list_vars()
                                      if v.persistable])
    names = sorted(payload)
    paddle_pb.save_combine(model_path + ".pdparams",
                           [(n, payload[n]) for n in names])


def load(program: Program, model_path: str, executor=None, var_list=None):
    import jax.numpy as jnp

    scope = global_scope()
    names = {v.name for v in (var_list or program.list_vars())}
    legacy = model_path + ".pdparams.npz"
    if os.path.exists(legacy):
        data = np.load(legacy)
        for name in data.files:
            if name in names:
                scope.set_var(name, jnp.asarray(data[name]))
        return
    persistable = {v.name: v for v in program.list_vars() if v.persistable}
    for name, arr in paddle_pb.load_combine(model_path + ".pdparams",
                                            sorted(persistable)).items():
        if name in names:
            out = jnp.asarray(arr)
            if persistable[name].dtype == "bfloat16":
                out = out.astype(jnp.bfloat16)
            scope.set_var(name, out)


def get_program_state(program: Optional[Program] = None):
    program = program or default_main_program()
    scope = global_scope()
    out = {}
    for v in program.list_vars():
        if v.persistable:
            arr = _scope_np(scope, v.name)
            if arr is not None:
                out[v.name] = arr
    return out


def set_program_state(program: Program, state_dict):
    import jax.numpy as jnp

    scope = global_scope()
    for name, arr in state_dict.items():
        scope.set_var(name, jnp.asarray(arr))


def prune_program(program: Program, feed_names: List[str],
                  fetch_names: List[str]) -> Program:
    """Backward slice from fetch vars — parity with framework/prune.cc."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block()
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        if op.type.endswith("_grad") or _is_opt_op(op.type):
            continue
        if any(n in needed for n in op.output_arg_names):
            keep.append(op)
            needed.update(op.input_arg_names)
    keep.reverse()
    block.ops = keep
    used = set(feed_names) | set(fetch_names)
    for op in keep:
        used.update(op.input_arg_names)
        used.update(op.output_arg_names)
    block.vars = {n: v for n, v in block.vars.items() if n in used}
    return pruned


def _is_opt_op(op_type: str) -> bool:
    from .framework.registry import has_op, get_op_spec

    if not has_op(op_type):
        return False
    return get_op_spec(op_type).is_optimizer
