"""Model / checkpoint save-load — parity with python/paddle/fluid/io.py
(save_vars:224, save_persistables:598, load_vars:667, load_persistables:902,
save_inference_model:1093, load_inference_model:1303, save:1598, load:1662).

The reference serializes each LoDTensor through save/load *ops*; here tensors
are jax.Arrays in the Scope, serialized as one .npz per save call plus a JSON
program desc (see framework/serialization.py for the desc format). Orbax-style
async sharded checkpointing for the distributed path lives in
parallel/checkpoint.py.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .framework.executor import Executor, Scope, global_scope
from .framework.program import Program, Variable, default_main_program
from .framework.serialization import program_from_desc, program_to_desc

__all__ = [
    "save_vars", "load_vars", "save_persistables", "load_persistables",
    "save_params", "load_params", "save_inference_model", "load_inference_model",
    "save", "load", "set_program_state", "get_program_state",
]


def _scope_np(scope: Scope, name: str):
    v = scope.find_var(name)
    if v is None:
        return None
    arr = np.asarray(v)
    return arr


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate is None or predicate(v)]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if filename is None:
        filename = "__params__"
    payload = {}
    for v in vars:
        name = v.name if isinstance(v, Variable) else v
        arr = _scope_np(scope, name)
        if arr is None:
            continue
        if str(arr.dtype) == "bfloat16":
            arr = arr.astype(np.float32)
        payload[name] = arr
    np.savez(os.path.join(dirname, filename + ".npz"), **payload)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [v for v in main_program.list_vars() if predicate is None or predicate(v)]
    if filename is None:
        filename = "__params__"
    path = os.path.join(dirname, filename + ".npz")
    data = np.load(path)
    scope = global_scope()
    import jax.numpy as jnp

    by_name = {(v.name if isinstance(v, Variable) else v): v for v in vars}
    for name in data.files:
        if name not in by_name:
            continue
        arr = data[name]
        var = by_name[name]
        if isinstance(var, Variable) and var.dtype == "bfloat16":
            arr = jnp.asarray(arr).astype(jnp.bfloat16)
        scope.set_var(name, jnp.asarray(arr))


def _is_persistable(v: Variable) -> bool:
    return v.persistable and not v.is_data


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def save_params(executor, dirname, main_program=None, filename=None):
    from .framework.program import Parameter

    save_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def load_params(executor, dirname, main_program=None, filename=None):
    from .framework.program import Parameter

    load_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def save_inference_model(dirname, feeded_var_names: List[str], target_vars,
                         executor, main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Prune the program to the feed→fetch slice (reference framework/prune.cc)
    and save desc + params."""
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    pruned = prune_program(main_program, feeded_var_names,
                           [v.name for v in target_vars])
    os.makedirs(dirname, exist_ok=True)
    desc = program_to_desc(pruned)
    desc["_feed_names"] = list(feeded_var_names)
    desc["_fetch_names"] = [v.name for v in target_vars]
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "w") as f:
        json.dump(desc, f)
    if not program_only:
        save_persistables(executor, dirname, pruned, filename=params_filename)
    return [v.name for v in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename)) as f:
        desc = json.load(f)
    program = program_from_desc(desc)
    feed_names = desc.get("_feed_names", [])
    fetch_names = desc.get("_fetch_names", [])
    try:
        load_persistables(executor, dirname, program, filename=params_filename)
    except FileNotFoundError:
        pass
    fetch_vars = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


def save(program: Program, model_path: str):
    """Single-file program+params save (fluid.io.save:1598)."""
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdmodel", "w") as f:
        json.dump(program_to_desc(program), f)
    scope = global_scope()
    payload = {}
    for v in program.list_vars():
        if v.persistable:
            arr = _scope_np(scope, v.name)
            if arr is not None:
                payload[v.name] = arr
    np.savez(model_path + ".pdparams.npz", **payload)


def load(program: Program, model_path: str, executor=None, var_list=None):
    import jax.numpy as jnp

    data = np.load(model_path + ".pdparams.npz")
    scope = global_scope()
    names = {v.name for v in (var_list or program.list_vars())}
    for name in data.files:
        if name in names:
            scope.set_var(name, jnp.asarray(data[name]))


def get_program_state(program: Optional[Program] = None):
    program = program or default_main_program()
    scope = global_scope()
    out = {}
    for v in program.list_vars():
        if v.persistable:
            arr = _scope_np(scope, v.name)
            if arr is not None:
                out[v.name] = arr
    return out


def set_program_state(program: Program, state_dict):
    import jax.numpy as jnp

    scope = global_scope()
    for name, arr in state_dict.items():
        scope.set_var(name, jnp.asarray(arr))


def prune_program(program: Program, feed_names: List[str],
                  fetch_names: List[str]) -> Program:
    """Backward slice from fetch vars — parity with framework/prune.cc."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block()
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        if op.type.endswith("_grad") or _is_opt_op(op.type):
            continue
        if any(n in needed for n in op.output_arg_names):
            keep.append(op)
            needed.update(op.input_arg_names)
    keep.reverse()
    block.ops = keep
    used = set(feed_names) | set(fetch_names)
    for op in keep:
        used.update(op.input_arg_names)
        used.update(op.output_arg_names)
    block.vars = {n: v for n, v in block.vars.items() if n in used}
    return pruned


def _is_opt_op(op_type: str) -> bool:
    from .framework.registry import has_op, get_op_spec

    if not has_op(op_type):
        return False
    return get_op_spec(op_type).is_optimizer
