"""Gradient merge (batch-merge) — parity with the reference's
multi_batch_merge pass (framework/ir/multi_batch_merge_pass.cc), which
repeats the forward/backward subgraph k times per iteration and applies the
optimizer once on the merged gradients.

TPU-native shape: ONE compiled program whose fwd+bwd region runs as a
``lax.scan`` over k microbatch slices of the fed batch, accumulating the
gradient vars the optimizer tail consumes; the tail then applies once on
the averaged grads. Semantics match a single large-batch step exactly when
the loss is a batch mean (tested)."""
from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import LowerCtx, run_lowering


def annotate_grad_merge(program, loss, bwd_end, k_steps,
                        grad_names, avg=True, remat_policy="none",
                        acc_dtype="float32"):
    from . import remat as remat_mod

    block = program.global_block()
    # anchor the fwd/bwd <-> optimizer-tail boundary on the OPS, not on an
    # absolute index: a later fleet transpile (GradAllReduce inserts
    # c_allreduce after each grad's last write) shifts indices, and a stale
    # bwd_end would truncate the scanned region
    for op in block.ops[bwd_end:]:
        op._set_attr("__opt_tail__", 1)
    program._annotations["grad_merge"] = {
        "bwd_end": bwd_end,
        "k": int(k_steps),
        "loss": loss.name,
        "grads": list(grad_names),
        "avg": bool(avg),
        "remat": remat_mod.resolve(remat_policy).name,
        # accumulator dtype for the k-microbatch grad sum; f32 default
        # regardless of param dtype (bf16 accumulation drifts over k)
        "acc_dtype": str(acc_dtype),
    }
    program._bump_version()


def resolve_tail_start(ops, fallback):
    """Index of the first optimizer-tail op (see annotate_* anchors);
    robust to ops inserted into the fwd/bwd region after minimize()."""
    for idx, op in enumerate(ops):
        if op.attr("__opt_tail__", 0):
            return idx
    return fallback


class _CompiledGradMergeBlock:
    """Executor counterpart for grad_merge-annotated programs (same call
    contract as executor._CompiledBlock).

    Composes with data parallelism the way the reference's
    multi_batch_merge_pass composes with ParallelExecutor: when a
    ``mesh_plan`` is present the k-microbatch scan runs per device shard —
    ``gspmd`` mode shards the fed batch over the dp axis and lets the XLA
    partitioner insert gradient all-reduces; ``shard_map`` mode runs the
    per-rank program whose own c_allreduce_* ops sync the merged grads
    (once per k microbatches, on the optimizer tail)."""

    def __init__(self, program, feed_sig, fetch_names, param_names,
                 written_names, scope, mesh_plan=None):
        ann = program._annotations["grad_merge"]
        block = program.global_block()
        ops = block.ops
        k = ann["k"]
        bwd_end = resolve_tail_start(ops, ann["bwd_end"])
        loss_name = ann["loss"]
        grad_names = [g for g in ann["grads"] if g]
        avg = ann["avg"]
        acc_dtype = jnp.dtype(ann.get("acc_dtype", "float32"))
        self.program = program
        self.feed_names = [n for n, _, _ in feed_sig]
        self.fetch_names = list(fetch_names)
        self.param_names = list(param_names)
        self.written_names = list(written_names)
        self.mesh_plan = mesh_plan
        mesh_axes = dict(mesh_plan.ring_axes) if mesh_plan else {}

        batched = set()
        batch = None
        for name, shape, _ in feed_sig:
            var = block.vars.get(name)
            if getattr(var, "is_data", False) and shape:
                if batch is None:
                    batch = shape[0]
                elif shape[0] != batch:
                    raise ValueError(
                        f"gradient merge: data feed {name!r} has leading "
                        f"dim {shape[0]} != batch {batch}; all data feeds "
                        "must share the batch dimension")
                batched.add(name)
        if batch is None:
            raise ValueError("gradient merge needs batched data feeds")

        # per-rank batch: in shard_map mode each rank sees batch/dp rows
        # (feeds shard over the single data axis; anything else is out of
        # scope for a fluid grad-merge program and must fail loudly)
        shard_ranks = 1
        shard_mesh = None
        if mesh_plan is not None and mesh_plan.mode != "single":
            from .mesh import build_mesh
            shard_mesh = build_mesh(mesh_plan.axes)
            if mesh_plan.mode == "shard_map":
                if mesh_plan.data_axis is None or len(mesh_plan.axes) > 1:
                    raise NotImplementedError(
                        "gradient merge composes with a single "
                        f"data-parallel axis; mesh plan has axes "
                        f"{mesh_plan.axes} data_axis={mesh_plan.data_axis}")
                shard_ranks = int(shard_mesh.shape[mesh_plan.data_axis])
        local_batch = batch // shard_ranks if shard_ranks > 1 else batch
        if shard_ranks > 1 and batch % shard_ranks:
            raise ValueError(
                f"batch {batch} not divisible by {shard_ranks} dp ranks")
        if local_batch % k:
            raise ValueError(
                f"per-rank batch {local_batch} not divisible by k_steps {k}")
        mb = local_batch // k
        self._batched = batched

        # persistables mutated in the fwd/bwd region (batch_norm stats)
        # must thread through the scan carry and reach the tail env
        fwd_written = [n for n in written_names
                       if any(n in op.output_arg_names
                              for op in ops[:bwd_end])]
        # forward intermediates a caller may fetch (values come from the
        # LAST microbatch; the loss itself is averaged over all k)
        fwd_fetch = [n for n in fetch_names
                     if n != loss_name and n not in grad_names
                     and any(n in op.output_arg_names
                             for op in ops[:bwd_end])]

        def fn(mutable_params, const_params, feeds, rng_key):
            params = dict(const_params)
            params.update(mutable_params)
            split = {n: (f.reshape((k, mb) + tuple(f.shape[1:]))
                         if n in batched else f)
                     for n, f in feeds.items()}

            def seed_env(i):
                env = dict(params)
                for n, f in split.items():
                    env[n] = (jax.lax.dynamic_index_in_dim(
                        f, i, 0, keepdims=False) if n in batched else f)
                return env

            keep = (set(grad_names) | set(fwd_written) | set(fwd_fetch)
                    | {loss_name})

            def run_fwd_bwd(env0, key):
                """One microbatch's fwd+bwd region, functionally: env in ->
                needed outputs out (so the remat policy can wrap it)."""
                env = dict(env0)
                ctx = LowerCtx(program, block, env, rng_key=key,
                               mesh_axes=mesh_axes)
                for op in ops[:bwd_end]:
                    run_lowering(ctx, op)
                return {n: env[n] for n in keep if n in env}

            from . import remat as remat_mod

            policy = remat_mod.resolve(ann.get("remat", "none"))
            if not policy.is_none:
                run_fwd_bwd = policy.wrap(run_fwd_bwd)

            def body(carry, i):
                acc, loss_acc, state, _ = carry
                env = seed_env(i)
                env.update(state)  # sequential persistable updates (BN)
                # distinct randomness per microbatch (dropout masks)
                outs = run_fwd_bwd(env, jax.random.fold_in(rng_key, i))
                new_acc = {g: acc[g] + outs[g].astype(acc_dtype)
                           for g in grad_names}
                new_state = {n: outs[n] for n in fwd_written if n in outs}
                fetched = {n: outs[n] for n in fwd_fetch if n in outs}
                return (new_acc, loss_acc + outs[loss_name]
                        .astype(jnp.float32), new_state, fetched), None

            # abstract probe shapes the accumulator / carry pytrees
            def probe():
                outs = run_fwd_bwd(seed_env(0), jax.random.PRNGKey(0))
                return ({g: outs[g] for g in grad_names},
                        {n: outs[n] for n in fwd_written if n in outs},
                        {n: outs[n] for n in fwd_fetch if n in outs})

            g_shapes, s_shapes, f_shapes = jax.eval_shape(probe)
            acc0 = {g: jnp.zeros(sh.shape, acc_dtype)
                    for g, sh in g_shapes.items()}
            state0 = {n: params[n].astype(s_shapes[n].dtype)
                      if n in params else jnp.zeros(s_shapes[n].shape,
                                                    s_shapes[n].dtype)
                      for n in s_shapes}
            fetch0 = {n: jnp.zeros(sh.shape, sh.dtype)
                      for n, sh in f_shapes.items()}
            (acc, loss_sum, fwd_state, fetched), _ = jax.lax.scan(
                body, (acc0, jnp.float32(0.0), state0, fetch0),
                jnp.arange(k))

            env = dict(params)
            env.update({n: f for n, f in feeds.items() if n not in batched})
            env.update(fwd_state)
            env.update(fetched)
            scale = 1.0 / k if avg else 1.0
            for g in grad_names:
                # keep the optimizer-input dtype identical to the
                # non-merged path (bf16 programs must stay bf16)
                env[g] = (acc[g] * scale).astype(g_shapes[g].dtype)
            env[loss_name] = loss_sum / k
            ctx = LowerCtx(program, block, env, rng_key=rng_key,
                           mesh_axes=mesh_axes)
            for op in ops[bwd_end:]:
                run_lowering(ctx, op)
            fetches = [jnp.atleast_1d(env[n]) for n in self.fetch_names]
            new_state = {n: env[n] for n in self.written_names if n in env}
            return fetches, new_state

        written = set(written_names)
        self.mesh = None
        if mesh_plan is None or mesh_plan.mode == "single":
            self._jitted = jax.jit(fn, donate_argnums=(0,))
            return

        from .mesh import (jit_shard_map, named_sharding,
                           probe_produced_state)

        mesh = shard_mesh
        self.mesh = mesh
        n_dev = int(np.prod(mesh.devices.shape))
        data_axis = mesh_plan.data_axis

        def feed_dims(shape):
            if shape and shape[0] > 0 and shape[0] % n_dev == 0:
                return (data_axis,) + (None,) * (len(shape) - 1)
            return None

        if mesh_plan.mode == "gspmd":
            mutable_sh = {n: named_sharding(mesh, None)
                          for n in self.param_names if n in written}
            const_sh = {n: named_sharding(mesh, None)
                        for n in self.param_names if n not in written}
            feed_sh = {n: named_sharding(mesh,
                                         feed_dims(shape) if n in batched
                                         else None)
                       for n, shape, _ in feed_sig}
            self._jitted = jax.jit(
                fn,
                in_shardings=(mutable_sh, const_sh, feed_sh,
                              named_sharding(mesh, None)),
                donate_argnums=(0,))
            return

        # shard_map: per-rank semantics, program's own c_* ops sync grads
        from jax.sharding import PartitionSpec as P

        from .mesh import aval_of, feed_aval

        mutable_avals = {n: aval_of(scope.find_var(n)) for n in param_names
                         if n in written and scope.has_var(n)}
        const_avals = {n: aval_of(scope.find_var(n)) for n in param_names
                       if n not in written and scope.has_var(n)}
        feed_avals = {
            n: feed_aval(((shape[0] // shard_ranks,) + tuple(shape[1:]))
                         if n in batched else tuple(shape), dt)
            for n, shape, dt in feed_sig}
        produced = probe_produced_state(fn, mutable_avals, const_avals,
                                        feed_avals, self.written_names)

        def per_rank(mutable_params, const_params, feeds, rng_key):
            fetches, new_state = fn(mutable_params, const_params, feeds,
                                    rng_key)
            return fetches, {n: new_state[n] for n in produced}

        mutable_specs = {n: P() for n in self.param_names if n in written}
        const_specs = {n: P() for n in self.param_names if n not in written}
        feed_specs = {n: (P(data_axis) if n in batched else P())
                      for n, _, _ in feed_sig}
        fetch_specs = [P(data_axis) for _ in fetch_names]
        state_specs = {n: P() for n in produced}
        self._jitted = jit_shard_map(
            per_rank, mesh,
            in_specs=(mutable_specs, const_specs, feed_specs, P()),
            out_specs=(fetch_specs, state_specs),
            donate_argnums=(0,))

    def __call__(self, scope, feed, rng_key):
        mutable, const = {}, {}
        written = set(self.written_names)
        for n in self.param_names:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"persistable var {n!r} is not initialized in scope — "
                    "run the startup program first")
            (mutable if n in written else const)[n] = v
        feeds = {n: feed[n] for n in self.feed_names}
        fetches, new_state = self._jitted(mutable, const, feeds, rng_key)
        for n, v in new_state.items():
            scope.set_var(n, v)
        return fetches
