"""In-run health: hang watchdog, straggler detection, divergence guardrails.

PR 7 made the framework survive process *death*; this module handles ranks
that are alive and sick (docs/health.md):

- **Hang watchdog** (:class:`HangWatchdog`): a per-worker monitor thread fed
  by cheap progress stamps at dispatch boundaries (``Executor.run``, the
  parallel engine's train step, the prefetch consumer).  No progress for a
  configurable deadline -> dump every thread's stack plus a PR 4-style
  forensics bundle, count ``paddle_hangs_total{site}``, and ``os._exit``
  with :data:`HANG_EXIT_CODE` — a code the ``parallel/launch.py`` supervisor
  maps to a gang restart with ``cause=hang`` (resuming from the PR 7
  checkpoints).  Known-long host phases (XLA compiles) run under
  :func:`suspend` so they never count against the deadline.

- **Straggler detection**: each rank's :class:`RankHeartbeat` writes
  ``{step, step-time EWMA}`` to a shared run dir; :func:`detect_stragglers`
  (polled by the supervisor via :class:`StragglerMonitor`) flags ranks whose
  EWMA skews beyond ``ratio`` x the gang median —
  ``paddle_straggler_detected_total{rank}`` plus a rate-limited warning
  naming the slow rank.

- **Divergence guardrails** (:class:`DivergenceGuard`): bounded skip-batch
  on NaN/Inf or loss-spike steps, and after K consecutive bad steps an
  automatic rollback to the latest valid ``ElasticCheckpointer`` step with
  optional LR cooldown.  The *decision* depends only on the (already
  all-reduced) loss value, so every dp rank takes the same branch and the
  collectives stay matched; the pure-JAX engine additionally gets the
  in-jit :func:`nonfinite_guard` (``make_train_step(skip_nonfinite=True)``)
  whose skip predicate is a psum'd scalar — identical on every rank by
  construction (the AMP ``bad_steps`` idea of
  contrib/mixed_precision/decorator.py, generalized to full precision).
"""
from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Tuple

from ..observability import metrics as _obs_metrics

__all__ = [
    "HANG_EXIT_CODE", "HangWatchdog", "progress", "suspend",
    "install_watchdog", "uninstall_watchdog", "current_watchdog",
    "maybe_install_from_env",
    "RankHeartbeat", "read_heartbeats", "detect_stragglers",
    "StragglerMonitor",
    "GuardrailConfig", "DivergenceGuard", "DivergenceError",
    "nonfinite_guard",
]

#: Distinct exit code a worker uses when its own watchdog declares it hung.
#: ``parallel.launch`` maps it to a supervised gang restart with
#: ``cause=hang`` (any other nonzero exit is ``crash``; an untrapped
#: SIGTERM death is ``preempt``).
HANG_EXIT_CODE = 43

# env contract (exported by launch(..., hang_deadline_s=, health_dir=))
ENV_DEADLINE = "PADDLE_HEALTH_DEADLINE_S"
ENV_DIR = "PADDLE_HEALTH_DIR"
ENV_INTERVAL = "PADDLE_HEALTH_CHECK_INTERVAL_S"

_REG = _obs_metrics.default_registry()
_m_hangs = _REG.counter(
    "paddle_hangs_total",
    "Hang-watchdog firings by last-progress site", ("site",))
_m_straggler = _REG.counter(
    "paddle_straggler_detected_total",
    "Straggler detections by rank (EWMA step time beyond ratio x median)",
    ("rank",))
_g_ewma = _REG.gauge(
    "paddle_rank_step_time_ewma_ms",
    "Per-rank heartbeat step-time EWMA (ms)", ("rank",))
_m_skipped = _REG.counter(
    "paddle_guardrail_skipped_steps_total",
    "Training steps skipped by the divergence guardrail", ("reason",))
_m_rollbacks = _REG.counter(
    "paddle_guardrail_rollbacks_total",
    "Automatic rollbacks to the latest valid checkpoint")


# ---------------------------------------------------------------------------
# Hang watchdog
# ---------------------------------------------------------------------------

def _dump_all_stacks() -> str:
    """Every live thread's Python stack as text (the watchdog's core
    forensic: WHERE each thread is stuck)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.extend(ln.rstrip() for ln in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


class HangWatchdog:
    """Monitor thread that declares the process hung when no progress stamp
    lands for ``deadline_s`` seconds.

    Hot-path contract: :meth:`note` is a single tuple store — call it from
    dispatch boundaries at will.  :meth:`suspend` (or the module-level
    :func:`suspend`) brackets known-long host phases (first-call XLA
    compiles) so they never count against the deadline.

    On firing the watchdog writes a forensics bundle under ``dump_dir``
    (all-thread stacks, last-progress info, flag state, a metrics-registry
    snapshot), counts ``paddle_hangs_total{site}``, invokes ``on_hang`` and
    — with ``exit_on_hang`` (the production default) — ``os._exit``\\ s with
    :data:`HANG_EXIT_CODE` so the supervisor restarts the gang.
    """

    def __init__(self, deadline_s: float, check_interval_s: Optional[float] = None,
                 dump_dir: Optional[str] = None, exit_on_hang: bool = True,
                 on_hang: Optional[Callable[[dict], None]] = None):
        self.deadline_s = float(deadline_s)
        if self.deadline_s <= 0:
            raise ValueError("hang deadline must be > 0 seconds")
        self.check_interval_s = float(
            check_interval_s if check_interval_s is not None
            else max(0.05, min(1.0, self.deadline_s / 4)))
        self.dump_dir = dump_dir
        self.exit_on_hang = exit_on_hang
        self.on_hang = on_hang
        self.fired = False
        self.dump_path: Optional[str] = None
        self._stamp: Tuple[str, int] = ("start", time.monotonic_ns())
        self._suspended = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- hot path ---------------------------------------------------------
    def note(self, site: str) -> None:
        """Progress stamp: one tuple store (atomic under the GIL)."""
        self._stamp = (site, time.monotonic_ns())

    @contextlib.contextmanager
    def suspend(self):
        """Pause the deadline clock for a known-long host phase (compile,
        checkpoint restore).  Re-stamps on exit so the suspended span never
        counts."""
        self._suspended += 1
        try:
            yield
        finally:
            self._suspended -= 1
            self.note("resume")

    # -- introspection ----------------------------------------------------
    def last_progress(self) -> Tuple[str, float]:
        """(site, age in seconds) of the most recent stamp."""
        site, ts = self._stamp
        return site, (time.monotonic_ns() - ts) / 1e9

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "HangWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self.note("start")
            self._thread = threading.Thread(
                target=self._run, name="hang-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2 * self.check_interval_s + 1)

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            if self._suspended > 0:
                # clock paused; suspend() re-stamps on exit
                self.note(self._stamp[0])
                continue
            site, age = self.last_progress()
            if age > self.deadline_s:
                self._fire(site, age)
                return

    def _fire(self, site: str, age: float) -> None:
        self.fired = True
        info = {
            "reason": "hang",
            "site": site,
            "last_progress_age_s": round(age, 3),
            "deadline_s": self.deadline_s,
            "pid": os.getpid(),
            "rank": os.environ.get("PADDLE_TRAINER_ID"),
            "ts": time.time(),
            "exit_code": HANG_EXIT_CODE,
        }
        stacks = _dump_all_stacks()
        sys.stderr.write(
            f"[hang-watchdog] no progress for {age:.1f}s "
            f"(deadline {self.deadline_s}s, last site {site!r}) — "
            f"dumping stacks and exiting {HANG_EXIT_CODE}\n")
        try:
            self.dump_path = self._write_bundle(info, stacks)
            info["dump"] = self.dump_path
        except Exception as e:  # forensics must never mask the exit
            sys.stderr.write(f"[hang-watchdog] bundle write failed: {e}\n")
            sys.stderr.write(stacks + "\n")
        _m_hangs.labels(site).inc()
        if self.on_hang is not None:
            try:
                self.on_hang(info)
            except Exception:
                pass
        if self.exit_on_hang:
            sys.stderr.flush()
            os._exit(HANG_EXIT_CODE)

    def _write_bundle(self, info: dict, stacks: str) -> Optional[str]:
        """PR 4-style self-contained forensics directory:

            <dump_dir>/hang_rank<R>_pid<P>/
              hang_info.json   site, age, deadline, pid/rank, exit code
              stacks.txt       every thread's Python stack
              flags.json       full framework flag state
              metrics.json     metrics-registry snapshot at the hang
        """
        if not self.dump_dir:
            sys.stderr.write(stacks + "\n")
            return None
        rank = info.get("rank") or "0"
        d = os.path.join(str(self.dump_dir),
                         f"hang_rank{rank}_pid{info['pid']}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "hang_info.json"), "w") as f:
            json.dump(info, f, indent=1)
        with open(os.path.join(d, "stacks.txt"), "w") as f:
            f.write(stacks)
        try:
            from ..framework.core import flags_snapshot

            with open(os.path.join(d, "flags.json"), "w") as f:
                json.dump({k: v if isinstance(
                    v, (str, int, float, bool, type(None))) else repr(v)
                    for k, v in flags_snapshot().items()}, f, indent=1)
        except Exception:
            pass
        try:
            with open(os.path.join(d, "metrics.json"), "w") as f:
                json.dump(_REG.snapshot(), f, indent=1, default=str)
        except Exception:
            pass
        try:
            # flight-recorder ring snapshot (ISSUE 19): the event tail —
            # last steps, last collective seq entered — lands next to the
            # stacks, and tools/flight_assemble.py names the blamed rank
            from ..observability import flight as _flight

            _flight.dump("hang", dir_path=d)
        except Exception:
            pass
        return d


_watchdog: Optional[HangWatchdog] = None


def progress(site: str) -> None:
    """Module-level progress stamp — a no-op (one global read) until a
    watchdog is installed, so hot paths call it unconditionally."""
    w = _watchdog
    if w is not None:
        w.note(site)


@contextlib.contextmanager
def suspend():
    """Module-level :meth:`HangWatchdog.suspend` — pauses the deadline
    clock (no-op without a watchdog).  Executor/engine compiles run under
    this, so the window doubles as a goodput instrumentation point: its
    wall time is charged to the ledger's ``compile`` category (nesting
    with the executor's own compile timer is exclusive-time safe)."""
    from ..observability import goodput as _goodput

    w = _watchdog
    if w is None:
        with _goodput.timer("compile"):
            yield
        return
    with w.suspend(), _goodput.timer("compile"):
        yield


def install_watchdog(deadline_s: float, **kw) -> HangWatchdog:
    """Install (and start) the process-wide watchdog.  Re-installing
    replaces the previous one."""
    global _watchdog
    if _watchdog is not None:
        _watchdog.stop()
    w = HangWatchdog(deadline_s, **kw)
    _watchdog = w
    w.start()
    return w


def uninstall_watchdog() -> None:
    global _watchdog
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None


def current_watchdog() -> Optional[HangWatchdog]:
    return _watchdog


def maybe_install_from_env() -> Optional[HangWatchdog]:
    """Install the watchdog from the launcher's env contract
    (``PADDLE_HEALTH_DEADLINE_S`` + ``PADDLE_HEALTH_DIR``); idempotent, and
    a no-op when the env is unset.  ``Executor.train_from_dataset`` and the
    bench/fault workers call this on entry so every supervised worker is
    watched without per-callsite plumbing."""
    deadline = os.environ.get(ENV_DEADLINE)
    if not deadline:
        return _watchdog
    if _watchdog is not None:
        return _watchdog
    interval = os.environ.get(ENV_INTERVAL)
    return install_watchdog(
        float(deadline),
        check_interval_s=float(interval) if interval else None,
        dump_dir=os.environ.get(ENV_DIR))


# ---------------------------------------------------------------------------
# Straggler detection: per-rank heartbeats on a shared run dir
# ---------------------------------------------------------------------------

_HB_PREFIX = "heartbeat.rank"


class RankHeartbeat:
    """Worker-side heartbeat writer: per-step EWMA of step time, persisted
    atomically to ``<dir>/heartbeat.rank<N>.json`` (rate-limited to one
    write per ``min_write_interval_s`` so the hot loop pays a dict dump at
    most a few times a second)."""

    def __init__(self, dirname: str, rank: int, alpha: float = 0.2,
                 min_write_interval_s: float = 0.5):
        self.dirname = str(dirname)
        os.makedirs(self.dirname, exist_ok=True)
        self.rank = int(rank)
        self.alpha = float(alpha)
        self.min_write_interval_s = float(min_write_interval_s)
        self.ewma_ms: Optional[float] = None
        self.step = 0
        self._last_beat_ns: Optional[int] = None
        self._last_write = 0.0
        self.path = os.path.join(self.dirname,
                                 f"{_HB_PREFIX}{self.rank}.json")

    def beat(self, step: Optional[int] = None,
             step_time_ms: Optional[float] = None) -> None:
        """Record one step.  ``step_time_ms`` defaults to the wall time
        since the previous beat."""
        now = time.monotonic_ns()
        if step_time_ms is None:
            if self._last_beat_ns is None:
                self._last_beat_ns = now
                self.step = int(step) if step is not None else self.step + 1
                return
            step_time_ms = (now - self._last_beat_ns) / 1e6
        self._last_beat_ns = now
        self.step = int(step) if step is not None else self.step + 1
        if self.ewma_ms is None:
            self.ewma_ms = float(step_time_ms)
        else:
            self.ewma_ms += self.alpha * (float(step_time_ms) - self.ewma_ms)
        wall = time.time()
        if wall - self._last_write >= self.min_write_interval_s:
            self._write(wall)

    def flush(self) -> None:
        if self.ewma_ms is not None:
            self._write(time.time())

    def _write(self, wall: float) -> None:
        rec = {"rank": self.rank, "step": self.step,
               "ewma_ms": round(self.ewma_ms, 4), "ts": wall,
               "pid": os.getpid()}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, self.path)
            self._last_write = wall
        except OSError:  # heartbeat is advisory, never fatal
            pass


def read_heartbeats(dirname: str, max_age_s: Optional[float] = None
                    ) -> Dict[int, dict]:
    """All rank heartbeat records under ``dirname`` (stale ones older than
    ``max_age_s`` dropped)."""
    out: Dict[int, dict] = {}
    try:
        names = os.listdir(str(dirname))
    except OSError:
        return out
    now = time.time()
    for name in names:
        if not (name.startswith(_HB_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(str(dirname), name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue  # torn read of an in-flight replace; next poll wins
        if max_age_s is not None and now - rec.get("ts", 0) > max_age_s:
            continue
        out[int(rec["rank"])] = rec
    return out


def detect_stragglers(heartbeats, ratio: float = 2.0,
                      min_ranks: int = 2) -> List[dict]:
    """Flag ranks whose step-time EWMA exceeds ``ratio`` x the gang median.

    ``heartbeats``: a dir path or a ``{rank: record}`` dict from
    :func:`read_heartbeats`.  Needs at least ``min_ranks`` reporting ranks
    (a median of one is meaningless).  Returns one finding dict per slow
    rank: ``{rank, ewma_ms, median_ms, ratio}``.
    """
    if not isinstance(heartbeats, dict):
        heartbeats = read_heartbeats(heartbeats)
    ewmas = {r: rec["ewma_ms"] for r, rec in heartbeats.items()
             if rec.get("ewma_ms") is not None}
    if len(ewmas) < max(2, int(min_ranks)):
        return []
    vals = sorted(ewmas.values())
    # lower median: with an even rank count the upper-middle value may
    # itself be the straggler, and averaging it in dilutes the threshold
    # (a 2-rank gang would otherwise need a 3x skew to flag at ratio=2)
    median = vals[(len(vals) - 1) // 2]
    if median <= 0:
        return []
    out = []
    for rank, ewma in sorted(ewmas.items()):
        if ewma > ratio * median:
            out.append({"rank": rank, "ewma_ms": round(ewma, 3),
                        "median_ms": round(median, 3),
                        "ratio": round(ewma / median, 3)})
    return out


class StragglerMonitor:
    """Supervisor-side poller: reads the heartbeat dir, counts
    ``paddle_straggler_detected_total{rank}``, mirrors every rank's EWMA
    into ``paddle_rank_step_time_ewma_ms{rank}``, and warns — rate-limited
    to once per ``warn_cooldown_s`` per rank — naming the slow rank."""

    def __init__(self, dirname: str, ratio: float = 2.0,
                 min_ranks: int = 2, warn_cooldown_s: float = 30.0,
                 log: Optional[Callable[[str], None]] = None):
        self.dirname = str(dirname)
        self.ratio = float(ratio)
        self.min_ranks = int(min_ranks)
        self.warn_cooldown_s = float(warn_cooldown_s)
        self.log = log or (lambda m: sys.stderr.write(m + "\n"))
        self.detections = 0
        self._last_warn: Dict[int, float] = {}

    def poll(self) -> List[dict]:
        hb = read_heartbeats(self.dirname)
        for rank, rec in hb.items():
            if rec.get("ewma_ms") is not None:
                _g_ewma.labels(str(rank)).set(rec["ewma_ms"])
        findings = detect_stragglers(hb, ratio=self.ratio,
                                     min_ranks=self.min_ranks)
        now = time.monotonic()
        for f in findings:
            self.detections += 1
            _m_straggler.labels(str(f["rank"])).inc()
            if now - self._last_warn.get(f["rank"], -1e18) \
                    >= self.warn_cooldown_s:
                self._last_warn[f["rank"]] = now
                self.log(
                    f"[health] straggler: rank {f['rank']} step-time EWMA "
                    f"{f['ewma_ms']:.1f}ms is {f['ratio']:.1f}x the gang "
                    f"median {f['median_ms']:.1f}ms "
                    f"(threshold {self.ratio}x)")
        return findings


# ---------------------------------------------------------------------------
# Divergence guardrails
# ---------------------------------------------------------------------------

class DivergenceError(RuntimeError):
    """Raised when the guardrail exhausted its rollback budget — the run
    cannot self-heal and needs a human (docs/health.md runbook)."""


class GuardrailConfig:
    """Divergence-guardrail policy (docs/health.md).

    - ``skip_nonfinite``: a NaN/Inf loss marks the step bad.
    - ``spike_mult``: a finite loss above ``spike_mult`` x the rolling
      median of the last ``window`` good losses (needs ``min_history``)
      marks the step bad; ``None`` disables spike detection.
    - ``max_consecutive_bad`` (K): after K consecutive bad steps the guard
      asks for a rollback to the latest valid checkpoint (skip-batch alone
      cannot heal a poisoned *state*, only a poisoned *batch*).
    - ``lr_cooldown``: multiplier applied to the learning rate at each
      rollback (1.0 disables).
    - ``max_rollbacks``: rollback budget; exceeding it raises
      :class:`DivergenceError`.
    """

    def __init__(self, skip_nonfinite: bool = True,
                 spike_mult: Optional[float] = None, window: int = 32,
                 min_history: int = 5, max_consecutive_bad: int = 3,
                 lr_cooldown: float = 0.5, max_rollbacks: int = 2):
        self.skip_nonfinite = bool(skip_nonfinite)
        self.spike_mult = None if spike_mult is None else float(spike_mult)
        self.window = int(window)
        self.min_history = int(min_history)
        self.max_consecutive_bad = int(max_consecutive_bad)
        self.lr_cooldown = float(lr_cooldown)
        self.max_rollbacks = int(max_rollbacks)


class DivergenceGuard:
    """Per-step bad-step judge + rollback bookkeeping.

    The caller feeds each step's loss to :meth:`judge` and acts on the
    verdict: ``"ok"`` (continue), ``"skip"`` (discard this step's update),
    ``"rollback"`` (restore the latest valid checkpoint, then call
    :meth:`rolled_back`).  Decisions depend only on the loss value — which
    is identical on every dp rank after the loss all-reduce — so a
    multi-rank gang takes the same branch everywhere and collectives stay
    matched.
    """

    def __init__(self, config: Optional[GuardrailConfig] = None):
        self.config = config or GuardrailConfig()
        self.consecutive_bad = 0
        self.skipped_steps = 0
        self.rollbacks = 0
        self.last_reason: Optional[str] = None
        self._history: List[float] = []

    def _median(self) -> Optional[float]:
        if len(self._history) < self.config.min_history:
            return None
        vals = sorted(self._history)
        n = len(vals)
        return (vals[n // 2] if n % 2 else
                0.5 * (vals[n // 2 - 1] + vals[n // 2]))

    def _is_bad(self, loss: float) -> Optional[str]:
        import math

        if not math.isfinite(loss):
            return "nonfinite" if self.config.skip_nonfinite else None
        if self.config.spike_mult is not None:
            med = self._median()
            if med is not None and med > 0 \
                    and loss > self.config.spike_mult * med:
                return "spike"
        return None

    def judge(self, loss) -> str:
        """Classify one step by its loss; returns "ok" | "skip" |
        "rollback"."""
        import numpy as np

        arr = np.asarray(loss)
        val = float(arr.ravel()[0]) if arr.size else float("nan")
        reason = self._is_bad(val)
        if reason is None:
            self.consecutive_bad = 0
            self.last_reason = None
            self._history.append(val)
            del self._history[:-self.config.window]
            return "ok"
        self.consecutive_bad += 1
        self.skipped_steps += 1
        self.last_reason = reason
        _m_skipped.labels(reason).inc()
        if self.consecutive_bad >= self.config.max_consecutive_bad:
            return "rollback"
        return "skip"

    def rolled_back(self) -> None:
        """Record a performed rollback; raises :class:`DivergenceError`
        when the budget is spent."""
        self.rollbacks += 1
        self.consecutive_bad = 0
        _m_rollbacks.inc()
        if self.rollbacks > self.config.max_rollbacks:
            raise DivergenceError(
                f"divergence guardrail exhausted: {self.rollbacks} rollbacks "
                f"(budget {self.config.max_rollbacks}) and the loss is still "
                f"bad (last reason: {self.last_reason}) — see "
                "docs/health.md runbook")


def nonfinite_guard(old_state, new_state, *scalars):
    """In-jit skip-batch: keep ``old_state`` wholesale when any of the
    ``scalars`` (loss, grad norm — already psum'd across the mesh) is
    NaN/Inf, else take ``new_state``.  Returns ``(guarded_state, bad)``
    with ``bad`` a traced bool scalar.

    Because the predicate is computed from all-reduced scalars, every rank
    selects the same branch — the dp-consistency requirement that keeps
    later collectives matched (the full-precision generalization of AMP's
    ``update_loss_scaling`` zero-grad skip)."""
    import jax
    import jax.numpy as jnp

    bad = jnp.zeros((), bool)
    for s in scalars:
        bad = bad | ~jnp.isfinite(jnp.asarray(s, jnp.float32))
    guarded = jax.tree_util.tree_map(
        lambda o, n: jnp.where(bad, o, n), old_state, new_state)
    return guarded, bad
