"""First-class rematerialization (activation checkpointing) policies.

Before this module the remat knob lived as ad-hoc strings scattered through
bench.py / GPTConfig / pipeline code ("remat=True", "remat_policy='dots'").
This is the single registry all three execution paths consult:

- :mod:`paddle_tpu.parallel.parallelize` (via :func:`models.gpt.run_blocks`)
  applies the policy per transformer block inside the GPipe/TP shard_map;
- :mod:`paddle_tpu.parallel.pipeline_program` applies it to each fluid
  pipeline *stage* body (stage activations are recomputed in the backward
  of the microbatch schedule instead of saved across all M+S-1 scan ticks);
- :mod:`paddle_tpu.parallel.grad_merge` accepts the same annotation for its
  per-microbatch fwd/bwd region so one knob drives every path (note: a fluid
  grad-merge program carries *explicit* gradient ops, so policies other than
  ``none`` only change behavior when the scanned region is differentiated
  again — the wrap is semantically a no-op otherwise).

Named policies (HBM high -> low, recompute FLOPs low -> high):

==================  ========================================================
``none``            no checkpointing: save every intermediate (max HBM,
                    zero recompute)
``save_only_flash`` save only tensors tagged with ``checkpoint_name`` —
                    the flash-attention outputs (models/gpt.py tags them
                    as ``"attn_out"``); everything else is recomputed
``dots``            ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``:
                    save matmul outputs, recompute elementwise — the
                    measured MFU winner on v5e (KERNEL_NOTES session 4)
``full``            recompute everything inside the wrapped region
                    (min HBM, ~1/3 extra step FLOPs)
==================  ========================================================

Old spellings stay valid as aliases: ``remat=False`` == ``"none"``,
``remat=True`` (no policy) == ``"full"``, and the jax-internal policy name
``dots_with_no_batch_dims_saveable`` maps to ``"dots"``.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple, Union

import jax

__all__ = [
    "POLICY_NAMES", "RematPolicy", "resolve", "policy_names",
    "checkpoint_name", "ATTN_CHECKPOINT_NAME",
]

POLICY_NAMES: Tuple[str, ...] = ("none", "full", "dots", "save_only_flash")

# name tagged onto attention outputs (flash or plain XLA path) so
# save_only_flash can pick them out of the block
ATTN_CHECKPOINT_NAME = "attn_out"

_ALIASES = {
    # legacy GPTConfig / bench.py spellings
    "off": "none",
    "false": "none",
    "true": "full",
    "everything": "full",
    # jax-internal policy names
    "dots_with_no_batch_dims_saveable": "dots",
    "dots_saveable": "dots",
    "save_only_these_names": "save_only_flash",
    "save_only_flash_attn": "save_only_flash",
}


def checkpoint_name(x, name: str = ATTN_CHECKPOINT_NAME):
    """Tag ``x`` for name-based save policies (thin jax.ad_checkpoint shim)."""
    from jax.ad_checkpoint import checkpoint_name as _cn

    return _cn(x, name)


@dataclasses.dataclass(frozen=True)
class RematPolicy:
    """One named policy; ``wrap(fn)`` applies it as a jax.checkpoint."""

    name: str

    @property
    def is_none(self) -> bool:
        return self.name == "none"

    def jax_policy(self) -> Optional[Callable]:
        """The jax.checkpoint ``policy=`` callable (None = save nothing,
        i.e. full recompute; meaningless for ``none``)."""
        if self.name == "dots":
            return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        if self.name == "save_only_flash":
            return jax.checkpoint_policies.save_only_these_names(
                ATTN_CHECKPOINT_NAME)
        return None  # "full" (and "none", which never reaches checkpoint)

    def wrap(self, fn: Callable, static_argnums: Tuple[int, ...] = ()) \
            -> Callable:
        """Return ``fn`` wrapped per this policy (identity for ``none``)."""
        if self.is_none:
            return fn
        policy = self.jax_policy()
        if policy is None:
            return jax.checkpoint(fn, static_argnums=static_argnums)
        return jax.checkpoint(fn, static_argnums=static_argnums,
                              policy=policy)


def policy_names() -> Tuple[str, ...]:
    return POLICY_NAMES


def resolve(policy: Union[str, RematPolicy, None] = None,
            remat: Optional[bool] = None) -> RematPolicy:
    """Resolve a policy name (or legacy ``remat=`` bool) to a RematPolicy.

    ``resolve("dots")`` — by name; ``resolve(None, remat=False)`` /
    ``resolve("full", remat=False)`` — the legacy bool wins when it says
    *off* (``remat=False`` always means ``none``, matching the old
    ``GPTConfig.remat`` contract); ``resolve(None, remat=True)`` defaults
    to ``full``.
    """
    if isinstance(policy, RematPolicy):
        name = policy.name
    elif policy is None:
        name = "full" if (remat is None or remat) else "none"
    else:
        name = str(policy).strip().lower()
        name = _ALIASES.get(name, name)
    if remat is False:
        name = "none"
    if name not in POLICY_NAMES:
        raise ValueError(
            f"unknown remat policy {policy!r}; valid names: "
            f"{', '.join(POLICY_NAMES)} (aliases: "
            f"{', '.join(sorted(_ALIASES))})")
    return RematPolicy(name)
