"""Communication-optimization layer for the parallel engine.

The baseline multi-chip gradient path (parallelize.py) reduces every
gradient leaf with one full-precision replicated ``psum`` and keeps a full
copy of the optimizer state on every dp rank — the unfused, unsharded,
unoverlapped baseline GSPMD (arXiv:2105.04663) and EQuARX
(arXiv:2506.17615) show leaves 1.2-2x on the table at dp>=4. This module
holds the three levers (see docs/comm_opt.md):

1. **Bucketed reduce-scatter** (:class:`BucketLayout`,
   :func:`reduce_scatter_flat`): gradients are flat-concatenated by dtype
   into size-capped buckets (default ~32 MiB), reduced with
   ``lax.psum_scatter`` so each dp rank owns 1/dp of every bucket, the
   optimizer runs on the shard (moments live sharded — optimizer-state HBM
   drops by dp x), and updated params return via ``all_gather``. Gradient
   reduction bytes on the wire halve vs all-reduce.
2. **Quantized collectives** (:func:`reduce_scatter_flat` /
   :func:`quantized_allreduce` with ``comm_dtype="bf16"|"int8"``):
   EQuARX-style chunk-scaled quantize -> exchange -> dequantize. The
   exchange is an ``all_to_all`` of the quantized payload so accumulation
   happens locally in f32 (scales stay f32); an optional error-feedback
   residual carries the per-rank quantization error into the next step.
3. **Wire-byte accounting** (:func:`record_collective`): every collective
   lowered through this module (and parallelize.py / ops/collective.py)
   increments ``paddle_collective_bytes_total{op,dtype}`` with ring-model
   per-rank bytes at TRACE time, so per-step bytes read straight off the
   metrics registry (tools/comm_bench.py -> COMM_BENCH.json).

Comm/compute overlap itself is scheduling: ``sysconfig.tpu_perf_flags()``
sets the XLA async-collective / latency-hiding-scheduler flags, the
pipeline tick is double-buffered (parallelize.py / pipeline_program.py),
and :func:`measure_overlap_fraction` reads the achieved overlap off a
profiler capture.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..observability import flight as _flight
from ..observability import metrics as _obs_metrics

__all__ = [
    "CommConfig", "BucketLayout", "Bucket", "build_bucket_layout",
    "axis_size", "record_collective", "wire_bytes", "quantize_chunked",
    "dequantize_chunked", "reduce_scatter_flat", "quantized_allreduce",
    "quantized_reduce_scatter_op", "measure_overlap_fraction",
]

# Per-rank bytes-on-wire, ring model, recorded at trace time (collectives
# run inside one fused XLA program; static shapes make the byte count a
# compile-time constant). tools/comm_bench.py reads the per-step delta.
_m_wire_bytes = _obs_metrics.default_registry().counter(
    "paddle_collective_bytes_total",
    "Per-rank wire bytes of collectives lowered into compiled programs "
    "(ring model, counted once per trace)", ("op", "dtype"))


def axis_size(name) -> int:
    """Static size of a named mesh axis inside shard_map (jax 0.4.x:
    ``jax.core.axis_frame`` returns the size directly; newer jax returns a
    frame object)."""
    from jax.core import axis_frame

    fr = axis_frame(name)
    return int(getattr(fr, "size", fr))


def _axes_size(axes) -> int:
    if isinstance(axes, (tuple, list)):
        n = 1
        for a in axes:
            n *= axis_size(a)
        return n
    return axis_size(axes)


def wire_bytes(op: str, payload_bytes: int, ranks: int) -> int:
    """Ring-model per-rank bytes for one collective of ``payload_bytes``
    global payload over ``ranks`` participants."""
    if ranks <= 1:
        return 0
    if op == "psum":                      # ring all-reduce: RS + AG legs
        return 2 * (ranks - 1) * payload_bytes // ranks
    if op in ("psum_scatter", "all_gather", "all_to_all"):
        return (ranks - 1) * payload_bytes // ranks
    if op == "ppermute":
        return payload_bytes
    raise ValueError(f"unknown collective op {op!r}")


def record_collective(op: str, dtype, payload_bytes: int, ranks: int,
                      site: Optional[str] = None) -> int:
    """Count one lowered collective into the wire-bytes counter; returns
    the per-rank ring bytes recorded.

    This is THE chokepoint every collective call site flows through
    (ops/collective.py lowerings, parallelize.py psum/ppermute sites,
    and this module's own bucketed/quantized wrappers), so it also
    stamps the flight recorder's lowered-collective sequence stream
    (ISSUE 19): one monotone (lseq, op, dtype, bytes, ranks, site)
    event per collective baked into a traced program.  Ranks trace
    identical programs in identical order, so the stream is the
    cross-rank fingerprint tools/flight_assemble.py checks for
    divergence.  ``site`` labels the calling wrapper (defaults to
    ``op``); tools/paddle_lint.py statically verifies every wrapper
    reaches this stamp."""
    b = wire_bytes(op, int(payload_bytes), int(ranks))
    if b:
        _m_wire_bytes.labels(op, str(jnp.dtype(dtype).name)).inc(b)
        _flight.stamp_collective(op, jnp.dtype(dtype).name,
                                 payload_bytes, ranks, site=site)
    return b


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------

_COMM_DTYPES = {
    None: None, "": None, "f32": None, "fp32": None, "float32": None,
    "bf16": "bf16", "bfloat16": "bf16",
    "int8": "int8",
}


def normalize_comm_dtype(name) -> Optional[str]:
    if name not in _COMM_DTYPES:
        raise ValueError(
            f"comm dtype {name!r}: expected one of f32/bf16/int8")
    return _COMM_DTYPES[name]


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """The communication levers of one train step (docs/comm_opt.md)."""
    grad_reduce: str = "psum"            # "psum" | "reduce_scatter"
    comm_dtype: Optional[str] = None     # None(f32) | "bf16" | "int8"
    bucket_mb: float = 32.0              # per-bucket cap, MiB of grad bytes
    error_feedback: bool = False         # carry quantization residual
    quant_chunk: int = 256               # elements per int8 scale chunk
    pipeline_double_buffer: bool = True  # overlap ppermute with next tick

    def __post_init__(self):
        if self.grad_reduce not in ("psum", "reduce_scatter"):
            raise ValueError(
                f"grad_reduce {self.grad_reduce!r}: "
                "expected 'psum' or 'reduce_scatter'")
        object.__setattr__(
            self, "comm_dtype", normalize_comm_dtype(self.comm_dtype))
        if self.error_feedback and self.comm_dtype is None:
            raise ValueError("error_feedback requires a quantized comm_dtype")


# ---------------------------------------------------------------------------
# Bucket layout: flat concat by dtype, size-capped, padded for the mesh
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Bucket:
    """One flat comm bucket: contiguous concat of whole leaves (by
    tree-flatten order), zero-padded to ``size`` (a multiple of the
    reduce group size, and of the quant chunk when quantizing)."""
    dtype: str                       # numpy dtype name of the leaves
    entries: Tuple[Tuple[int, Tuple[int, ...], int], ...]  # (leaf_idx, shape, numel)
    size: int                        # padded flat length
    pad: int

    @property
    def nbytes(self) -> int:
        return self.size * np.dtype(self.dtype).itemsize


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    buckets: Tuple[Bucket, ...]
    ranks: int                       # reduce-scatter group size
    total_len: int                   # sum of bucket sizes (padded)

    @property
    def shard_len(self) -> int:
        return self.total_len // self.ranks


def build_bucket_layout(shapes_dtypes: Sequence[Tuple[Tuple[int, ...], Any]],
                        ranks: int, cap_bytes: int,
                        pad_multiple: int = 1) -> BucketLayout:
    """Greedy size-capped bucketing of leaves (local shard shapes), grouped
    by dtype. A leaf larger than the cap gets its own bucket — leaves are
    never split, so flatten/unflatten stay cheap reshapes."""
    ranks = max(1, int(ranks))
    align = ranks * max(1, int(pad_multiple))
    by_dtype: Dict[str, List[Tuple[int, Tuple[int, ...], int]]] = {}
    for idx, (shape, dt) in enumerate(shapes_dtypes):
        name = np.dtype(dt).name
        numel = int(np.prod(shape)) if shape else 1
        by_dtype.setdefault(name, []).append((idx, tuple(shape), numel))

    buckets: List[Bucket] = []
    for dt_name in sorted(by_dtype):
        cur: List[Tuple[int, Tuple[int, ...], int]] = []
        cur_bytes = 0
        itemsize = np.dtype(dt_name).itemsize

        def flush():
            nonlocal cur, cur_bytes
            if not cur:
                return
            n = sum(e[2] for e in cur)
            size = -(-n // align) * align
            buckets.append(Bucket(dtype=dt_name, entries=tuple(cur),
                                  size=size, pad=size - n))
            cur, cur_bytes = [], 0

        for entry in by_dtype[dt_name]:
            if cur and cur_bytes + entry[2] * itemsize > cap_bytes:
                flush()
            cur.append(entry)
            cur_bytes += entry[2] * itemsize
        flush()
    total = sum(b.size for b in buckets)
    return BucketLayout(buckets=tuple(buckets), ranks=ranks, total_len=total)


def flatten_bucket(leaves: Sequence[Any], bucket: Bucket,
                   dtype=jnp.float32):
    """Concat the bucket's leaves (flattened, cast to ``dtype``) + pad."""
    parts = [jnp.asarray(leaves[i]).astype(dtype).reshape(-1)
             for i, _, _ in bucket.entries]
    vec = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    if bucket.pad:
        vec = jnp.concatenate([vec, jnp.zeros((bucket.pad,), dtype)])
    return vec


def unflatten_bucket(vec, bucket: Bucket) -> Dict[int, Any]:
    """Inverse of :func:`flatten_bucket`: {leaf_idx: array of leaf shape}
    (still in ``vec``'s dtype — caller casts)."""
    out: Dict[int, Any] = {}
    off = 0
    for idx, shape, numel in bucket.entries:
        out[idx] = vec[off:off + numel].reshape(shape)
        off += numel
    return out


def bucket_wd_mask(bucket: Bucket) -> np.ndarray:
    """Flat weight-decay mask for one bucket (1.0 on >=2-D leaves, the
    standard no-decay-on-bias/layernorm rule — parallelize._adamw_update)."""
    parts = [np.full((numel,), 1.0 if len(shape) >= 2 else 0.0, np.float32)
             for _, shape, numel in bucket.entries]
    parts.append(np.zeros((bucket.pad,), np.float32))
    return np.concatenate(parts)


# ---------------------------------------------------------------------------
# Chunk-scaled quantization (EQuARX-style)
# ---------------------------------------------------------------------------

def quantize_chunked(x, comm_dtype: Optional[str], chunk: int):
    """f32 [n] -> (payload, scales|None). bf16 is a plain cast (no scales);
    int8 is chunk-scaled symmetric: per ``chunk`` elements one f32 scale =
    absmax/127. ``n`` must be a chunk multiple for int8."""
    if comm_dtype is None:
        return x, None
    if comm_dtype == "bf16":
        return x.astype(jnp.bfloat16), None
    if comm_dtype != "int8":
        raise ValueError(f"bad comm dtype {comm_dtype!r}")
    xr = x.reshape(-1, chunk)
    scale = jnp.max(jnp.abs(xr), axis=1, keepdims=True) / 127.0
    scale = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(xr / scale), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale[:, 0]


def dequantize_chunked(payload, scales, comm_dtype: Optional[str],
                       chunk: int):
    """Inverse of :func:`quantize_chunked`, always f32 out."""
    if comm_dtype is None:
        return payload.astype(jnp.float32)
    if comm_dtype == "bf16":
        return payload.astype(jnp.float32)
    q = payload.reshape(-1, chunk).astype(jnp.float32)
    return (q * scales[:, None]).reshape(-1)


# ---------------------------------------------------------------------------
# The collectives
# ---------------------------------------------------------------------------

def reduce_scatter_flat(vec, axis, ccfg: CommConfig, residual=None,
                        record: bool = True):
    """Reduce ``vec`` (f32, length divisible by the axis size — and by
    size*quant_chunk for int8) over mesh ``axis``; each rank keeps its
    1/ranks shard, reduced in f32.

    f32 comm lowers to a native ``lax.psum_scatter`` (bit-identical to
    ``psum`` + slice — tested). Quantized comm quantizes the local vector
    chunk-scaled, exchanges shards via ``all_to_all`` (wire payload in
    comm_dtype, the reduce-scatter-optimal (ranks-1)/ranks bytes), and
    accumulates the dequantized shards locally in f32.

    Returns ``(shard, new_residual)`` — ``new_residual`` is the local
    quantization error when ``ccfg.error_feedback`` (caller adds the
    incoming ``residual`` to ``vec`` BEFORE calling; it is accepted here so
    the two stay paired in the train step), else None.
    """
    ranks = axis_size(axis)
    n = vec.shape[0]
    if ccfg.comm_dtype is None:
        if record:
            record_collective("psum_scatter", jnp.float32, n * 4, ranks,
                              site="reduce_scatter_flat")
        if ranks == 1:
            return vec, None
        return lax.psum_scatter(vec, axis, scatter_dimension=0,
                                tiled=True), None

    payload, scales = quantize_chunked(vec, ccfg.comm_dtype, ccfg.quant_chunk)
    new_residual = None
    if ccfg.error_feedback:
        new_residual = vec - dequantize_chunked(
            payload, scales, ccfg.comm_dtype, ccfg.quant_chunk)
    if ranks == 1:
        shard = dequantize_chunked(payload, scales, ccfg.comm_dtype,
                                   ccfg.quant_chunk)
        return shard, new_residual

    if record:
        record_collective(
            "all_to_all", payload.dtype, n * payload.dtype.itemsize, ranks,
            site="reduce_scatter_flat")
    rows = lax.all_to_all(payload.reshape(ranks, n // ranks), axis,
                          split_axis=0, concat_axis=0)
    if scales is not None:
        if record:
            record_collective("all_to_all", jnp.float32,
                              scales.size * 4, ranks,
                              site="reduce_scatter_flat")
        srows = lax.all_to_all(scales.reshape(ranks, -1), axis,
                               split_axis=0, concat_axis=0)
        deq = jax.vmap(lambda p, s: dequantize_chunked(
            p, s, ccfg.comm_dtype, ccfg.quant_chunk))(rows, srows)
    else:
        deq = rows.astype(jnp.float32)
    return jnp.sum(deq, axis=0), new_residual


def all_gather_flat(shard, axis, record: bool = True):
    """Gather per-rank shards back into the full flat vector."""
    ranks = axis_size(axis)
    if ranks == 1:
        return shard
    if record:
        record_collective("all_gather", shard.dtype,
                          shard.size * shard.dtype.itemsize * ranks, ranks,
                          site="all_gather_flat")
    return lax.all_gather(shard, axis, tiled=True)


def _pad_to(vec, multiple: int):
    pad = (-vec.shape[0]) % multiple
    if pad:
        vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
    return vec, pad


def quantized_allreduce(x, axis, comm_dtype, quant_chunk: int = 256,
                        mean: bool = False, record: bool = True):
    """All-reduce with wire payload in ``comm_dtype`` and f32 accumulation:
    quantized reduce-scatter leg, requantize the reduced shard, quantized
    all-gather leg (the EQuARX RS+AG structure). Arbitrary shapes; returns
    ``x``'s dtype. Used by the fluid ``c_allreduce_*`` lowerings and the
    GradientMergeOptimizer tail (FLAGS_collective_comm_dtype)."""
    cd = normalize_comm_dtype(comm_dtype)
    ranks = axis_size(axis)
    if cd is None or ranks == 1:
        if record:
            record_collective("psum", x.dtype, x.size * x.dtype.itemsize,
                              ranks, site="quantized_allreduce")
        out = lax.psum(x, axis)
        return out / ranks if mean else out
    ccfg = CommConfig(comm_dtype=cd, quant_chunk=quant_chunk)
    orig_dtype, orig_shape, n = x.dtype, x.shape, x.size
    flat = x.astype(jnp.float32).reshape(-1)
    flat, _ = _pad_to(flat, ranks * quant_chunk)
    shard, _ = reduce_scatter_flat(flat, axis, ccfg, record=record)
    if mean:
        shard = shard / ranks
    # requantize the reduced shard for the gather leg (fresh scales: the
    # sum's range grew by up to ranks x)
    shard, _ = _pad_to(shard, quant_chunk)
    payload, scales = quantize_chunked(shard, cd, quant_chunk)
    full_q = all_gather_flat(payload, axis, record=record)
    if scales is not None:
        full_s = all_gather_flat(scales, axis, record=record)
    else:
        full_s = None
    full = dequantize_chunked(full_q, full_s, cd, quant_chunk)
    return full[:n].reshape(orig_shape).astype(orig_dtype)


def quantized_reduce_scatter_op(x, axis, comm_dtype, quant_chunk: int = 256,
                                record: bool = True):
    """c_reducescatter semantics ([ranks*k, ...] -> [k, ...] reduced shard)
    with a quantized wire payload and f32 accumulation."""
    cd = normalize_comm_dtype(comm_dtype)
    ranks = axis_size(axis)
    if cd is None or ranks == 1:
        if record:
            record_collective("psum_scatter", x.dtype,
                              x.size * x.dtype.itemsize, ranks,
                              site="quantized_reduce_scatter")
        return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    orig_dtype = x.dtype
    shard_shape = (x.shape[0] // ranks,) + tuple(x.shape[1:])
    row = int(np.prod(shard_shape)) if shard_shape else 1
    # chunk-align every rank's row so shard boundaries stay chunk boundaries
    row_pad = (-row) % quant_chunk
    flat = x.astype(jnp.float32).reshape(ranks, row)
    if row_pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((ranks, row_pad), jnp.float32)], axis=1)
    ccfg = CommConfig(comm_dtype=cd, quant_chunk=quant_chunk)
    shard, _ = reduce_scatter_flat(flat.reshape(-1), axis, ccfg,
                                   record=record)
    return shard[:row].reshape(shard_shape).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Overlap measurement (profiler capture -> achieved comm/compute overlap)
# ---------------------------------------------------------------------------

_COLLECTIVE_HLO_MARKERS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "all_reduce", "all_gather", "reduce_scatter",
    "all_to_all", "collective_permute",
)


def _merge_intervals(iv: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    iv = sorted(iv)
    out: List[Tuple[float, float]] = []
    for s, e in iv:
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _intersect_total(a: List[Tuple[float, float]],
                     b: List[Tuple[float, float]]) -> float:
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def measure_overlap_fraction(trace_dir: str) -> Optional[Dict[str, float]]:
    """Read a profiler xplane capture and measure how much collective span
    time overlaps compute span time on the device execution lines.

    Returns {overlap_fraction, collective_ms, exposed_ms, compute_ms,
    source} or None when no capture / no collective events are present.
    ``source`` is "device_plane" (real accelerator timeline) or
    "cpu_thread_emulation" (host-thread lines: the virtual devices share
    one pool, so the fraction measures emulation concurrency, not ICI
    overlap — COMM_BENCH labels it so).
    """
    from ..utils.device_trace import _latest_xplane, _line_role, \
        profile_data_cls

    path = _latest_xplane(trace_dir)
    if path is None:
        return None
    pd = profile_data_cls().from_file(path)
    coll: List[Tuple[float, float]] = []
    comp: List[Tuple[float, float]] = []
    saw_device_plane = False
    for plane in pd.planes:
        device_plane = plane.name.startswith("/device:")
        for line in plane.lines:
            # device planes, the CPU runtime line, and the per-thread
            # Eigen compute-pool lines (where the CPU client's hlo events
            # actually land — intervals across threads union correctly)
            lname_str = str(line.name)
            if not (device_plane or "CpuClient" in lname_str
                    or "XLAEigen" in lname_str):
                continue
            if device_plane and _line_role(
                    str(line.name),
                    (str(ev.name) for ev in line.events)) in (
                        "steps", "modules"):
                continue
            for ev in line.events:
                try:
                    stats = dict(ev.stats)
                except Exception:
                    stats = {}
                name = str(stats.get("hlo_op") or ev.name)
                dur = float(getattr(ev, "duration_ns", 0.0) or 0.0)
                if dur <= 0:
                    continue
                start = float(getattr(ev, "start_ns", 0.0) or 0.0)
                lname = name.lower()
                if any(m in lname for m in _COLLECTIVE_HLO_MARKERS):
                    coll.append((start, start + dur))
                    saw_device_plane = saw_device_plane or device_plane
                else:
                    comp.append((start, start + dur))
    if not coll:
        return None
    coll_m = _merge_intervals(coll)
    comp_m = _merge_intervals(comp)
    coll_total = sum(e - s for s, e in coll_m)
    overlapped = _intersect_total(coll_m, comp_m)
    return {
        "overlap_fraction": overlapped / coll_total if coll_total else 0.0,
        "collective_ms": coll_total / 1e6,
        "exposed_ms": (coll_total - overlapped) / 1e6,
        "compute_ms": sum(e - s for s, e in comp_m) / 1e6,
        # off-TPU the 8 "devices" are host threads sharing one pool, so
        # cross-thread overlap is emulation concurrency, not ICI overlap —
        # labeled so COMM_BENCH readers don't mistake it for the real thing
        "source": ("device_plane" if saw_device_plane
                   else "cpu_thread_emulation"),
    }
