"""4D-parallel training engine: dp x pp x tp (+sequence parallel) on one mesh.

The reference's parallelism is NCCL data-parallel (ParallelExecutor SSA graph,
framework/parallel_executor.cc) plus a threaded pipeline trainer
(framework/pipeline_trainer.cc + section_worker.cc: stages pass Scopes through
blocking queues) — there is no tensor or sequence parallelism (SURVEY.md §2.3).
This module is the TPU-native superset, one compiled XLA program instead of
thread queues:

- **dp**: batch sharded over the ``dp`` mesh axis; gradient all-reduce is a
  single psum (replaces AllReduceOpHandle / FusedAllReduceOpHandle —
  framework/details/all_reduce_op_handle.cc).
- **pp**: GPipe. Block params are stacked [num_layers, ...] and sharded over
  ``pp`` on the layer axis; the microbatch schedule is a ``lax.scan`` over
  M + S - 1 ticks with a ``ppermute`` shifting activations stage->stage+1
  over ICI each tick (replaces SectionWorker scope queues).
- **tp + sp**: Megatron tensor parallel over ``tp`` (QKV/fc column-split,
  proj/out row-split) with *sequence parallelism*: activations between blocks
  stay sharded on the sequence dim over ``tp``, so the row-parallel psum
  becomes a reduce_scatter and layernorms/dropout run on 1/tp of the tokens.

Gradient correctness uses one uniform rule: inside shard_map each rank
differentiates the *global* (fully psum-ed) loss w.r.t. its local param
shards, then each leaf's grad is psum-ed over every mesh axis **not**
appearing in that leaf's PartitionSpec. This is valid because every
replicated-leaf use happens on sequence-sharded activations (partial sums
over tp), tick-masked stages contribute exact zeros (over pp), and the loss
is batch-partial over dp.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from . import comm_opt
from . import health as _health
from . import mesh as mesh_mod
from ..models import gpt as gpt_mod
from ..models.gpt import GPTConfig
from ..observability import metrics as _obs_metrics
from .comm_opt import CommConfig

# Collective self-reporting. Collectives execute inside ONE fused XLA
# program, so their wall time is only observable on the device timeline:
# every collective here is wrapped in a jax.named_scope whose name lands in
# each HLO instruction's metadata, and the profiler's merged trace
# (observability/trace_merge.py) then shows `collective/...` spans on the
# device track. The counter below registers at TRACE time (once per
# compile), giving an always-live count of collectives lowered per step.
_m_collectives = _obs_metrics.default_registry().counter(
    "paddle_collective_lowered_total",
    "Collective ops lowered into compiled train steps", ("kind",))


def _named_collective(kind: str):
    """named_scope + lowering counter for one collective call site."""
    _m_collectives.labels(kind).inc()
    return jax.named_scope(f"collective/{kind}")


def shard_map_compat(f, mesh, in_specs, out_specs):
    try:
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_vma=False)
    except TypeError:
        return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                          check_rep=False)


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    dp: int = 1
    pp: int = 1
    tp: int = 1
    microbatches: int = 1          # GPipe microbatches (>= pp for low bubble)
    axis_names: Tuple[str, str, str] = ("dp", "pp", "tp")

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.tp


def build_mesh(pcfg: ParallelConfig, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = pcfg.n_devices
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    return mesh_mod.build_mesh(
        list(zip(pcfg.axis_names, (pcfg.dp, pcfg.pp, pcfg.tp))), devices[:n])


def _axes_not_in_spec(spec: P, axis_names) -> Tuple[str, ...]:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return tuple(a for a in axis_names if a not in used)


def psum_grads_by_spec(grads, specs, axis_names, skip_axes=(),
                       comm_dtype=None, quant_chunk=256):
    """psum each grad leaf over the mesh axes its param is replicated on.

    ``skip_axes`` leaves named axes un-reduced (the reduce-scatter path
    handles dp itself, bucketed). ``comm_dtype`` routes the reduction
    through :func:`comm_opt.quantized_allreduce` (chunk-scaled wire payload,
    f32 accumulation) — applied per axis, a hierarchical all-reduce.
    """
    def one(g, s):
        axes = tuple(a for a in _axes_not_in_spec(s, axis_names)
                     if a not in skip_axes)
        if not axes:
            return g
        with _named_collective("psum_grad"):
            if comm_dtype is not None:
                for a in axes:
                    g = comm_opt.quantized_allreduce(
                        g, a, comm_dtype, quant_chunk=quant_chunk)
                return g
            comm_opt.record_collective(
                "psum", g.dtype, g.size * g.dtype.itemsize,
                comm_opt._axes_size(axes), site="psum_grads_by_spec")
            return jax.lax.psum(g, axes)

    return jax.tree_util.tree_map(one, grads, specs,
                                  is_leaf=lambda x: isinstance(x, P))


def shard_params(params, specs, mesh):
    """Place a param pytree on the mesh per its specs."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs)


# ---------------------------------------------------------------------------
# The per-rank loss: full GPipe/TP/SP forward + CE, returns the GLOBAL loss.
# ---------------------------------------------------------------------------

def _pipeline_loss(params, tokens, labels, cfg: GPTConfig,
                   pcfg: ParallelConfig, double_buffer: bool = False):
    """Runs inside shard_map. Local shapes:
    tokens/labels [M, mb_local, T]; params['blocks'] leaves [L/pp, ...] with
    tp-local head/ffn dims; replicated leaves full-size.
    Returns the global mean token loss (replicated scalar).

    ``double_buffer=True`` moves the stage-boundary ppermute from the tail
    of each tick to the head of the NEXT tick (the carry holds the
    un-permuted activation): microbatch t's activation is in flight while
    tick t+1 computes its embedding, so XLA's async collective-permute +
    latency-hiding scheduler (sysconfig.tpu_perf_flags) can overlap the
    send with compute. Tick values are identical to the serial schedule
    (the permute commutes with the carry), so the loss trajectory matches
    bit-for-bit — tested in tests/test_comm_opt.py.
    """
    dp_ax, pp_ax, tp_ax = pcfg.axis_names
    S, M = pcfg.pp, pcfg.microbatches
    tp = pcfg.tp
    stage = jax.lax.axis_index(pp_ax)
    tp_idx = jax.lax.axis_index(tp_ax)

    M_, mb, T = tokens.shape
    Ts = T // tp
    blocks = params["blocks"]

    def seq_chunk(x2d):  # [mb, T] -> tp-local [mb, Ts]
        return jax.lax.dynamic_slice_in_dim(x2d, tp_idx * Ts, Ts, axis=1)

    def stage_fn(x):
        return gpt_mod.run_blocks(blocks, x, cfg,
                                  tp_axis=tp_ax if tp > 1 else None)

    def mb_loss(x, lbl):  # x [mb, Ts, D] seq-sharded; lbl [mb, T]
        # chunked CE: full [mb*Ts, V] logits never materialize (see
        # gpt.ce_from_hidden) — the classic big-vocab OOM at wide batch
        return gpt_mod.ce_from_hidden(params, x, seq_chunk(lbl), cfg)

    perm = [(i, (i + 1) % S) for i in range(S)]
    total_tokens = M * mb * T  # per-dp-rank token count (dp summed via psum)

    def _permute_act(x):
        with _named_collective("ppermute_activation"):
            comm_opt.record_collective(
                "ppermute", x.dtype, x.size * x.dtype.itemsize, S,
                site="ppermute_activation")
            return jax.lax.ppermute(x, pp_ax, perm)

    def tick(carry, t):
        state, loss_acc = carry
        if double_buffer and S > 1:
            # the carry holds LAST tick's un-permuted output: start its
            # ppermute now so the send is in flight while this tick embeds
            state = _permute_act(state)
        mb_in = jnp.clip(t, 0, M - 1)
        tok = jax.lax.dynamic_index_in_dim(tokens, mb_in, axis=0,
                                           keepdims=False)
        # stage 0 consumes the embedded microbatch; others consume the
        # ppermuted activation from the previous stage
        x_emb = gpt_mod.embed(params, seq_chunk(tok), cfg,
                              pos_offset=tp_idx * Ts)
        x_in = jnp.where(stage == 0, x_emb, state)
        out = stage_fn(x_in)
        # last stage emits a finished microbatch at ticks S-1 .. S-1+M-1
        out_idx = t - (S - 1)
        valid = (stage == S - 1) & (out_idx >= 0) & (out_idx < M)
        lbl = jax.lax.dynamic_index_in_dim(
            labels, jnp.clip(out_idx, 0, M - 1), axis=0, keepdims=False)
        # lax.cond: the vocab projection + CE only runs on the last stage's
        # M valid ticks instead of every tick on every rank (it costs more
        # than a stage's transformer blocks at GPT_SMALL scale)
        l = jax.lax.cond(valid, lambda: mb_loss(out, lbl),
                         lambda: jnp.float32(0.0))
        loss_acc = loss_acc + l
        if double_buffer or S == 1:
            state = out
        else:
            state = _permute_act(out)
        return (state, loss_acc), None

    D = cfg.d_model
    state0 = jnp.zeros((mb, Ts, D), cfg.dtype)
    n_ticks = M + S - 1
    (state, loss_sum), _ = jax.lax.scan(
        tick, (state0, jnp.float32(0.0)), jnp.arange(n_ticks))

    # Return the rank-LOCAL partial loss normalized by the GLOBAL token count.
    # Deliberately no psum here: this function is differentiated per-rank
    # under shard_map, and with replication checking off a psum would
    # transpose to another psum, scaling every grad by the rank count.
    # Summing the per-rank scalars happens (a) implicitly for grads — SPMD AD
    # seeds cotangent 1 on every rank, so collective transposes yield
    # d(sum_r local_r)/d(local shard) — and (b) explicitly for the reported
    # loss value, via the psum in grad_fn OUTSIDE value_and_grad.
    denom = total_tokens * pcfg.dp
    return loss_sum / denom


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def init_adamw_state(params, moment_dtype=None, fused=False):
    """moment_dtype=jnp.bfloat16 halves the 2x-params-f32 of Adam state —
    at GPT-wide scale that is ~4 GB of a 16 GB HBM, the difference between
    remat and no-remat fitting (update math still runs in f32; bf16's 8-bit
    mantissa on m/v costs <0.1% step-loss drift, checked in
    tests/test_gpt_parallel.py::test_bf16_moments_track_f32).

    ``fused=True`` stores m/v as ONE flat [total_numel] megabuffer each
    (the _adamw_update_fused layout): two donated buffers for the whole
    optimizer state instead of two per leaf."""
    if fused:
        total = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
        dt = moment_dtype or jnp.float32
        return {"m": jnp.zeros((total,), dt), "v": jnp.zeros((total,), dt),
                "step": jnp.zeros((), jnp.int32)}

    def zeros(p):
        return jax.tree_util.tree_map(
            lambda x: jnp.zeros_like(x, dtype=moment_dtype or x.dtype), p)
    return {"m": zeros(params), "v": zeros(params),
            "step": jnp.zeros((), jnp.int32)}


def _clip_scale(gnorm, grad_clip):
    """grad_clip=None disables clipping with a bit-exact scale of 1.0 (the
    reduce-scatter parity tests rely on x*1.0 == x)."""
    if grad_clip is None:
        return jnp.float32(1.0)
    return jnp.minimum(1.0, grad_clip / (gnorm + 1e-6))


def _adamw_update(params, grads, opt, lr, b1=0.9, b2=0.95, eps=1e-8,
                  weight_decay=0.1, grad_clip=1.0):
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in jax.tree_util.tree_leaves(grads)))
    scale = _clip_scale(gnorm, grad_clip)
    step = opt["step"] + 1
    c1 = 1 - b1 ** step.astype(jnp.float32)
    c2 = 1 - b2 ** step.astype(jnp.float32)

    def upd(p, g, m, v):
        g = g.astype(jnp.float32) * scale
        mf = b1 * m.astype(jnp.float32) + (1 - b1) * g
        vf = b2 * v.astype(jnp.float32) + (1 - b2) * g * g
        u = (mf / c1) / (jnp.sqrt(vf / c2) + eps)
        # standard GPT/Megatron recipe: no decay on 1-D params (biases,
        # layernorm scales) — only matmul/embedding matrices
        wd = weight_decay if p.ndim >= 2 else 0.0
        # moments round-trip through their storage dtype (possibly bf16 —
        # init_adamw_state moment_dtype); math stays f32
        return p - lr * (u + wd * p), mf.astype(m.dtype), vf.astype(v.dtype)

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(opt["m"])
    flat_v = treedef.flatten_up_to(opt["v"])
    out = [upd(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_m = treedef.unflatten([o[1] for o in out])
    new_v = treedef.unflatten([o[2] for o in out])
    return new_p, {"m": new_m, "v": new_v, "step": step}, gnorm


def _adamw_update_fused(params, grads, opt, lr, b1=0.9, b2=0.95, eps=1e-8,
                        weight_decay=0.1, grad_clip=1.0, use_pallas=False):
    """Flat-buffer AdamW sweep: every leaf's grad/param is concatenated into
    one f32 megabuffer, the moments live flat (init_adamw_state fused=True),
    and the whole update is ONE vectorized expression — the per-param
    optimizer stream (hundreds of tiny fusions + donations at GPT depth)
    collapses to a handful of full-bandwidth passes over contiguous HBM.
    Same math as _adamw_update leaf-by-leaf; parity tested in
    tests/test_memory_levers.py. Single-device / replicated-param layouts
    only (make_train_step guards).

    ``use_pallas`` routes the elementwise sweep through ONE Pallas
    megakernel launch (ops/pallas_kernels.megakernel_adamw_flat) instead
    of XLA's residual elementwise-fusion stream — the grad-norm reduction
    and clip scale stay outside and ride in as scalars, so the in-kernel
    expression order matches this function bit-for-bit at f32 moments."""
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    sizes = [int(p.size) for p in flat_p]
    gf = jnp.concatenate([g.astype(jnp.float32).reshape(-1) for g in flat_g])
    pf = jnp.concatenate([p.astype(jnp.float32).reshape(-1) for p in flat_p])
    # no decay on 1-D leaves (biases, layernorm scales) — same rule as the
    # per-leaf path, precomputed as a flat constant mask
    wd_mask = jnp.concatenate(
        [jnp.full((n,), 1.0 if p.ndim >= 2 else 0.0, jnp.float32)
         for p, n in zip(flat_p, sizes)])

    gnorm = jnp.sqrt(jnp.sum(jnp.square(gf)))
    scale = _clip_scale(gnorm, grad_clip)
    step = opt["step"] + 1
    c1 = 1 - b1 ** step.astype(jnp.float32)
    c2 = 1 - b2 ** step.astype(jnp.float32)
    if use_pallas:
        from ..ops.pallas_kernels import megakernel_adamw_flat

        new_flat, m_out, v_out = megakernel_adamw_flat(
            pf, gf, opt["m"], opt["v"], wd_mask, lr, scale, c1, c2,
            b1=b1, b2=b2, eps=eps, weight_decay=weight_decay)
    else:
        gf = gf * scale
        mf = b1 * opt["m"].astype(jnp.float32) + (1 - b1) * gf
        vf = b2 * opt["v"].astype(jnp.float32) + (1 - b2) * gf * gf
        u = (mf / c1) / (jnp.sqrt(vf / c2) + eps)
        new_flat = pf - lr * (u + weight_decay * wd_mask * pf)
        m_out = mf.astype(opt["m"].dtype)
        v_out = vf.astype(opt["v"].dtype)

    new_leaves, off = [], 0
    for p, n in zip(flat_p, sizes):
        new_leaves.append(new_flat[off:off + n].reshape(p.shape)
                          .astype(p.dtype))
        off += n
    new_p = treedef.unflatten(new_leaves)
    return new_p, {"m": m_out, "v": v_out, "step": step}, gnorm


def _rs_param_layout(cfg: GPTConfig, pcfg: ParallelConfig,
                     ccfg: CommConfig):
    """Bucket layout over the rank-LOCAL param shard shapes (tree-flatten
    order) for the reduce-scatter path. Deterministic in (cfg, pcfg, ccfg)
    so ``init_sharded`` and ``make_train_step`` agree."""
    dp_ax, pp_ax, tp_ax = pcfg.axis_names
    specs = gpt_mod.param_specs(cfg, pp=pp_ax, tp=tp_ax)
    sizes = dict(zip(pcfg.axis_names, (pcfg.dp, pcfg.pp, pcfg.tp)))
    avals = jax.eval_shape(partial(gpt_mod.init_params, cfg=cfg),
                           jax.ShapeDtypeStruct((2,), jnp.uint32))
    flat_avals, treedef = jax.tree_util.tree_flatten(avals)
    flat_specs = treedef.flatten_up_to(specs)

    def local_shape(shape, spec):
        out = list(shape)
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            div = int(np.prod([sizes[a] for a in axes]))
            if out[d] % div:
                raise ValueError(
                    f"param dim {shape}[{d}] not divisible by mesh {axes}")
            out[d] //= div
        return tuple(out)

    for s in flat_specs:
        if dp_ax in _spec_axes(s):
            raise NotImplementedError(
                "reduce_scatter grad path expects dp-replicated params")
    shapes_dtypes = [(local_shape(a.shape, s), a.dtype)
                     for a, s in zip(flat_avals, flat_specs)]
    pad_multiple = ccfg.quant_chunk if ccfg.comm_dtype == "int8" else 1
    layout = comm_opt.build_bucket_layout(
        shapes_dtypes, ranks=pcfg.dp,
        cap_bytes=int(ccfg.bucket_mb * (1 << 20)),
        pad_multiple=pad_multiple)
    return layout, specs, treedef


def rs_param_layout(cfg: GPTConfig, pcfg: ParallelConfig,
                    comm: Optional[CommConfig] = None,
                    **comm_kw) -> Tuple[Any, int]:
    """Public accessor for the reduce-scatter bucket layout: returns
    ``(BucketLayout, repl)`` where ``repl`` (= pp*tp) is how many times each
    dp shard repeats in the addressable flat moment buffer
    (``init_sharded`` shards it over EVERY mesh axis).  Checkpoint
    manifests record exactly this pair so a restore onto a different dp
    can reshard the moments bit-exactly
    (parallel/checkpoint.py:reshard_flat, docs/elastic.md)."""
    ccfg = comm if comm is not None else CommConfig(
        grad_reduce="reduce_scatter", **comm_kw)
    layout, _, _ = _rs_param_layout(cfg, pcfg, ccfg)
    return layout, pcfg.pp * pcfg.tp


def _spec_axes(spec: P):
    out = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.update(entry)
        else:
            out.add(entry)
    return out


def _make_rs_step(cfg: GPTConfig, pcfg: ParallelConfig, mesh: Mesh,
                  ccfg: CommConfig, lr, weight_decay, grad_clip,
                  specs, param_sh, data_spec, data_sh, double_buffer,
                  skip_nonfinite: bool = False):
    """The reduce-scatter train step: ONE shard_map holding grad, bucketed
    psum_scatter, the sharded flat AdamW sweep, and the param all_gather.

    Per dp rank: grads are flat-concatenated into the bucket layout
    (comm_opt.build_bucket_layout over the rank-local leaf shards), each
    bucket is reduced with ``lax.psum_scatter`` (or the quantized
    all_to_all exchange) so the rank owns 1/dp of it, AdamW runs on the
    shard against dp-sharded flat moments, and the updated param shards
    are ``all_gather``-ed back into replicated leaves. Every bucket's
    collectives sit in ``collective/rs_bucket<i>`` / ``collective/
    ag_bucket<i>`` named scopes so the merged trace measures overlap.
    """
    dp_ax = pcfg.axis_names[0]
    dp = pcfg.dp
    layout, _, treedef = _rs_param_layout(cfg, pcfg, ccfg)
    buckets = layout.buckets
    # static per-bucket flat constants: weight-decay mask (no decay on
    # 1-D leaves) and the grad-norm replication weight (a leaf replicated
    # over pp/tp appears on every such rank; weight 1/replication so the
    # all-axes psum counts each unique element once)
    sizes = dict(zip(pcfg.axis_names, (pcfg.dp, pcfg.pp, pcfg.tp)))
    flat_specs = treedef.flatten_up_to(specs)
    wd_masks, repl_w = [], []
    for b in buckets:
        parts = []
        for idx, shape, numel in b.entries:
            repl = int(np.prod([sizes[a] for a in pcfg.axis_names[1:]
                                if a not in _spec_axes(flat_specs[idx])]))
            parts.append(np.full((numel,), 1.0 / repl, np.float32))
        parts.append(np.zeros((b.pad,), np.float32))
        repl_w.append(np.concatenate(parts))
        wd_masks.append(comm_opt.bucket_wd_mask(b))
    b1, b2, eps = 0.9, 0.95, 1e-8

    def per_rank(params, opt, tokens, labels):
        local_loss, grads = jax.value_and_grad(_pipeline_loss)(
            params, tokens, labels, cfg, pcfg, double_buffer)
        with _named_collective("psum_loss"):
            comm_opt.record_collective("psum", jnp.float32, 4,
                                       pcfg.n_devices, site="psum_loss")
            loss = jax.lax.psum(local_loss, pcfg.axis_names)
        # pp/tp replication is still a per-leaf psum; the dp reduction is
        # the bucketed scatter below
        grads = psum_grads_by_spec(
            grads, specs, pcfg.axis_names, skip_axes=(dp_ax,),
            comm_dtype=ccfg.comm_dtype, quant_chunk=ccfg.quant_chunk)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_p = jax.tree_util.tree_leaves(params)
        dp_idx = jax.lax.axis_index(dp_ax)

        g_shards, p_shards, wd_shards, w_shards, ef_out = [], [], [], [], []
        ef_off = 0
        for i, b in enumerate(buckets):
            blen = b.size // dp
            with jax.named_scope(f"collective/rs_bucket{i}"):
                _m_collectives.labels("psum_scatter_grad").inc()
                vec = comm_opt.flatten_bucket(flat_g, b, jnp.float32)
                if ccfg.error_feedback:
                    vec = vec + jax.lax.dynamic_slice(
                        opt["ef"], (ef_off,), (b.size,))
                shard, resid = comm_opt.reduce_scatter_flat(vec, dp_ax, ccfg)
                g_shards.append(shard)
                if ccfg.error_feedback:
                    ef_out.append(resid)
            pvec = comm_opt.flatten_bucket(flat_p, b, jnp.float32)
            start = dp_idx * blen
            p_shards.append(jax.lax.dynamic_slice(pvec, (start,), (blen,)))
            wd_shards.append(jax.lax.dynamic_slice(
                jnp.asarray(wd_masks[i]), (start,), (blen,)))
            w_shards.append(jax.lax.dynamic_slice(
                jnp.asarray(repl_w[i]), (start,), (blen,)))
            ef_off += b.size

        gf = jnp.concatenate(g_shards) if len(g_shards) > 1 else g_shards[0]
        pf = jnp.concatenate(p_shards) if len(p_shards) > 1 else p_shards[0]
        wd_mask = jnp.concatenate(wd_shards) if len(wd_shards) > 1 \
            else wd_shards[0]
        w = jnp.concatenate(w_shards) if len(w_shards) > 1 else w_shards[0]

        with jax.named_scope("train/opt_update"):
            gnorm = jnp.sqrt(jax.lax.psum(
                jnp.sum(jnp.square(gf) * w), pcfg.axis_names))
            gf = gf * _clip_scale(gnorm, grad_clip)
            step_no = opt["step"] + 1
            c1 = 1 - b1 ** step_no.astype(jnp.float32)
            c2 = 1 - b2 ** step_no.astype(jnp.float32)
            mf = b1 * opt["m"].astype(jnp.float32) + (1 - b1) * gf
            vf = b2 * opt["v"].astype(jnp.float32) + (1 - b2) * gf * gf
            u = (mf / c1) / (jnp.sqrt(vf / c2) + eps)
            new_flat = pf - lr * (u + weight_decay * wd_mask * pf)

        # gather updated shards back into replicated leaves, per bucket
        new_by_idx = {}
        off = 0
        for i, b in enumerate(buckets):
            blen = b.size // dp
            with jax.named_scope(f"collective/ag_bucket{i}"):
                _m_collectives.labels("all_gather_params").inc()
                full = comm_opt.all_gather_flat(new_flat[off:off + blen],
                                                dp_ax)
            new_by_idx.update(comm_opt.unflatten_bucket(full, b))
            off += blen
        new_leaves = [new_by_idx[i].astype(p.dtype)
                      for i, p in enumerate(flat_p)]
        new_params = jax.tree_util.tree_unflatten(treedef, new_leaves)
        new_opt = {"m": mf.astype(opt["m"].dtype),
                   "v": vf.astype(opt["v"].dtype), "step": step_no}
        if ccfg.error_feedback:
            new_opt["ef"] = (jnp.concatenate(ef_out)
                             if len(ef_out) > 1 else ef_out[0])
        return loss, new_params, new_opt, gnorm

    flat_spec = P(tuple(pcfg.axis_names))
    opt_specs = {"m": flat_spec, "v": flat_spec, "step": P()}
    if ccfg.error_feedback:
        opt_specs["ef"] = flat_spec
    sharded = shard_map_compat(
        per_rank, mesh,
        in_specs=(specs, opt_specs, data_spec, data_spec),
        out_specs=(P(), specs, opt_specs, P()),
    )
    opt_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), opt_specs,
        is_leaf=lambda x: isinstance(x, P))

    @partial(jax.jit,
             in_shardings=(param_sh, opt_sh, data_sh, data_sh),
             out_shardings=(param_sh, opt_sh, None, None),
             donate_argnums=(0, 1))
    def step(params, opt_state, tokens, labels):
        with jax.named_scope("train/grad"):
            loss, new_params, new_opt, gnorm = sharded(
                params, opt_state, tokens, labels)
        if skip_nonfinite:
            # divergence guardrail (docs/health.md): loss and gnorm are
            # psum'd over the whole mesh, so every rank selects the same
            # branch and the next step's collectives stay matched
            with jax.named_scope("train/guardrail"):
                (new_params, new_opt), _bad = _health.nonfinite_guard(
                    (params, opt_state), (new_params, new_opt), loss, gnorm)
        return new_params, new_opt, loss, gnorm

    return step


def _make_gspmd_step(cfg: GPTConfig, pcfg: ParallelConfig, mesh: Mesh,
                     plan, lr, weight_decay, grad_clip,
                     skip_nonfinite: bool = False):
    """The sharding-layer train step (ISSUE 12, docs/sharding.md): pure
    ``jax.jit`` + ``NamedSharding`` from a propagated
    :class:`~paddle_tpu.sharding.ShardingPlan` — no shard_map, no
    hand-written collectives; GSPMD inserts whatever the specs imply
    (grad all-reduce for dp, all-gather/reduce-scatter for fsdp, the
    Megatron pattern for tp).

    The loss reduction is grouped by dp rank (reshape [B] ->
    [dp, B/dp], per-group CE, sum of per-group loss/denom) so the f32
    arithmetic ORDER matches the hand-written psum baseline exactly —
    that is what makes the dp parity test bit-identical, not just close.
    """
    from ..sharding.spec import spec_axes as _spec_axes_of

    dp_ax = pcfg.axis_names[0]
    dp = pcfg.dp
    param_specs = plan.param_specs
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, P))
    opt_specs = {"m": param_specs, "v": param_specs, "step": P()}
    opt_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), opt_specs,
        is_leaf=lambda x: isinstance(x, P))
    data_sh = NamedSharding(mesh, plan.data_spec)

    # static wire-byte accounting (comm_opt ring model, recorded once at
    # trace time like the explicit collectives): a dp-replicated leaf's
    # grad implies one psum over dp; a dp-sharded (fsdp) leaf implies
    # grad reduce-scatter + param all-gather. GSPMD inserts the real
    # collectives itself, so this is the plan-level estimate feeding the
    # same paddle_collective_bytes_total family comm_bench reads.
    _comm_recorded = {"done": False}

    def _record_static_comm():
        if _comm_recorded["done"] or dp <= 1:
            return
        _comm_recorded["done"] = True
        avals = jax.eval_shape(partial(gpt_mod.init_params, cfg=cfg),
                               jax.ShapeDtypeStruct((2,), jnp.uint32))
        flat_avals, treedef = jax.tree_util.tree_flatten(avals)
        flat_specs = treedef.flatten_up_to(param_specs)
        for a, s in zip(flat_avals, flat_specs):
            nbytes = int(np.prod(a.shape)) * 4  # f32 grads
            if dp_ax in _spec_axes_of(tuple(s)):
                comm_opt.record_collective("psum_scatter", jnp.float32,
                                           nbytes, dp,
                                           site="static_estimate")
                comm_opt.record_collective("all_gather", jnp.float32,
                                           nbytes, dp,
                                           site="static_estimate")
            else:
                comm_opt.record_collective("psum", jnp.float32, nbytes, dp,
                                           site="static_estimate")

    def loss_fn(params, tokens, labels):
        M, B, T = tokens.shape
        denom = jnp.float32(M * B * T)
        total = jnp.float32(0.0)
        for i in range(M):
            x = gpt_mod.embed(params, tokens[i], cfg)
            x = gpt_mod.run_blocks(params["blocks"], x, cfg)
            if dp > 1 and B % dp == 0:
                D = x.shape[-1]
                xg = jax.lax.with_sharding_constraint(
                    x.reshape(dp, B // dp, T, D),
                    NamedSharding(mesh, P(dp_ax)))
                lg = labels[i].reshape(dp, B // dp, T)
                ce = jax.vmap(
                    lambda a, b: gpt_mod.ce_from_hidden(params, a, b, cfg)
                )(xg, lg)
                total = total + jnp.sum(ce / denom)
            else:
                total = total + gpt_mod.ce_from_hidden(
                    params, x, labels[i], cfg) / denom
        return total

    @partial(jax.jit,
             in_shardings=(param_sh, opt_sh, data_sh, data_sh),
             out_shardings=(param_sh, opt_sh, None, None),
             donate_argnums=(0, 1))
    def step(params, opt_state, tokens, labels):
        _record_static_comm()  # host-side, runs once at trace time
        with jax.named_scope("train/grad"):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tokens, labels)
            # pin grads to the plan layouts: fsdp grads stay sharded (no
            # full-size grad materialization), dp grads replicate
            grads = jax.tree_util.tree_map(
                lambda g, s: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, s)),
                grads, param_specs,
                is_leaf=lambda x: isinstance(x, P))
        with jax.named_scope("train/opt_update"):
            new_params, new_opt, gnorm = _adamw_update(
                params, grads, opt_state, lr,
                weight_decay=weight_decay, grad_clip=grad_clip)
        if skip_nonfinite:
            # loss/gnorm are global (GSPMD reduces them), so the skip
            # decision is identical on every device (docs/health.md)
            with jax.named_scope("train/guardrail"):
                (new_params, new_opt), _bad = _health.nonfinite_guard(
                    (params, opt_state), (new_params, new_opt),
                    loss, gnorm)
        return new_params, new_opt, loss, gnorm

    return step


def make_train_step(cfg: GPTConfig, pcfg: ParallelConfig, mesh: Mesh,
                    lr: float = 3e-4, weight_decay: float = 0.1,
                    fused_opt: bool = False, fused_opt_pallas=None,
                    grad_reduce: str = "psum",
                    grad_allreduce_dtype=None, bucket_mb: float = 32.0,
                    error_feedback: bool = False, grad_clip=1.0,
                    comm: Optional[CommConfig] = None,
                    skip_nonfinite: bool = False,
                    sharding=None, tuned=None):
    """Build the jitted 4D-parallel training step.

    Returns ``step(params, opt_state, tokens, labels) ->
    (params, opt_state, loss, gnorm)``. tokens/labels are
    [microbatches, global_batch, T] int32.

    ``fused_opt=True`` runs the optimizer as a flat-buffer sweep
    (_adamw_update_fused; opt state from ``init_sharded(fused_opt=True)``).
    Single-device meshes only — concatenating differently-sharded leaves
    would force an all-gather per step. ``fused_opt_pallas`` additionally
    lowers that sweep through ONE Pallas megakernel launch
    (ops/pallas_kernels.megakernel_adamw_flat) — None = auto (TPU only),
    True/False forces; ignored without ``fused_opt``.

    Communication levers (docs/comm_opt.md; or pass a ready
    :class:`CommConfig` as ``comm``):

    - ``grad_reduce="reduce_scatter"``: per-leaf dp psum is replaced by
      size-capped flat gradient buckets reduced with ``lax.psum_scatter``;
      each dp rank applies AdamW to its shard (moments + the flat master
      sweep live dp-sharded — opt state from
      ``init_sharded(grad_reduce="reduce_scatter")``) and the updated
      params return via ``all_gather``. Gradient-reduction wire bytes
      halve; optimizer-state HBM drops by dp x. f32 comm is bit-identical
      to the psum baseline (tests/test_comm_opt.py).
    - ``grad_allreduce_dtype="bf16"|"int8"``: chunk-scaled quantized wire
      payload with f32 accumulation (comm_opt.py); ``error_feedback=True``
      (reduce_scatter mode) carries the per-rank quantization residual in
      the train state.
    - ``grad_clip=None`` disables gradient clipping exactly (scale 1.0).

    ``skip_nonfinite=True`` arms the in-jit divergence guardrail
    (``health.nonfinite_guard``, docs/health.md): a step whose psum'd loss
    or grad norm is NaN/Inf keeps the old ``(params, opt_state)`` wholesale
    (step counter included) — the batch is skipped identically on every dp
    rank, the full-precision generalization of AMP's overflow skip.

    ``sharding=`` routes through the GSPMD sharding layer (ISSUE 12,
    docs/sharding.md): a preset name (``"dp"`` | ``"fsdp"`` | ``"tp"``),
    an annotation dict on the weight leaves, or a ready
    :class:`paddle_tpu.sharding.ShardingPlan`. The plan's propagated
    specs drive a pure ``jax.jit`` + ``NamedSharding`` step (no
    shard_map) — dp is bit-identical to the hand-written psum baseline
    (f32 comm, tests/test_sharding.py), fsdp shards params AND optimizer
    moments dp-ways, tp derives the Megatron split from six annotations.
    Combining ``sharding=`` with the comm levers keeps comm_opt as the
    lowering underneath: a dp-replicated plan + ``grad_reduce=
    "reduce_scatter"``/quantized wire dtypes runs the existing bucketed
    shard_map path (the plan only supplies the layout contract); plans
    that shard params over dp cannot take that path and raise.

    ``tuned=`` accepts a TUNED.json path (or loaded doc) from
    tools/autotune.py. Application is fingerprint-gated (a config tuned
    on different hardware warns and falls back to the kwargs as given)
    and only overrides knobs left at their documented defaults — an
    explicit caller choice, or a ready ``comm=`` CommConfig, always
    wins over the tuner.
    """
    if tuned is not None and comm is None:
        kw = _resolve_tuned(tuned, pcfg, dict(
            grad_reduce=grad_reduce,
            grad_allreduce_dtype=grad_allreduce_dtype,
            bucket_mb=bucket_mb, error_feedback=error_feedback,
            fused_opt=fused_opt))
        grad_reduce = kw["grad_reduce"]
        grad_allreduce_dtype = kw["grad_allreduce_dtype"]
        bucket_mb = kw["bucket_mb"]
        error_feedback = kw["error_feedback"]
        fused_opt = kw["fused_opt"]
    ccfg = comm if comm is not None else CommConfig(
        grad_reduce=grad_reduce, comm_dtype=grad_allreduce_dtype,
        bucket_mb=bucket_mb, error_feedback=error_feedback)
    plan = None
    if sharding is not None:
        from ..sharding import resolve_plan

        plan = resolve_plan(sharding, cfg, pcfg)
        if pcfg.pp > 1:
            raise NotImplementedError(
                "sharding= plans do not cover GPipe pipeline stages; use "
                "the hand-written pp path (pp=1 required)")
        wants_comm_opt = (ccfg.grad_reduce == "reduce_scatter"
                          or ccfg.comm_dtype is not None)
        if not wants_comm_opt:
            step = _make_gspmd_step(cfg, pcfg, mesh, plan, lr,
                                    weight_decay, grad_clip,
                                    skip_nonfinite=skip_nonfinite)
            return _wrap_step_with_report(
                step, pcfg, report_name=(
                    f"parallel_train_step/dp{pcfg.dp}pp{pcfg.pp}"
                    f"tp{pcfg.tp}mb{pcfg.microbatches}"
                    f"_gspmd-{plan.mode}"),
                extra_mode=f"gspmd+named_sharding:{plan.mode}")
        if not plan.params_replicated_over(pcfg.axis_names[0]):
            raise NotImplementedError(
                "comm_opt grad reduction (reduce_scatter / quantized "
                "wire dtypes) needs dp-replicated params; plan "
                f"{plan.mode!r} shards params over "
                f"{pcfg.axis_names[0]!r} — drop the comm levers or use "
                "sharding='dp'")
        # dp-replicated plan + comm levers: fall through to the
        # hand-written comm_opt lowerings below — the plan's layout
        # contract matches them by construction
    if fused_opt and pcfg.n_devices > 1 and ccfg.grad_reduce != "reduce_scatter":
        raise NotImplementedError(
            "fused_opt on a multi-device mesh requires "
            "grad_reduce='reduce_scatter' (the bucketed flat sweep) "
            f"(got dp={pcfg.dp} pp={pcfg.pp} tp={pcfg.tp})")
    if ccfg.error_feedback and ccfg.grad_reduce != "reduce_scatter":
        raise NotImplementedError(
            "error_feedback requires grad_reduce='reduce_scatter' "
            "(the residual rides the sharded train state)")
    dp_ax, pp_ax, tp_ax = pcfg.axis_names
    specs = gpt_mod.param_specs(cfg, pp=pp_ax, tp=tp_ax)
    data_spec = P(None, dp_ax, None)
    db = ccfg.pipeline_double_buffer

    def grad_fn(params, tokens, labels):
        local_loss, grads = jax.value_and_grad(_pipeline_loss)(
            params, tokens, labels, cfg, pcfg, db)
        with _named_collective("psum_loss"):
            comm_opt.record_collective("psum", jnp.float32, 4,
                                       pcfg.n_devices, site="psum_loss")
            loss = jax.lax.psum(local_loss, pcfg.axis_names)
        grads = psum_grads_by_spec(
            grads, specs, pcfg.axis_names,
            comm_dtype=ccfg.comm_dtype, quant_chunk=ccfg.quant_chunk)
        return loss, grads

    param_sh = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                      is_leaf=lambda x: isinstance(x, P))
    data_sh = NamedSharding(mesh, data_spec)

    if ccfg.grad_reduce == "reduce_scatter":
        step = _make_rs_step(cfg, pcfg, mesh, ccfg, lr, weight_decay,
                             grad_clip, specs, param_sh, data_spec, data_sh,
                             db, skip_nonfinite=skip_nonfinite)
    else:
        sharded_grad = shard_map_compat(
            grad_fn, mesh,
            in_specs=(specs, data_spec, data_spec),
            out_specs=(P(), specs),
        )

        if fused_opt:
            opt_specs = {"m": P(), "v": P(), "step": P()}
        else:
            opt_specs = {"m": specs, "v": specs, "step": P()}
        opt_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), opt_specs,
            is_leaf=lambda x: isinstance(x, P))
        if fused_opt:
            from ..ops.pallas_kernels import use_opt_megakernel

            update = partial(
                _adamw_update_fused,
                use_pallas=use_opt_megakernel(fused_opt_pallas))
        else:
            update = _adamw_update

        @partial(jax.jit,
                 in_shardings=(param_sh, opt_sh, data_sh, data_sh),
                 out_shardings=(param_sh, opt_sh, None, None),
                 donate_argnums=(0, 1))
        def step(params, opt_state, tokens, labels):
            # named scopes stamp the phase into HLO metadata: the merged
            # host+device trace shows train/grad vs train/opt_update spans
            with jax.named_scope("train/grad"):
                loss, grads = sharded_grad(params, tokens, labels)
            # optimizer update is elementwise: GSPMD partitions it with zero
            # communication (replaces the reference's fuse_optimizer_ops pass)
            with jax.named_scope("train/opt_update"):
                new_params, new_opt, gnorm = update(
                    params, grads, opt_state, lr,
                    weight_decay=weight_decay, grad_clip=grad_clip)
            if skip_nonfinite:
                # loss/gnorm are already all-reduced: every rank takes the
                # same skip branch (docs/health.md)
                with jax.named_scope("train/guardrail"):
                    (new_params, new_opt), _bad = _health.nonfinite_guard(
                        (params, opt_state), (new_params, new_opt),
                        loss, gnorm)
            return new_params, new_opt, loss, gnorm

    report_name = (f"parallel_train_step/dp{pcfg.dp}pp{pcfg.pp}tp{pcfg.tp}"
                   f"mb{pcfg.microbatches}"
                   + ("_fused" if fused_opt else "")
                   + ("_rs" if ccfg.grad_reduce == "reduce_scatter" else "")
                   + (f"_{ccfg.comm_dtype}" if ccfg.comm_dtype else "")
                   + (f"_plan-{plan.mode}" if plan is not None else ""))
    return _wrap_step_with_report(step, pcfg, report_name=report_name,
                                  extra_mode="gspmd+shard_map")


def _wrap_step_with_report(step, pcfg: ParallelConfig, report_name: str,
                           extra_mode: str):
    # Program-report capture (observability/program_report.py): the first
    # invocation lowers + compiles explicitly, keeps the executable as the
    # dispatch target, and records cost/memory analysis, compile wall-ms
    # and the donation map — the same introspection surface Executor.run's
    # compiled blocks get. Any AOT failure reverts to implicit jit
    # dispatch permanently (never a correctness dependency).
    from ..observability import program_report as _prep

    aot = {"exec": None, "failed": False}

    def step_with_report(params, opt_state, tokens, labels):
        # hang-watchdog progress stamp (docs/health.md): one tuple store
        _health.progress("train_step")
        if aot["exec"] is None and not aot["failed"]:
            import time as _time

            t0 = _time.perf_counter_ns()
            try:
                # first-call XLA compile can run for minutes: pause the
                # hang-watchdog deadline clock for its duration
                with _health.suspend():
                    lowered = step.lower(params, opt_state, tokens, labels)
                    aot["exec"] = lowered.compile()
            except Exception:
                aot["failed"] = True
            else:
                _prep.capture(
                    report_name, compiled=aot["exec"],
                    compile_ms=(_time.perf_counter_ns() - t0) / 1e6,
                    donated=["params", "opt_state"],
                    inputs=(params, opt_state, tokens, labels),
                    extra={"mode": extra_mode,
                           "mesh": {a: int(s) for a, s in
                                    zip(pcfg.axis_names,
                                        (pcfg.dp, pcfg.pp, pcfg.tp))}})
        from ..observability import goodput as _goodput

        if aot["exec"] is not None:
            try:
                with _goodput.timer("productive_step"):
                    return aot["exec"](params, opt_state, tokens, labels)
            except TypeError:
                # arg-signature drift (raised before execution, nothing
                # donated yet): revert to jit dispatch for good
                aot["exec"] = None
                aot["failed"] = True
        with _goodput.timer("productive_step"):
            return step(params, opt_state, tokens, labels)

    def _hlo_text():
        # optimized HLO of the kept AOT executable (None before the first
        # call / after an AOT fallback) — the roofline attribution
        # (observability/attribution.py) joins its per-instruction static
        # costs with the measured device trace
        if aot["exec"] is None:
            return None
        try:
            return aot["exec"].as_text()
        except Exception:
            return None

    step_with_report.report_name = report_name
    step_with_report.hlo_text = _hlo_text
    return step_with_report


def make_forward(cfg: GPTConfig, pcfg: ParallelConfig, mesh: Mesh):
    """Jitted inference forward under dp+tp (GSPMD; pipeline folds into one
    stage pass per rank is only needed for training throughput)."""
    specs = gpt_mod.param_specs(cfg, pp=pcfg.axis_names[1],
                                tp=pcfg.axis_names[2])
    param_sh = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                      is_leaf=lambda x: isinstance(x, P))

    @partial(jax.jit, in_shardings=(param_sh, NamedSharding(mesh, P(pcfg.axis_names[0], None))))
    def fwd(params, tokens):
        return gpt_mod.forward(params, tokens, cfg)

    return fwd


def _resolve_tuned(tuned, pcfg, current):
    """Fingerprint-gate + apply a TUNED.json onto the caller's step
    kwargs (paddle_tpu/tuning/tuned.py owns the semantics)."""
    from ..tuning import tuned as tuned_mod

    doc = tuned_mod.load_for_device(tuned)
    if doc is None:
        return current
    return tuned_mod.resolve_train_step_kwargs(doc, pcfg, current)


def init_sharded(key, cfg: GPTConfig, pcfg: ParallelConfig, mesh: Mesh,
                 moment_dtype=None, fused_opt: bool = False,
                 grad_reduce: str = "psum", bucket_mb: float = 32.0,
                 error_feedback: bool = False, grad_allreduce_dtype=None,
                 comm: Optional[CommConfig] = None, sharding=None,
                 tuned=None):
    """Initialize params + AdamW state directly with mesh shardings (large
    models never materialize unsharded).

    ``grad_reduce="reduce_scatter"`` (pass the same comm kwargs as
    ``make_train_step``) lays the AdamW moments out as dp-sharded flat
    megabuffers matching the comm_opt bucket layout — optimizer-state HBM
    per device drops by dp x vs the replicated per-leaf layout.

    ``sharding=`` (a preset / annotation dict / ShardingPlan, same as
    ``make_train_step``) lays params AND per-leaf AdamW moments out per
    the plan's propagated specs — under ``"fsdp"`` both drop by dp x
    without the flat-buffer layout (comm levers then use the rs path
    above instead).

    ``tuned=`` mirrors ``make_train_step(tuned=)`` — pass the SAME
    TUNED.json to both so the optimizer-state layout matches the step
    the tuner picked."""
    if tuned is not None and comm is None:
        kw = _resolve_tuned(tuned, pcfg, dict(
            grad_reduce=grad_reduce,
            grad_allreduce_dtype=grad_allreduce_dtype,
            bucket_mb=bucket_mb, error_feedback=error_feedback,
            fused_opt=fused_opt))
        grad_reduce = kw["grad_reduce"]
        grad_allreduce_dtype = kw["grad_allreduce_dtype"]
        bucket_mb = kw["bucket_mb"]
        error_feedback = kw["error_feedback"]
        fused_opt = kw["fused_opt"]
    if sharding is not None:
        from ..sharding import resolve_plan

        plan = resolve_plan(sharding, cfg, pcfg)
        wants_comm_opt = (grad_reduce == "reduce_scatter"
                          or (comm is not None
                              and (comm.grad_reduce == "reduce_scatter"
                                   or comm.comm_dtype is not None))
                          or comm_opt.normalize_comm_dtype(
                              grad_allreduce_dtype) is not None)
        if not wants_comm_opt:
            param_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), plan.param_specs,
                is_leaf=lambda x: isinstance(x, P))
            init_jit = jax.jit(lambda k: gpt_mod.init_params(k, cfg),
                               out_shardings=param_sh)
            params = init_jit(key)
            opt_sh = {"m": param_sh, "v": param_sh, "step": None}
            opt_jit = jax.jit(
                partial(init_adamw_state, moment_dtype=moment_dtype),
                out_shardings=opt_sh)
            return params, opt_jit(params)
        # comm levers: the plan must be dp-replicated and the flat rs
        # layout below is the (sharded-state) source of truth
        if not plan.params_replicated_over(pcfg.axis_names[0]):
            raise NotImplementedError(
                "comm_opt grad reduction needs dp-replicated params; "
                f"plan {plan.mode!r} shards them")
    specs = gpt_mod.param_specs(cfg, pp=pcfg.axis_names[1], tp=pcfg.axis_names[2])
    param_sh = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                      is_leaf=lambda x: isinstance(x, P))
    ccfg = comm if comm is not None else CommConfig(
        grad_reduce=grad_reduce, comm_dtype=grad_allreduce_dtype,
        bucket_mb=bucket_mb, error_feedback=error_feedback)

    init_jit = jax.jit(lambda k: gpt_mod.init_params(k, cfg),
                       out_shardings=param_sh)
    params = init_jit(key)

    if ccfg.grad_reduce == "reduce_scatter":
        layout, _, _ = _rs_param_layout(cfg, pcfg, ccfg)
        n_dev = pcfg.n_devices
        flat_sh = NamedSharding(mesh, P(tuple(pcfg.axis_names)))
        mdt = moment_dtype or jnp.float32
        shapes = {"m": ((n_dev * layout.shard_len,), mdt),
                  "v": ((n_dev * layout.shard_len,), mdt),
                  "step": ((), jnp.int32)}
        opt_sh = {"m": flat_sh, "v": flat_sh,
                  "step": NamedSharding(mesh, P())}
        if ccfg.error_feedback:
            shapes["ef"] = ((n_dev * layout.total_len,), jnp.float32)
            opt_sh["ef"] = flat_sh
        opt_jit = jax.jit(
            lambda: {k: jnp.zeros(sh, dt) for k, (sh, dt) in shapes.items()},
            out_shardings=opt_sh)
        return params, opt_jit()

    if fused_opt:
        flat_sh = NamedSharding(mesh, P())
        opt_sh = {"m": flat_sh, "v": flat_sh, "step": None}
    else:
        opt_sh = {"m": param_sh, "v": param_sh, "step": None}
    opt_jit = jax.jit(partial(init_adamw_state, moment_dtype=moment_dtype,
                              fused=fused_opt),
                      out_shardings=opt_sh)
    return params, opt_jit(params)
