"""Distributed environment contract.

Parity with the reference's PADDLE_* env-var contract
(incubate/fleet/base/role_maker.py:501-536 PaddleCloudRoleMaker reads
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS /
PADDLE_CURRENT_ENDPOINT) and distributed/utils.py:338-375. The bootstrap that
the reference does via gRPC gen_nccl_id / raw sockets is jax.distributed
coordinator initialization here.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax


def trainer_id() -> int:
    return int(os.getenv("PADDLE_TRAINER_ID", "0"))


def trainer_num() -> int:
    v = os.getenv("PADDLE_TRAINERS_NUM")
    if v is not None:
        return int(v)
    if _initialized:
        return max(jax.process_count(), 1)
    return 1


def trainer_endpoints() -> List[str]:
    eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
    return [e for e in eps.split(",") if e]


def current_endpoint() -> str:
    return os.getenv("PADDLE_CURRENT_ENDPOINT", "")


_initialized = False


def init_distributed_env(coordinator: Optional[str] = None) -> None:
    """Initialize multi-process JAX from the PADDLE_* contract (replaces the
    reference's c_gen_nccl_id + c_comm_init bootstrap ops)."""
    global _initialized
    # NOTE: do not touch jax.process_count() (or any backend-querying API)
    # before jax.distributed.initialize — the query initializes the XLA
    # backend and initialize() then raises RuntimeError.
    if _initialized or trainer_num() <= 1:
        _initialized = True
        return
    eps = trainer_endpoints()
    coordinator = coordinator or (eps[0] if eps else None)
    if coordinator is None:
        raise RuntimeError(
            "multi-trainer env without PADDLE_TRAINER_ENDPOINTS — cannot "
            "determine the jax.distributed coordinator address"
        )
    # a slow-starting peer (or a coordinator that isn't bound yet) raises a
    # connect error on the fast ranks — retry with backoff instead of
    # failing the whole gang (docs/elastic.md)
    from .launch import init_collective_with_retry

    init_collective_with_retry(
        lambda: jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=trainer_num(),
            process_id=trainer_id(),
        ),
        retries=int(os.environ.get("PADDLE_INIT_RETRIES", "5")),
        backoff_s=0.5,
        log=lambda m: print(f"[init_distributed_env] {m}", flush=True),
    )
    _initialized = True
