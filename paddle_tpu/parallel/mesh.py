"""Device mesh management.

Replaces the reference's NCCL ring registry (platform/collective_helper.h:62
NCCLCommContext keyed ring_id->comm) with named jax.sharding.Mesh axes:
ring_id -> axis name is the only mapping collectives need; XLA routes the
collectives over ICI/DCN according to the mesh's device layout.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class MeshConfig:
    """Logical mesh shape: ordered (axis_name, size) pairs. size -1 = infer
    from the device count (at most one)."""

    axes: List[Tuple[str, int]]

    def resolve(self, n_devices: int) -> List[Tuple[str, int]]:
        fixed = 1
        infer_idx = None
        for i, (name, size) in enumerate(self.axes):
            if size == -1:
                infer_idx = i
            else:
                fixed *= size
        axes = list(self.axes)
        if infer_idx is not None:
            axes[infer_idx] = (axes[infer_idx][0], max(n_devices // fixed, 1))
        return axes


def build_mesh(config: MeshConfig | Sequence[Tuple[str, int]],
               devices: Optional[Sequence] = None) -> Mesh:
    if not isinstance(config, MeshConfig):
        config = MeshConfig(list(config))
    devices = list(devices if devices is not None else jax.devices())
    axes = config.resolve(len(devices))
    shape = tuple(s for _, s in axes)
    names = tuple(n for n, _ in axes)
    total = int(np.prod(shape))
    if total > len(devices):
        raise ValueError(
            f"mesh axes {axes} need {total} devices, have {len(devices)}")
    had_inferred = any(s == -1 for _, s in config.axes)
    if had_inferred and total != len(devices):
        # an inferred axis must tile the device count exactly — silently
        # running on a subset would skew per-device batch math
        raise ValueError(
            f"mesh axes {axes} (with inferred size) cover {total} of "
            f"{len(devices)} devices — sizes must tile the device count")
    dev_array = np.array(devices[:total]).reshape(shape)
    return Mesh(dev_array, names)


_current_mesh: Optional[Mesh] = None


def current_mesh() -> Optional[Mesh]:
    return _current_mesh


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    global _current_mesh
    old = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = old


def spec_for(var_sharding: Optional[Sequence[Optional[str]]]) -> P:
    """Convert a per-dim axis-name tuple (None = replicated dim) to a
    PartitionSpec."""
    if var_sharding is None:
        return P()
    return P(*var_sharding)


def named_sharding(mesh: Mesh, var_sharding=None) -> NamedSharding:
    return NamedSharding(mesh, spec_for(var_sharding))


def aval_of(x) -> jax.ShapeDtypeStruct:
    """Abstract value of a scope variable (or anything array-like)."""
    import jax.numpy as jnp

    a = jnp.asarray(x) if not hasattr(x, "shape") else x
    return jax.ShapeDtypeStruct(a.shape, a.dtype)


def feed_aval(shape, dt) -> jax.ShapeDtypeStruct:
    """Abstract value for a feed signature entry; 'bfloat16' has no numpy
    dtype and must map to the jax one."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if str(dt) == "bfloat16" else np.dtype(dt)
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def jit_shard_map(per_rank, mesh: Mesh, in_specs, out_specs,
                  donate_argnums=()):
    """shard_map + jit with the replication-check kwarg spelled for the
    running jax version (check_vma on current, check_rep on older). The
    single wrapping point for the executor / pipeline / grad-merge
    per-rank executables."""
    try:
        from jax import shard_map as _shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        wrapped = _shard_map(per_rank, **kwargs, check_vma=False)
    except TypeError:  # older jax spells it check_rep
        wrapped = _shard_map(per_rank, **kwargs, check_rep=False)
    return jax.jit(wrapped, donate_argnums=donate_argnums)


def probe_produced_state(fn, mutable_avals, const_avals, feed_avals,
                         fallback):
    """Discover which persistable names ``fn`` actually produces by
    abstract evaluation (shapes the shard_map out_specs pytree before
    tracing). Falls back to ``fallback`` when the probe itself cannot
    run (e.g. collectives that need a bound axis context)."""
    key_aval = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    try:
        _, state_shape = jax.eval_shape(fn, mutable_avals, const_avals,
                                        feed_avals, key_aval)
        return sorted(state_shape.keys())
    except Exception:
        return list(fallback)
