"""Device mesh management.

Replaces the reference's NCCL ring registry (platform/collective_helper.h:62
NCCLCommContext keyed ring_id->comm) with named jax.sharding.Mesh axes:
ring_id -> axis name is the only mapping collectives need; XLA routes the
collectives over ICI/DCN according to the mesh's device layout.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class MeshConfig:
    """Logical mesh shape: ordered (axis_name, size) pairs. size -1 = infer
    from the device count (at most one)."""

    axes: List[Tuple[str, int]]

    def resolve(self, n_devices: int) -> List[Tuple[str, int]]:
        fixed = 1
        infer_idx = None
        for i, (name, size) in enumerate(self.axes):
            if size == -1:
                infer_idx = i
            else:
                fixed *= size
        axes = list(self.axes)
        if infer_idx is not None:
            axes[infer_idx] = (axes[infer_idx][0], max(n_devices // fixed, 1))
        return axes


def build_mesh(config: MeshConfig | Sequence[Tuple[str, int]],
               devices: Optional[Sequence] = None) -> Mesh:
    if not isinstance(config, MeshConfig):
        config = MeshConfig(list(config))
    devices = list(devices if devices is not None else jax.devices())
    axes = config.resolve(len(devices))
    shape = tuple(s for _, s in axes)
    names = tuple(n for n, _ in axes)
    total = int(np.prod(shape))
    if total > len(devices):
        raise ValueError(
            f"mesh axes {axes} need {total} devices, have {len(devices)}")
    had_inferred = any(s == -1 for _, s in config.axes)
    if had_inferred and total != len(devices):
        # an inferred axis must tile the device count exactly — silently
        # running on a subset would skew per-device batch math
        raise ValueError(
            f"mesh axes {axes} (with inferred size) cover {total} of "
            f"{len(devices)} devices — sizes must tile the device count")
    dev_array = np.array(devices[:total]).reshape(shape)
    return Mesh(dev_array, names)


_current_mesh: Optional[Mesh] = None


def current_mesh() -> Optional[Mesh]:
    return _current_mesh


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    global _current_mesh
    old = _current_mesh
    _current_mesh = mesh
    try:
        yield mesh
    finally:
        _current_mesh = old


def spec_for(var_sharding: Optional[Sequence[Optional[str]]]) -> P:
    """Convert a per-dim axis-name tuple (None = replicated dim) to a
    PartitionSpec."""
    if var_sharding is None:
        return P()
    return P(*var_sharding)


def named_sharding(mesh: Mesh, var_sharding=None) -> NamedSharding:
    return NamedSharding(mesh, spec_for(var_sharding))
