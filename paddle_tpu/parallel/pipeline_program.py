"""Fluid-level pipeline parallelism: run a user Program's forward as GPipe
stages over a ``pp`` mesh axis.

Reference: PipelineOptimizer (python/paddle/fluid/optimizer.py:3556-3858)
splits block-0 into section sub-programs executed by SectionWorker threads
passing Scopes through blocking queues (framework/pipeline_trainer.cc,
section_worker.cc). The TPU-native equivalent here is ONE compiled program:

- the forward op-list is cut into S contiguous stages (at user cut vars or
  evenly); the boundary interface (vars produced before / consumed after the
  cut) is packed into a fixed-size carry vector;
- a ``shard_map`` over a ``("pp", S)`` mesh runs the schedule; each rank
  selects its stage body with ``lax.switch(axis_index)``, and activations
  move stage->stage+1 by ``lax.ppermute`` inside a ``lax.scan`` over
  M + S - 1 microbatch ticks (the same schedule as the GPT engine,
  parallelize.py);
- gradients come from ``jax.grad`` through the whole schedule (scan /
  ppermute / switch all have transposes), psum'd over ``pp`` so every rank
  holds full grads; the Program's own backward ops are skipped;
- the Program's optimizer tail (clip / regularizer / update ops appended by
  the inner optimizer) then runs unchanged via the normal lowering, with the
  computed grads seeded under their ``<param>@GRAD`` names — so any fluid
  optimizer works un-modified under the pipeline.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import GRAD_SUFFIX, LowerCtx, run_lowering


# ---------------------------------------------------------------------------
# annotation (written by PipelineOptimizer.minimize)
# ---------------------------------------------------------------------------

def annotate_pipeline(program, loss, n_fwd: int, bwd_end: int,
                      num_stages: int, num_microbatches: int,
                      cut_list=None, trainable_params: Sequence[str] = (),
                      remat_policy: str = "none"):
    """Record the stage split on the program; the Executor routes programs
    carrying this annotation through _CompiledPipelineBlock."""
    block = program.global_block()
    if cut_list:
        producer = {}
        for idx, op in enumerate(block.ops[:n_fwd]):
            for name in op.output_arg_names:
                producer[name] = idx
        bounds = []
        for cut in cut_list:
            vars_ = cut if isinstance(cut, (list, tuple)) else [cut]
            idxs = []
            for v in vars_:
                name = v.name if hasattr(v, "name") else v
                if name not in producer:
                    raise ValueError(
                        f"pipeline cut variable {name!r} must be produced "
                        "by a forward op (feeds and parameters cannot be "
                        "stage boundaries)")
                idxs.append(producer[name])
            bounds.append(max(idxs) + 1)
        bounds = sorted(set(bounds))
        if bounds and bounds[-1] >= n_fwd:
            bounds = [b for b in bounds if b < n_fwd]
        stage_bounds = [0] + bounds + [n_fwd]
    else:
        S = int(num_stages)
        per = max(1, n_fwd // S)
        stage_bounds = [min(i * per, n_fwd) for i in range(S)] + [n_fwd]
    stage_ranges = [(stage_bounds[i], stage_bounds[i + 1])
                    for i in range(len(stage_bounds) - 1)]
    # anchor the region boundaries on the ops (see grad_merge.py
    # resolve_tail_start): transpiles that insert ops into the backward
    # region (fleet GradAllReduce) must not shift the optimizer tail
    for op in block.ops[n_fwd:bwd_end]:
        op._set_attr("__bwd_op__", 1)
    for op in block.ops[bwd_end:]:
        op._set_attr("__opt_tail__", 1)
    from . import remat as remat_mod

    program._annotations["pipeline"] = {
        "stage_ranges": stage_ranges,
        "n_fwd": n_fwd,
        "bwd_end": bwd_end,
        "loss": loss.name,
        "microbatches": int(num_microbatches),
        "trainable": list(trainable_params),
        "remat": remat_mod.resolve(remat_policy).name,
    }
    program._bump_version()


# ---------------------------------------------------------------------------
# compiled pipeline executable
# ---------------------------------------------------------------------------

class _CompiledPipelineBlock:
    """Counterpart of executor._CompiledBlock for pipeline-annotated
    programs. Same call contract: (scope, feeds, rng) -> fetches, and
    persistable updates written back to the scope."""

    def __init__(self, program, feed_sig, fetch_names, param_names,
                 written_names, scope, mesh_plan=None):
        from ..parallel.mesh import build_mesh

        ann = program._annotations["pipeline"]
        block = program.global_block()
        ops = block.ops
        self.program = program
        self.feed_names = [n for n, _, _ in feed_sig]
        self.fetch_names = list(fetch_names)
        self.param_names = list(param_names)
        self.written_names = list(written_names)
        self.mesh_plan = mesh_plan

        from .grad_merge import resolve_tail_start

        stage_ranges: List[Tuple[int, int]] = ann["stage_ranges"]
        S = len(stage_ranges)
        M = ann["microbatches"]
        loss_name = ann["loss"]
        trainable = [n for n in ann["trainable"] if n in param_names]
        # boundaries are op-anchored (annotate_pipeline), so transpiles
        # that insert ops after minimize() can't leave a stale bwd_end;
        # insertions into the FORWARD region would invalidate stage_ranges
        # and must fail loudly instead of mis-splitting stages
        n_fwd_now = next(
            (i for i, op in enumerate(ops)
             if op.attr("__bwd_op__", 0) or op.attr("__opt_tail__", 0)),
            ann["n_fwd"])
        if n_fwd_now != ann["n_fwd"]:
            raise NotImplementedError(
                "ops were inserted into the forward region after "
                "PipelineOptimizer.minimize(); re-run minimize() after "
                "program surgery so stage boundaries are recomputed")
        bwd_end = resolve_tail_start(ops, ann["bwd_end"])
        opt_ops = ops[bwd_end:]
        self._S, self._M = S, M

        # persistables written by the FORWARD region (batch_norm moving
        # stats, metric states): these update once per microbatch, so they
        # ride the scan carry and are threaded sequentially through the
        # schedule, then psum'd as deltas so every rank ends with the
        # owning stage's final value.
        written_set = set(written_names)
        param_set = set(param_names)
        fwd_written: List[str] = []
        fwd_written_seen = set()
        for op in ops[:ann["n_fwd"]]:
            for name in op.output_arg_names:
                if (name in written_set and name in param_set
                        and name not in fwd_written_seen):
                    fwd_written_seen.add(name)
                    fwd_written.append(name)

        # ---- static interface analysis -------------------------------------
        producer: Dict[str, int] = {}
        for idx, op in enumerate(ops[:ann["n_fwd"]]):
            for name in op.output_arg_names:
                producer[name] = idx
        persist = set(param_names)
        feed_set = set(self.feed_names)
        # boundary b sits after stage b (b in 0..S-2)
        iface_names: List[List[str]] = []
        for b in range(S - 1):
            bound = stage_ranges[b][1]
            names = set()
            for op in ops[bound:ann["n_fwd"]]:
                for name in op.input_arg_names:
                    p = producer.get(name)
                    if p is None or p >= bound:
                        continue
                    if name in persist or name in feed_set:
                        continue
                    names.add(name)
            iface_names.append(sorted(names))

        # ---- mesh: (dp?, pp) — composes with data parallelism the way the
        # reference's PipelineTrainer composes with MultiTrainer replicas:
        # each dp group runs the full pipeline on its batch shard and grads
        # are averaged over dp before the (replicated) optimizer tail
        dp_axes: Tuple[Tuple[str, int], ...] = ()
        data_axis = None
        ring_axes: Dict[int, str] = {}
        if mesh_plan is not None and mesh_plan.axes:
            dp_axes = tuple(
                (n, s) for n, s in mesh_plan.axes if n != "pp")
            if len(dp_axes) > 1:
                # feeds are sharded (and grads averaged) over exactly one
                # data axis; a second model-parallel axis has no meaning
                # for a fluid pipeline program
                raise NotImplementedError(
                    f"pipeline composes with a single data-parallel axis; "
                    f"mesh plan has extra axes {dp_axes}")
            data_axis = mesh_plan.data_axis
            ring_axes = dict(mesh_plan.ring_axes)
        if data_axis is None and dp_axes:
            data_axis = dp_axes[0][0]
        mesh = build_mesh(dp_axes + (("pp", S),))
        self.mesh = mesh
        dp = int(mesh.shape[data_axis]) if data_axis else 1
        self._dp = dp
        has_collectives = any(op.type.startswith("c_") or
                              op.type in ("allreduce", "broadcast")
                              for op in ops)
        if has_collectives and not ring_axes:
            # a transpiled c_allreduce with no ring->axis mapping would
            # silently lower as identity and train without gradient sync
            raise NotImplementedError(
                "pipeline program contains collective ops but no mesh plan "
                "maps their ring_ids to mesh axes; run it through "
                "CompiledProgram.with_data_parallel / a mesh annotation")

        # ---- shapes: abstract-eval the forward on one microbatch -----------
        mb_feed_sig = []
        batch = None
        for name, shape, dt in feed_sig:
            var = block.vars.get(name)
            is_data = bool(getattr(var, "is_data", False)) and len(shape) > 0
            if is_data:
                batch = shape[0] if batch is None else batch
        if batch is None:
            raise ValueError("pipeline program has no batched data feeds")
        if batch % (M * dp) != 0:
            raise ValueError(
                f"batch {batch} not divisible by num_microbatches {M} "
                f"x dp {dp}")
        mb = batch // dp // M
        self._batched_feeds = set()
        for name, shape, dt in feed_sig:
            var = block.vars.get(name)
            if (getattr(var, "is_data", False) and shape and
                    shape[0] == batch):
                self._batched_feeds.add(name)
                mb_feed_sig.append((name, (mb,) + tuple(shape[1:]), dt))
            else:
                mb_feed_sig.append((name, tuple(shape), dt))

        from .mesh import aval_of, feed_aval

        param_avals = {n: aval_of(scope.find_var(n)) for n in param_names
                       if scope.has_var(n)}
        feed_avals = {n: feed_aval(s, d) for n, s, d in mb_feed_sig}

        def fwd_probe(params, feeds):
            env = dict(params)
            env.update(feeds)
            ctx = LowerCtx(program, block, env,
                           rng_key=jax.random.PRNGKey(0))
            for op in ops[:ann["n_fwd"]]:
                run_lowering(ctx, op)
            return [{n: env[n] for n in names} for names in iface_names]

        iface_avals = jax.eval_shape(fwd_probe, param_avals, feed_avals)

        # ---- carry packing: one fixed-size vector PER DTYPE ----------------
        # bf16 activations cross the stage cut as bf16 (half the ppermute
        # bytes of an f32 carry); integer/bool interface vars (token ids,
        # masks) ride their own vectors instead of being rejected. bool is
        # carried as uint8 (collective-friendly) and restored on unpack.
        def _carry_dt(dt):
            dt = np.dtype(dt) if not isinstance(dt, np.dtype) else dt
            return "uint8" if dt == np.dtype(bool) else dt.name

        layouts = []  # per boundary: [(name, shape, n_el, carry_dt, orig_dt)]
        dtype_sizes: Dict[str, int] = {}
        for b, avals in enumerate(iface_avals):
            lay = []
            sizes_b: Dict[str, int] = {}
            for name in iface_names[b]:
                av = avals[name]
                cdt = _carry_dt(av.dtype)
                n_el = int(np.prod(av.shape)) if av.shape else 1
                lay.append((name, tuple(av.shape), n_el, cdt, av.dtype))
                sizes_b[cdt] = sizes_b.get(cdt, 0) + n_el
            layouts.append(lay)
            for cdt, total in sizes_b.items():
                dtype_sizes[cdt] = max(dtype_sizes.get(cdt, 0), total)
        if not dtype_sizes:
            dtype_sizes = {"float32": 1}
        carry_dts = sorted(dtype_sizes)
        self._iface_elems = dict(dtype_sizes)

        def zero_carry():
            return {cdt: jnp.zeros((dtype_sizes[cdt],), jnp.dtype(cdt))
                    for cdt in carry_dts}

        def pack(b, env):
            vecs = {}
            for cdt in carry_dts:
                parts = [env[name].astype(jnp.dtype(cdt)).reshape(-1)
                         for name, _, _, c, _ in layouts[b] if c == cdt]
                if not parts:
                    vecs[cdt] = jnp.zeros((dtype_sizes[cdt],),
                                          jnp.dtype(cdt))
                    continue
                vec = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
                pad = dtype_sizes[cdt] - vec.shape[0]
                vecs[cdt] = jnp.pad(vec, (0, pad)) if pad else vec
            return vecs

        def unpack(b, vecs):
            out = {}
            off = {cdt: 0 for cdt in carry_dts}
            for name, shape, n_el, cdt, orig_dt in layouts[b]:
                o = off[cdt]
                out[name] = (vecs[cdt][o:o + n_el].reshape(shape)
                             .astype(orig_dt))
                off[cdt] = o + n_el
            return out

        perm = [(i, (i + 1) % S) for i in range(S)]
        n_fwd = ann["n_fwd"]
        from . import remat as remat_mod

        # stage-body remat: with a non-"none" policy each stage's
        # activations are recomputed in the backward of the microbatch
        # schedule instead of being saved across all M+S-1 scan ticks
        remat_policy = remat_mod.resolve(ann.get("remat", "none"))

        def per_rank(mutable_params, const_params, feeds, rng_key):
            stage = jax.lax.axis_index("pp")
            if dp > 1:
                # each dp group draws distinct randomness for its shard
                rng_key = jax.random.fold_in(
                    rng_key, jax.lax.axis_index(data_axis))
            base_params = dict(const_params)
            base_params.update(mutable_params)
            split = {}
            for n, f in feeds.items():
                if n in self._batched_feeds:
                    split[n] = f.reshape((M, mb) + tuple(f.shape[1:]))
                else:
                    split[n] = f

            def loss_fn(train_params):
                params = dict(base_params)
                params.update(train_params)

                def tick(carry, t):
                    iface, loss_sum, fwd_state = carry
                    # double-buffered stage boundary: the carry holds LAST
                    # tick's un-permuted outputs, so their ppermute issues
                    # at the head of this tick and the send is in flight
                    # while this tick's stage body computes (async
                    # collective-permute + latency-hiding scheduler,
                    # sysconfig.tpu_perf_flags). Values are identical to
                    # the permute-at-tail schedule — the permute commutes
                    # with the scan carry.
                    if S > 1:
                        from . import comm_opt as _comm

                        with jax.named_scope(
                                "collective/ppermute_activation"):
                            for _v in jax.tree_util.tree_leaves(iface):
                                _comm.record_collective(
                                    "ppermute", _v.dtype,
                                    _v.size * _v.dtype.itemsize, S,
                                    site="ppermute_activation")
                            iface = jax.tree_util.tree_map(
                                lambda a: jax.lax.ppermute(a, "pp", perm),
                                iface)
                    m = jnp.clip(t - stage, 0, M - 1)
                    feeds_mb = {
                        n: (jax.lax.dynamic_index_in_dim(f, m, 0,
                                                         keepdims=False)
                            if n in self._batched_feeds else f)
                        for n, f in split.items()
                    }
                    # distinct randomness per microbatch (dropout masks must
                    # differ across the M microbatches of one large batch);
                    # per-op distinctness comes from rng_for's name salt
                    mb_key = jax.random.fold_in(rng_key, m)

                    def make_branch(s):
                        lo, hi = stage_ranges[s]

                        def branch(operand):
                            vec, fstate = operand
                            env = dict(params)
                            env.update(fstate)
                            env.update(feeds_mb)
                            if s > 0:
                                env.update(unpack(s - 1, vec))
                            ctx = LowerCtx(program, block, env,
                                           rng_key=mb_key,
                                           mesh_axes=ring_axes)
                            for op in ops[lo:hi]:
                                run_lowering(ctx, op)
                            new_fstate = {n: env[n] for n in fwd_written}
                            if s < S - 1:
                                return (pack(s, env),
                                        jnp.zeros((), jnp.float32),
                                        new_fstate)
                            loss = env[loss_name].astype(jnp.float32)
                            return (zero_carry(),
                                    loss.reshape(()), new_fstate)

                        return remat_policy.wrap(branch)

                    out, mb_loss, new_fstate = jax.lax.switch(
                        stage, [make_branch(s) for s in range(S)],
                        (iface, fwd_state))
                    valid = ((t - stage) >= 0) & ((t - stage) < M)
                    is_last = stage == S - 1
                    loss_sum = loss_sum + jnp.where(valid & is_last,
                                                    mb_loss, 0.0)
                    # warm-up / drain ticks re-run a clipped microbatch: do
                    # not let them double-update forward-written state
                    fwd_state = {
                        n: jnp.where(valid, new_fstate[n], fwd_state[n])
                        for n in fwd_written
                    }
                    return (out, loss_sum, fwd_state), None

                carry0 = (zero_carry(),
                          jnp.zeros((), jnp.float32),
                          {n: jnp.asarray(params[n]) for n in fwd_written})
                (_, loss_sum, fwd_state_out), _ = jax.lax.scan(
                    tick, carry0, jnp.arange(M + S - 1))
                # rank-LOCAL loss (only the last stage is nonzero): grads
                # must not differentiate through a psum — its shard_map
                # transpose re-psums the cotangent, inflating grads by S
                return loss_sum / M, fwd_state_out

            train_params = {n: mutable_params[n] for n in trainable
                            if n in mutable_params}
            (local_loss, fwd_state_local), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(train_params)
            loss_val = jax.lax.psum(local_loss, "pp")
            grads = {n: jax.lax.psum(g, "pp") for n, g in grads.items()}
            if dp > 1:
                # global loss = mean over dp shards of per-shard mean loss;
                # grads follow (each rank's grad is d(local mean)/dparam)
                loss_val = jax.lax.pmean(loss_val, data_axis)
                grads = {n: jax.lax.pmean(g, data_axis)
                         for n, g in grads.items()}

            # forward-written persistables: only the owning stage's rank
            # holds the true final value; everyone else still has the base,
            # so a psum of deltas broadcasts the owner's update (then a mean
            # over dp groups, whose shards saw different data)
            fwd_final = {}
            for n in fwd_written:
                base = jnp.asarray(base_params[n])
                delta = (fwd_state_local[n] - base).astype(jnp.float32)
                upd = (base.astype(jnp.float32)
                       + jax.lax.psum(delta, "pp"))
                if dp > 1:
                    upd = jax.lax.pmean(upd, data_axis)
                fwd_final[n] = upd.astype(base.dtype)

            # ---- optimizer tail: the Program's own update ops -------------
            env = dict(base_params)
            env.update(fwd_final)
            env.update({n: f for n, f in feeds.items()
                        if n not in self._batched_feeds})
            env[loss_name] = loss_val
            for n, g in grads.items():
                env[n + GRAD_SUFFIX] = g
            ctx = LowerCtx(program, block, env, rng_key=rng_key,
                           mesh_axes=ring_axes)
            for op in opt_ops:
                run_lowering(ctx, op)

            fetches = []
            for name in self.fetch_names:
                if name == loss_name:
                    fetches.append(jnp.atleast_1d(loss_val))
                elif name in env:
                    fetches.append(jnp.atleast_1d(env[name]))
                else:
                    raise NotImplementedError(
                        f"pipeline fetch {name!r}: only the loss, "
                        "persistables, and optimizer-phase outputs are "
                        "fetchable")
            new_state = {n: env[n] for n in self.written_names if n in env}
            return fetches, new_state

        from jax.sharding import PartitionSpec as P

        written = set(written_names)
        mutable_specs = {n: P() for n in param_names if n in written}
        const_specs = {n: P() for n in param_names if n not in written}
        feed_specs = {n: (P(data_axis) if (dp > 1 and
                                           n in self._batched_feeds)
                          else P())
                      for n, _, _ in feed_sig}
        fetch_specs = [P() for _ in fetch_names]

        def _make_jit(produced_state_names):
            from ..parallel.mesh import jit_shard_map

            state_specs = {n: P() for n in produced_state_names}

            def wrapped_per_rank(mutable_params, const_params, feeds, key):
                fetches, new_state = per_rank(mutable_params, const_params,
                                              feeds, key)
                return fetches, {n: new_state[n]
                                 for n in produced_state_names}

            return jit_shard_map(
                wrapped_per_rank, mesh,
                in_specs=(mutable_specs, const_specs, feed_specs, P()),
                out_specs=(fetch_specs, state_specs),
                donate_argnums=(0,))

        # discover which written names the opt phase actually produces, via
        # an eval_shape of per_rank under a fake axis context: simplest is to
        # run eval_shape on the shard-mapped function itself
        # the opt-phase env starts from every scope persistable, so all
        # written names are bound; restrict to the ones present in the scope
        produced = [n for n in self.written_names if scope.has_var(n)]
        self._jitted = _make_jit(produced)
        self._produced = produced

    def __call__(self, scope, feed, rng_key):
        mutable, const = {}, {}
        written = set(self.written_names)
        for n in self.param_names:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"persistable var {n!r} is not initialized in scope — "
                    "run the startup program first")
            (mutable if n in written else const)[n] = v
        feeds = {n: feed[n] for n in self.feed_names}
        fetches, new_state = self._jitted(mutable, const, feeds, rng_key)
        for n, v in new_state.items():
            scope.set_var(n, v)
        return fetches
