"""Fluid-level pipeline parallelism: run a user Program's forward as GPipe
stages over a ``pp`` mesh axis.

Reference: PipelineOptimizer (python/paddle/fluid/optimizer.py:3556-3858)
splits block-0 into section sub-programs executed by SectionWorker threads
passing Scopes through blocking queues (framework/pipeline_trainer.cc,
section_worker.cc). The TPU-native equivalent here is ONE compiled program:

- the forward op-list is cut into S contiguous stages (at user cut vars or
  evenly); the boundary interface (vars produced before / consumed after the
  cut) is packed into a fixed-size carry vector;
- a ``shard_map`` over a ``("pp", S)`` mesh runs the schedule; each rank
  selects its stage body with ``lax.switch(axis_index)``, and activations
  move stage->stage+1 by ``lax.ppermute`` inside a ``lax.scan`` over
  M + S - 1 microbatch ticks (the same schedule as the GPT engine,
  parallelize.py);
- gradients come from ``jax.grad`` through the whole schedule (scan /
  ppermute / switch all have transposes), psum'd over ``pp`` so every rank
  holds full grads; the Program's own backward ops are skipped;
- the Program's optimizer tail (clip / regularizer / update ops appended by
  the inner optimizer) then runs unchanged via the normal lowering, with the
  computed grads seeded under their ``<param>@GRAD`` names — so any fluid
  optimizer works un-modified under the pipeline.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import GRAD_SUFFIX, LowerCtx, run_lowering

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map


# ---------------------------------------------------------------------------
# annotation (written by PipelineOptimizer.minimize)
# ---------------------------------------------------------------------------

def annotate_pipeline(program, loss, n_fwd: int, bwd_end: int,
                      num_stages: int, num_microbatches: int,
                      cut_list=None, trainable_params: Sequence[str] = ()):
    """Record the stage split on the program; the Executor routes programs
    carrying this annotation through _CompiledPipelineBlock."""
    block = program.global_block()
    if cut_list:
        producer = {}
        for idx, op in enumerate(block.ops[:n_fwd]):
            for name in op.output_arg_names:
                producer[name] = idx
        bounds = []
        for cut in cut_list:
            vars_ = cut if isinstance(cut, (list, tuple)) else [cut]
            idxs = []
            for v in vars_:
                name = v.name if hasattr(v, "name") else v
                if name not in producer:
                    raise ValueError(
                        f"pipeline cut variable {name!r} must be produced "
                        "by a forward op (feeds and parameters cannot be "
                        "stage boundaries)")
                idxs.append(producer[name])
            bounds.append(max(idxs) + 1)
        bounds = sorted(set(bounds))
        if bounds and bounds[-1] >= n_fwd:
            bounds = [b for b in bounds if b < n_fwd]
        stage_bounds = [0] + bounds + [n_fwd]
    else:
        S = int(num_stages)
        per = max(1, n_fwd // S)
        stage_bounds = [min(i * per, n_fwd) for i in range(S)] + [n_fwd]
    stage_ranges = [(stage_bounds[i], stage_bounds[i + 1])
                    for i in range(len(stage_bounds) - 1)]
    program._annotations["pipeline"] = {
        "stage_ranges": stage_ranges,
        "n_fwd": n_fwd,
        "bwd_end": bwd_end,
        "loss": loss.name,
        "microbatches": int(num_microbatches),
        "trainable": list(trainable_params),
    }
    program._bump_version()


# ---------------------------------------------------------------------------
# compiled pipeline executable
# ---------------------------------------------------------------------------

class _CompiledPipelineBlock:
    """Counterpart of executor._CompiledBlock for pipeline-annotated
    programs. Same call contract: (scope, feeds, rng) -> fetches, and
    persistable updates written back to the scope."""

    def __init__(self, program, feed_sig, fetch_names, param_names,
                 written_names, scope):
        from ..parallel.mesh import build_mesh

        ann = program._annotations["pipeline"]
        block = program.global_block()
        ops = block.ops
        self.program = program
        self.feed_names = [n for n, _, _ in feed_sig]
        self.fetch_names = list(fetch_names)
        self.param_names = list(param_names)
        self.written_names = list(written_names)

        stage_ranges: List[Tuple[int, int]] = ann["stage_ranges"]
        S = len(stage_ranges)
        M = ann["microbatches"]
        loss_name = ann["loss"]
        trainable = [n for n in ann["trainable"] if n in param_names]
        opt_ops = ops[ann["bwd_end"]:]
        self._S, self._M = S, M

        # ---- static interface analysis -------------------------------------
        producer: Dict[str, int] = {}
        for idx, op in enumerate(ops[:ann["n_fwd"]]):
            for name in op.output_arg_names:
                producer[name] = idx
        persist = set(param_names)
        feed_set = set(self.feed_names)
        # boundary b sits after stage b (b in 0..S-2)
        iface_names: List[List[str]] = []
        for b in range(S - 1):
            bound = stage_ranges[b][1]
            names = set()
            for op in ops[bound:ann["n_fwd"]]:
                for name in op.input_arg_names:
                    p = producer.get(name)
                    if p is None or p >= bound:
                        continue
                    if name in persist or name in feed_set:
                        continue
                    names.add(name)
            iface_names.append(sorted(names))

        # ---- shapes: abstract-eval the forward on one microbatch -----------
        mb_feed_sig = []
        batch = None
        for name, shape, dt in feed_sig:
            var = block.vars.get(name)
            is_data = bool(getattr(var, "is_data", False)) and len(shape) > 0
            if is_data:
                batch = shape[0] if batch is None else batch
        if batch is None:
            raise ValueError("pipeline program has no batched data feeds")
        if batch % M != 0:
            raise ValueError(
                f"batch {batch} not divisible by num_microbatches {M}")
        mb = batch // M
        self._batched_feeds = set()
        for name, shape, dt in feed_sig:
            var = block.vars.get(name)
            if (getattr(var, "is_data", False) and shape and
                    shape[0] == batch):
                self._batched_feeds.add(name)
                mb_feed_sig.append((name, (mb,) + tuple(shape[1:]), dt))
            else:
                mb_feed_sig.append((name, tuple(shape), dt))

        def _aval_of(v):
            a = jnp.asarray(v) if not hasattr(v, "dtype") else v
            return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

        param_avals = {n: _aval_of(scope.find_var(n)) for n in param_names
                       if scope.has_var(n)}
        feed_avals = {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                      for n, s, d in mb_feed_sig}

        def fwd_probe(params, feeds):
            env = dict(params)
            env.update(feeds)
            ctx = LowerCtx(program, block, env,
                           rng_key=jax.random.PRNGKey(0))
            for op in ops[:ann["n_fwd"]]:
                run_lowering(ctx, op)
            return [{n: env[n] for n in names} for names in iface_names]

        iface_avals = jax.eval_shape(fwd_probe, param_avals, feed_avals)

        # ---- carry packing: one fixed-size float32 vector ------------------
        layouts = []  # per boundary: [(name, shape, size, dtype)]
        sizes = []
        for b, avals in enumerate(iface_avals):
            lay = []
            total = 0
            for name in iface_names[b]:
                av = avals[name]
                if not jnp.issubdtype(av.dtype, jnp.floating):
                    raise NotImplementedError(
                        f"pipeline boundary var {name!r} has dtype "
                        f"{av.dtype}; only floating interfaces are supported")
                n_el = int(np.prod(av.shape)) if av.shape else 1
                lay.append((name, tuple(av.shape), n_el, av.dtype))
                total += n_el
            layouts.append(lay)
            sizes.append(total)
        K = max(sizes) if sizes else 1
        self._iface_elems = K

        def pack(b, env):
            if not layouts[b]:
                return jnp.zeros((K,), jnp.float32)
            parts = [env[name].astype(jnp.float32).reshape(-1)
                     for name, _, _, _ in layouts[b]]
            vec = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            pad = K - vec.shape[0]
            return jnp.pad(vec, (0, pad)) if pad else vec

        def unpack(b, vec):
            out = {}
            off = 0
            for name, shape, n_el, dtype in layouts[b]:
                out[name] = vec[off:off + n_el].reshape(shape).astype(dtype)
                off += n_el
            return out

        mesh = build_mesh((("pp", S),))
        self.mesh = mesh
        perm = [(i, (i + 1) % S) for i in range(S)]
        n_fwd = ann["n_fwd"]

        def per_rank(mutable_params, const_params, feeds, rng_key):
            stage = jax.lax.axis_index("pp")
            base_params = dict(const_params)
            base_params.update(mutable_params)
            split = {}
            for n, f in feeds.items():
                if n in self._batched_feeds:
                    split[n] = f.reshape((M, mb) + tuple(f.shape[1:]))
                else:
                    split[n] = f

            def loss_fn(train_params):
                params = dict(base_params)
                params.update(train_params)

                def tick(carry, t):
                    iface, loss_sum = carry
                    m = jnp.clip(t - stage, 0, M - 1)
                    feeds_mb = {
                        n: (jax.lax.dynamic_index_in_dim(f, m, 0,
                                                         keepdims=False)
                            if n in self._batched_feeds else f)
                        for n, f in split.items()
                    }

                    def make_branch(s):
                        lo, hi = stage_ranges[s]

                        def branch(vec):
                            env = dict(params)
                            env.update(feeds_mb)
                            if s > 0:
                                env.update(unpack(s - 1, vec))
                            ctx = LowerCtx(program, block, env,
                                           rng_key=rng_key)
                            for op in ops[lo:hi]:
                                run_lowering(ctx, op)
                            if s < S - 1:
                                return (pack(s, env),
                                        jnp.zeros((), jnp.float32))
                            loss = env[loss_name].astype(jnp.float32)
                            return (jnp.zeros((K,), jnp.float32),
                                    loss.reshape(()))

                        return branch

                    out, mb_loss = jax.lax.switch(
                        stage, [make_branch(s) for s in range(S)], iface)
                    valid = ((t - stage) >= 0) & ((t - stage) < M)
                    is_last = stage == S - 1
                    loss_sum = loss_sum + jnp.where(valid & is_last,
                                                    mb_loss, 0.0)
                    nxt = (jax.lax.ppermute(out, "pp", perm)
                           if S > 1 else out)
                    return (nxt, loss_sum), None

                carry0 = (jnp.zeros((K,), jnp.float32),
                          jnp.zeros((), jnp.float32))
                (_, loss_sum), _ = jax.lax.scan(
                    tick, carry0, jnp.arange(M + S - 1))
                # rank-LOCAL loss (only the last stage is nonzero): grads
                # must not differentiate through a psum — its shard_map
                # transpose re-psums the cotangent, inflating grads by S
                return loss_sum / M

            train_params = {n: mutable_params[n] for n in trainable
                            if n in mutable_params}
            local_loss, grads = jax.value_and_grad(loss_fn)(train_params)
            loss_val = jax.lax.psum(local_loss, "pp")
            grads = {n: jax.lax.psum(g, "pp") for n, g in grads.items()}

            # ---- optimizer tail: the Program's own update ops -------------
            env = dict(base_params)
            env.update({n: f for n, f in feeds.items()
                        if n not in self._batched_feeds})
            env[loss_name] = loss_val
            for n, g in grads.items():
                env[n + GRAD_SUFFIX] = g
            ctx = LowerCtx(program, block, env, rng_key=rng_key)
            for op in opt_ops:
                run_lowering(ctx, op)

            fetches = []
            for name in self.fetch_names:
                if name == loss_name:
                    fetches.append(jnp.atleast_1d(loss_val))
                elif name in env:
                    fetches.append(jnp.atleast_1d(env[name]))
                else:
                    raise NotImplementedError(
                        f"pipeline fetch {name!r}: only the loss, "
                        "persistables, and optimizer-phase outputs are "
                        "fetchable")
            new_state = {n: env[n] for n in self.written_names if n in env}
            return fetches, new_state

        from jax.sharding import PartitionSpec as P

        written = set(written_names)
        mutable_specs = {n: P() for n in param_names if n in written}
        const_specs = {n: P() for n in param_names if n not in written}
        feed_specs = {n: P() for n, _, _ in feed_sig}
        fetch_specs = [P() for _ in fetch_names]

        def _make_jit(produced_state_names):
            state_specs = {n: P() for n in produced_state_names}

            def wrapped_per_rank(mutable_params, const_params, feeds, key):
                fetches, new_state = per_rank(mutable_params, const_params,
                                              feeds, key)
                return fetches, {n: new_state[n]
                                 for n in produced_state_names}

            kwargs = dict(mesh=mesh,
                          in_specs=(mutable_specs, const_specs, feed_specs,
                                    P()),
                          out_specs=(fetch_specs, state_specs))
            try:
                w = _shard_map(wrapped_per_rank, **kwargs, check_vma=False)
            except TypeError:
                w = _shard_map(wrapped_per_rank, **kwargs, check_rep=False)
            return jax.jit(w, donate_argnums=(0,))

        # discover which written names the opt phase actually produces, via
        # an eval_shape of per_rank under a fake axis context: simplest is to
        # run eval_shape on the shard-mapped function itself
        # the opt-phase env starts from every scope persistable, so all
        # written names are bound; restrict to the ones present in the scope
        produced = [n for n in self.written_names if scope.has_var(n)]
        self._jitted = _make_jit(produced)
        self._produced = produced

    def __call__(self, scope, feed, rng_key):
        mutable, const = {}, {}
        written = set(self.written_names)
        for n in self.param_names:
            v = scope.find_var(n)
            if v is None:
                raise RuntimeError(
                    f"persistable var {n!r} is not initialized in scope — "
                    "run the startup program first")
            (mutable if n in written else const)[n] = v
        feeds = {n: feed[n] for n in self.feed_names}
        fetches, new_state = self._jitted(mutable, const, feeds, rng_key)
        for n, v in new_state.items():
            scope.set_var(n, v)
        return fetches
