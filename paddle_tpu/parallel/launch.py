"""Multi-process launcher — parity with python/paddle/distributed/launch.py
(:193 launch, utils.py:338-375 env contract): spawns one worker process per
device/host slot, sets the PADDLE_* env, and supervises the gang.

The reference's TrainerProc watch loop aborts the whole job on any failure;
this launcher is the elastic superset (ROADMAP item 4, docs/elastic.md):

- **Graceful shutdown**: a dying gang gets SIGTERM, a grace period to
  checkpoint-and-exit (workers install :func:`install_preemption_handler`),
  then SIGKILL.  The first failing child's exit code propagates (signal
  deaths map to the shell convention 128+N).
- **Preemption tolerance**: SIGTERM/SIGINT on the launcher is trapped and
  forwarded to the children, which checkpoint and exit cleanly; the
  launcher then returns 0 so an external scheduler sees a clean preemption.
- **Supervised restarts**: ``max_restarts > 0`` restarts the whole gang
  after a worker failure (collective jobs cannot survive a lone member —
  every rank restarts together and resumes from the latest committed
  checkpoint), with exponential backoff between attempts.  Restarts count
  into ``paddle_restarts_total{cause=hang|crash|preempt}`` through the
  PR 3 registry: a worker exiting with ``health.HANG_EXIT_CODE`` (its own
  hang watchdog fired) is ``hang``, an untrapped SIGTERM death is
  ``preempt``, and every other failure — any signal or nonzero exit — is
  ``crash``.
- **In-run health** (ISSUE 8, docs/health.md): ``hang_deadline_s`` /
  ``health_dir`` export the :mod:`.health` env contract to every worker
  (each installs a hang watchdog that stack-dumps and exits with the
  ``hang`` code when no dispatch progress lands inside the deadline), and
  the supervisor polls the shared heartbeat dir for stragglers —
  ``paddle_straggler_detected_total{rank}`` plus a rate-limited warning
  naming the slow rank.

On TPU the normal deployment is one process per HOST (all local chips in one
process), so --nproc_per_node defaults to 1; the per-GPU spawning of the
reference maps to per-host here.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from ..observability import flight as _flight
from ..observability import goodput as _goodput
from ..observability import metrics as _obs_metrics
from . import health as _health

_m_restarts = _obs_metrics.default_registry().counter(
    "paddle_restarts_total",
    "Supervised gang restarts by cause (hang, crash, preempt)",
    ("cause",))
_m_input_stalls = _obs_metrics.default_registry().counter(
    "paddle_input_stall_reports_total",
    "Input-stall reports surfaced by the supervisor, by rank", ("rank",))


def _poll_input_stall_reports(health_dir: str, seen: dict) -> list:
    """Surface workers' input-stall reports (docs/data.md): a stalled
    sharded stream writes ``input_stall.rank<R>.json`` into the shared
    health dir; the supervisor polls it alongside the straggler check so a
    slow/corrupt shard is visible at the JOB level, not just in one
    worker's log.  ``seen`` maps path -> last-surfaced mtime; returns the
    new reports."""
    import glob
    import json as _json

    out = []
    for path in sorted(glob.glob(
            os.path.join(health_dir, "input_stall.rank*.json"))):
        try:
            mtime = os.path.getmtime(path)
            if seen.get(path) == mtime:
                continue
            with open(path) as f:
                rep = _json.load(f)
        except (OSError, ValueError):
            continue
        seen[path] = mtime
        _m_input_stalls.labels(str(rep.get("rank", "?"))).inc()
        sys.stderr.write(
            f"launch: rank {rep.get('rank')} input stalled "
            f"{rep.get('waited_s')}s on shard {rep.get('shard')!r} "
            "(slow storage or a stuck decode worker — see docs/data.md "
            "runbook)\n")
        out.append(rep)
    return out


def get_cluster_endpoints(node_ips: List[str], nproc_per_node: int,
                          start_port: int = 6070) -> List[str]:
    eps = []
    for ip in node_ips:
        for i in range(nproc_per_node):
            eps.append(f"{ip}:{start_port + i}")
    return eps


# ---------------------------------------------------------------------------
# Worker-side helpers
# ---------------------------------------------------------------------------

class PreemptionSignal:
    """Process-wide preemption flag set by SIGTERM/SIGINT.  Training loops
    poll :attr:`triggered` (or :meth:`check`) at step boundaries, save a
    checkpoint, and exit cleanly — the launcher's grace period exists
    exactly for this."""

    def __init__(self):
        self.triggered = False
        self.signum: Optional[int] = None
        self._callbacks: List[Callable[[], None]] = []

    def check(self) -> bool:
        return self.triggered

    def reset(self) -> None:
        """Clear the flag (tests, or a loop that handled the preemption
        itself and decided to continue)."""
        self.triggered = False
        self.signum = None

    def add_callback(self, fn: Callable[[], None]) -> None:
        self._callbacks.append(fn)

    def _fire(self, signum):
        self.triggered = True
        self.signum = signum
        for fn in list(self._callbacks):
            try:
                fn()
            except Exception:
                pass


_preemption: Optional[PreemptionSignal] = None


def install_preemption_handler(
        signals=(signal.SIGTERM, signal.SIGINT)) -> PreemptionSignal:
    """Install (or return the already-installed) preemption trap.  Safe to
    call repeatedly; outside the main thread (where signal handlers cannot
    be installed) the returned flag simply never fires."""
    global _preemption
    if _preemption is not None:
        return _preemption
    sig = PreemptionSignal()

    def handler(signum, frame):
        sig._fire(signum)

    if threading.current_thread() is threading.main_thread():
        for s in signals:
            signal.signal(s, handler)
    _preemption = sig
    return sig


def preemption_signal() -> Optional[PreemptionSignal]:
    """The installed preemption trap, if any (None before install)."""
    return _preemption


def init_collective_with_retry(init_fn: Callable[[], None],
                               retries: int = 5, backoff_s: float = 0.5,
                               backoff_max_s: float = 8.0,
                               log=None) -> None:
    """Retry-with-backoff around collective/backend bring-up
    (``jax.distributed.initialize`` or a custom bootstrap): a slow-starting
    peer raises a connect error on the fast ranks — retrying with
    exponential backoff instead of failing the job lets the gang converge.
    Re-raises the last error after ``retries`` failed attempts."""
    delay = backoff_s
    for attempt in range(1, max(1, retries) + 1):
        try:
            init_fn()
            return
        except Exception as e:
            if attempt >= retries:
                raise
            if log is not None:
                log(f"collective init attempt {attempt}/{retries} failed "
                    f"({e!r}); retrying in {delay:.1f}s")
            time.sleep(delay)
            delay = min(delay * 2, backoff_max_s)


# ---------------------------------------------------------------------------
# Launcher / supervisor
# ---------------------------------------------------------------------------

def _exit_code(ret: int) -> int:
    """Popen returncode -> propagated exit code (signal death N -> 128+N,
    the shell convention)."""
    return 128 - ret if ret < 0 else ret


def _restart_cause(ret: int) -> str:
    """Popen returncode -> paddle_restarts_total cause label.

    ``hang``: the worker's own watchdog declared it stuck and exited with
    the distinct :data:`health.HANG_EXIT_CODE`.  ``preempt``: an untrapped
    SIGTERM death (an external scheduler pulled the node before the worker
    could checkpoint — a trapped preemption exits 0 and never restarts).
    Everything else — SIGKILL/segfault/any nonzero exit — is ``crash``.
    """
    if ret == _health.HANG_EXIT_CODE:
        return "hang"
    if ret < 0:
        return "preempt" if -ret == signal.SIGTERM else "crash"
    return "crash"


def _stop_gang(procs, grace_period_s: float, sig=signal.SIGTERM):
    """Graceful shutdown: ``sig`` to every live child, wait up to the grace
    period for them to checkpoint-and-exit, then SIGKILL stragglers."""
    for _, p, _ in procs:
        if p.poll() is None:
            try:
                p.send_signal(sig)
            except OSError:
                pass
    deadline = time.time() + max(0.0, grace_period_s)
    for _, p, _ in procs:
        if p.poll() is not None:
            continue
        remaining = deadline - time.time()
        try:
            p.wait(timeout=max(0.1, remaining))
        except subprocess.TimeoutExpired:
            p.kill()
    for _, p, _ in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


def _assemble_blame(flight_dir: str, attempt: int) -> Optional[dict]:
    """Run the blame engine (tools/flight_assemble.py) over the dead
    incarnation's flight files: write ``blame.attempt<K>.json`` next to
    them (the restart record), publish ``paddle_blamed_rank`` /
    ``paddle_step_skew_ms``, and return the verdict.  Forensics must
    never fail the restart — any error returns None."""
    try:
        import importlib.util
        import json as _json

        tool = os.path.join(
            os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            "tools", "flight_assemble.py")
        spec = importlib.util.spec_from_file_location(
            "paddle_flight_assemble", tool)
        if spec is None or spec.loader is None:
            return None
        fa = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(fa)
        report = fa.assemble_dir(flight_dir, attempt=attempt)
        verdict = report.get("verdict") or {}
        out = os.path.join(flight_dir, f"blame.attempt{attempt}.json")
        with open(out, "w") as f:
            _json.dump(report, f, indent=1)
        blamed = verdict.get("blamed_ranks") or []
        _flight.note_blame(blamed[0] if blamed else None,
                           verdict.get("step_skew_ms"))
        if blamed:
            sys.stderr.write(
                f"launch: blame verdict (attempt {attempt}): rank(s) "
                f"{blamed} {verdict.get('blame_mode')} at collective seq "
                f"{verdict.get('missed_seq')}"
                + (f" [{verdict['missed_name']}]"
                   if verdict.get("missed_name") else "")
                + f" — {out}\n")
        else:
            sys.stderr.write(
                f"launch: blame assembly (attempt {attempt}): no rank "
                f"blamed — {out}\n")
        return verdict
    except Exception as e:
        sys.stderr.write(f"launch: blame assembly failed: {e}\n")
        return None


def launch(training_script: str, script_args: Optional[List[str]] = None,
           cluster_node_ips: str = "127.0.0.1", node_ip: str = "127.0.0.1",
           nproc_per_node: int = 1, started_port: int = 6070,
           log_dir: Optional[str] = None, perf_flags: bool = True,
           max_restarts: int = 0, restart_backoff_s: float = 1.0,
           restart_backoff_max_s: float = 30.0,
           grace_period_s: float = 15.0,
           hang_deadline_s: Optional[float] = None,
           health_dir: Optional[str] = None,
           straggler_ratio: float = 2.0,
           straggler_warn_cooldown_s: float = 30.0,
           goodput_dir: Optional[str] = None,
           flight_dir: Optional[str] = None) -> int:
    """Spawn and supervise the worker gang; returns the job's exit code
    (0 on success or clean preemption; otherwise the FIRST failing child's
    exit code, with signal deaths mapped to 128+N).

    ``hang_deadline_s``/``health_dir`` arm the in-run health layer
    (docs/health.md): workers install a hang watchdog from the exported
    env contract, write per-rank heartbeats into ``health_dir``, and the
    supervisor polls that dir for stragglers (EWMA step time beyond
    ``straggler_ratio`` x the gang median).

    ``goodput_dir`` (defaults to ``<log_dir>/goodput``) arms gang-wide
    wall-clock accounting (docs/observability.md): workers export their
    per-rank goodput ledgers + Prometheus textfiles there via the
    ``PADDLE_GOODPUT_DIR`` env contract, the supervisor times every
    failure-detect -> respawn window as ``restart_downtime``, and at job
    end it merges everything into ``GOODPUT.json`` (gang goodput
    fraction) plus one merged gang exposition.

    ``flight_dir`` (defaults to ``<log_dir>/flight``, or
    ``<health_dir>/flight`` without a log dir) arms the per-rank flight
    recorder (ISSUE 19, docs/health.md): workers mirror their event
    rings to crash-surviving sidecars via ``PADDLE_FLIGHT_DIR``, and on
    a hang-cause restart the supervisor runs the blame engine
    (tools/flight_assemble.py) over the dead incarnation's files,
    writes ``blame.attempt<K>.json`` next to them, and publishes the
    ``paddle_blamed_rank`` / ``paddle_step_skew_ms`` metric pair.
    """
    from ..sysconfig import tpu_perf_flags

    node_ips = [ip.strip() for ip in cluster_node_ips.split(",")]
    endpoints = get_cluster_endpoints(node_ips, nproc_per_node, started_port)
    node_rank = node_ips.index(node_ip)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    if health_dir is None and (hang_deadline_s is not None) and log_dir:
        health_dir = os.path.join(log_dir, "health")
    if health_dir:
        os.makedirs(health_dir, exist_ok=True)
    if goodput_dir is None and log_dir:
        goodput_dir = os.path.join(log_dir, "goodput")
    if goodput_dir:
        os.makedirs(goodput_dir, exist_ok=True)
    if flight_dir is None:
        if log_dir:
            flight_dir = os.path.join(log_dir, "flight")
        elif health_dir:
            flight_dir = os.path.join(health_dir, "flight")
    if flight_dir:
        os.makedirs(flight_dir, exist_ok=True)
    straggler_mon = (_health.StragglerMonitor(
        health_dir, ratio=straggler_ratio,
        warn_cooldown_s=straggler_warn_cooldown_s)
        if health_dir else None)

    def spawn_gang(attempt: int):
        procs = []
        for local_rank in range(nproc_per_node):
            rank = node_rank * nproc_per_node + local_rank
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(len(endpoints)),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
                "PADDLE_RESTART_ATTEMPT": str(attempt),
            })
            # health env contract: workers self-install the hang watchdog
            # and heartbeat writer (health.maybe_install_from_env)
            if hang_deadline_s is not None:
                env[_health.ENV_DEADLINE] = str(float(hang_deadline_s))
            if health_dir:
                env[_health.ENV_DIR] = health_dir
            if goodput_dir:
                # goodput env contract: workers export their per-rank
                # ledger + exposition here at run-window exit
                env[_goodput.ENV_DIR] = goodput_dir
            if flight_dir:
                # flight env contract: workers sidecar their event
                # rings here (flight.maybe_attach_from_env)
                env[_flight.ENV_DIR] = flight_dir
            if perf_flags:
                # comm/compute-overlap preset into each worker's XLA_FLAGS
                # BEFORE its backend init (no-op unless the worker env
                # targets a TPU — the platform gate in sysconfig)
                tpu_perf_flags(env=env)
            # append mode: a restarted worker's log continues the file
            out = (open(os.path.join(log_dir, f"worker.{rank}.log"), "a")
                   if log_dir else None)
            p = subprocess.Popen(
                [sys.executable, training_script] + list(script_args or []),
                env=env, stdout=out,
                stderr=subprocess.STDOUT if out else None,
            )
            procs.append((rank, p, out))
        return procs

    # preemption trap: forward to children, give them the grace period to
    # checkpoint, then return cleanly (main thread only — signal handlers
    # cannot install elsewhere, e.g. under pytest workers calling us from
    # a thread)
    preempted = {"flag": False}
    old_handlers = {}
    in_main = threading.current_thread() is threading.main_thread()
    if in_main:
        def _trap(signum, frame):
            preempted["flag"] = True
        for s in (signal.SIGTERM, signal.SIGINT):
            old_handlers[s] = signal.signal(s, _trap)

    all_procs: List = []
    exit_code = 0
    restarts = 0
    restart_downtime_s = 0.0
    backoff = restart_backoff_s
    last_straggler_poll = 0.0
    stall_seen: dict = {}
    try:
        procs = spawn_gang(0)
        all_procs = list(procs)
        while True:
            if preempted["flag"]:
                sys.stderr.write("launch: preemption signal — forwarding "
                                 "SIGTERM to workers\n")
                _stop_gang(procs, grace_period_s)
                # a clean preemption (children checkpointed and exited 0)
                # is a clean job exit; a child that died badly propagates
                codes = [_exit_code(p.poll()) for _, p, _ in procs
                         if p.poll() not in (0, None)]
                exit_code = codes[0] if codes else 0
                break
            alive, failed = [], None
            for rank, p, out in procs:
                ret = p.poll()
                if ret is None:
                    alive.append((rank, p, out))
                elif ret != 0 and failed is None:
                    failed = (rank, ret)
            if failed is not None:
                rank, ret = failed
                t_fail = time.monotonic()
                code = _exit_code(ret)
                cause = _restart_cause(ret)
                sys.stderr.write(
                    f"launch: worker {rank} exited with {ret} "
                    f"(code {code}, cause {cause})\n")
                _stop_gang(procs, grace_period_s)
                if cause == "hang" and flight_dir:
                    # gang is quiesced: every surviving sidecar is
                    # flushed — name the rank that wedged the gang and
                    # the collective seq it missed (restart record)
                    _assemble_blame(flight_dir, attempt=restarts)
                if restarts < max_restarts:
                    restarts += 1
                    _m_restarts.labels(cause).inc()
                    sys.stderr.write(
                        f"launch: restarting gang (attempt {restarts}/"
                        f"{max_restarts}) in {backoff:.1f}s\n")
                    time.sleep(backoff)
                    backoff = min(backoff * 2, restart_backoff_max_s)
                    for _, _, out in procs:
                        if out:
                            out.close()
                    procs = spawn_gang(restarts)
                    all_procs.extend(procs)
                    # failure detection -> gang respawned: the whole gang
                    # was idle for this window (goodput restart_downtime,
                    # attributed at the job level — a SIGKILL'd worker
                    # cannot report its own death)
                    dt = time.monotonic() - t_fail
                    restart_downtime_s += dt
                    _goodput.attribute("restart_downtime", dt)
                    continue
                exit_code = code
                break
            procs = alive
            if not procs:
                break       # every worker exited 0
            if health_dir is not None and \
                    time.monotonic() - last_straggler_poll >= 2.0:
                last_straggler_poll = time.monotonic()
                if straggler_mon is not None:
                    straggler_mon.poll()
                _poll_input_stall_reports(health_dir, stall_seen)
            time.sleep(0.2)
    finally:
        if in_main:
            for s, h in old_handlers.items():
                signal.signal(s, h)
        # terminate, then reap every child and close its log handle so a
        # failed job leaves no zombies and no buffered log tail unflushed
        for _, p, out in all_procs:
            if p.poll() is None:
                p.terminate()
        for _, p, out in all_procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
            if out and not out.closed:
                out.close()
    if goodput_dir:
        # gang aggregation: merge the per-rank ledgers + expositions the
        # workers exported, charge the supervisor's restart-downtime
        # windows, and write GOODPUT.json with the gang goodput fraction
        try:
            path = _goodput.write_gang_report(
                goodput_dir, restart_downtime_s=restart_downtime_s,
                nranks=len(endpoints),
                extra={"exit_code": exit_code, "restarts": restarts})
            if path:
                sys.stderr.write(f"launch: gang goodput report: {path}\n")
        except Exception as e:   # accounting must never fail the job
            sys.stderr.write(f"launch: goodput aggregation failed: {e}\n")
    return exit_code


def main():  # CLI: python -m paddle_tpu.parallel.launch script.py args...
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster_node_ips", default="127.0.0.1")
    ap.add_argument("--node_ip", default="127.0.0.1")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--started_port", type=int, default=6070)
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--max_restarts", type=int, default=0,
                    help="restart the gang up to N times after a worker "
                         "failure (exponential backoff)")
    ap.add_argument("--restart_backoff", type=float, default=1.0)
    ap.add_argument("--grace_period", type=float, default=15.0,
                    help="seconds between SIGTERM and SIGKILL at shutdown")
    ap.add_argument("--hang_deadline", type=float, default=None,
                    help="arm each worker's hang watchdog: no dispatch "
                         "progress for this many seconds dumps stacks and "
                         "restarts the gang with cause=hang")
    ap.add_argument("--health_dir", default=None,
                    help="shared dir for hang dumps + per-rank heartbeats "
                         "(default: <log_dir>/health when the watchdog is "
                         "armed)")
    ap.add_argument("--straggler_ratio", type=float, default=2.0,
                    help="flag ranks whose step-time EWMA exceeds this "
                         "multiple of the gang median")
    ap.add_argument("--goodput_dir", default=None,
                    help="shared dir for per-rank goodput ledgers; the "
                         "supervisor merges them (plus its restart-"
                         "downtime windows) into GOODPUT.json (default: "
                         "<log_dir>/goodput)")
    ap.add_argument("--flight_dir", default=None,
                    help="shared dir for per-rank flight-recorder "
                         "sidecars; on a hang-cause restart the "
                         "supervisor writes blame.attempt<K>.json here "
                         "(default: <log_dir>/flight)")
    ap.add_argument("--no_perf_flags", action="store_true",
                    help="skip the sysconfig.tpu_perf_flags XLA preset")
    ap.add_argument("training_script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    sys.exit(launch(args.training_script, args.script_args,
                    args.cluster_node_ips, args.node_ip, args.nproc_per_node,
                    args.started_port, args.log_dir,
                    perf_flags=not args.no_perf_flags,
                    max_restarts=args.max_restarts,
                    restart_backoff_s=args.restart_backoff,
                    grace_period_s=args.grace_period,
                    hang_deadline_s=args.hang_deadline,
                    health_dir=args.health_dir,
                    straggler_ratio=args.straggler_ratio,
                    goodput_dir=args.goodput_dir,
                    flight_dir=args.flight_dir))


if __name__ == "__main__":
    main()
