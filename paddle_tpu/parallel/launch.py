"""Multi-process launcher — parity with python/paddle/distributed/launch.py
(:193 launch, utils.py:338-375 env contract): spawns one worker process per
device/host slot, sets the PADDLE_* env, watches children and aborts the job
on any failure (TrainerProc watch loop parity).

On TPU the normal deployment is one process per HOST (all local chips in one
process), so --nproc_per_node defaults to 1; the per-GPU spawning of the
reference maps to per-host here.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


def get_cluster_endpoints(node_ips: List[str], nproc_per_node: int,
                          start_port: int = 6070) -> List[str]:
    eps = []
    for ip in node_ips:
        for i in range(nproc_per_node):
            eps.append(f"{ip}:{start_port + i}")
    return eps


def launch(training_script: str, script_args: Optional[List[str]] = None,
           cluster_node_ips: str = "127.0.0.1", node_ip: str = "127.0.0.1",
           nproc_per_node: int = 1, started_port: int = 6070,
           log_dir: Optional[str] = None, perf_flags: bool = True) -> int:
    from ..sysconfig import tpu_perf_flags

    node_ips = [ip.strip() for ip in cluster_node_ips.split(",")]
    endpoints = get_cluster_endpoints(node_ips, nproc_per_node, started_port)
    node_rank = node_ips.index(node_ip)
    procs = []
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for local_rank in range(nproc_per_node):
        rank = node_rank * nproc_per_node + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(len(endpoints)),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        })
        if perf_flags:
            # comm/compute-overlap preset into each worker's XLA_FLAGS
            # BEFORE its backend init (no-op unless the worker env targets
            # a TPU — the platform gate in sysconfig.tpu_perf_flags)
            tpu_perf_flags(env=env)
        out = (open(os.path.join(log_dir, f"worker.{rank}.log"), "w")
               if log_dir else None)
        p = subprocess.Popen(
            [sys.executable, training_script] + list(script_args or []),
            env=env, stdout=out, stderr=subprocess.STDOUT if out else None,
        )
        procs.append((rank, p, out))
    all_procs = list(procs)

    # watch loop: abort the whole job if any worker dies (parity with
    # distributed/utils.py TrainerProc watch)
    exit_code = 0
    try:
        while procs:
            alive = []
            for rank, p, out in procs:
                ret = p.poll()
                if ret is None:
                    alive.append((rank, p, out))
                elif ret != 0:
                    exit_code = ret
                    sys.stderr.write(f"worker {rank} exited with {ret}; "
                                     "terminating job\n")
                    for _, q, _ in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    alive = []
                    break
            procs = alive
            if procs:
                time.sleep(1)
    finally:
        # terminate, then reap every child and close its log handle so a
        # failed job leaves no zombies and no buffered log tail unflushed
        for _, p, out in all_procs:
            if p.poll() is None:
                p.terminate()
        for _, p, out in all_procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
            if out:
                out.close()
    return exit_code


def main():  # CLI: python -m paddle_tpu.parallel.launch script.py args...
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--cluster_node_ips", default="127.0.0.1")
    ap.add_argument("--node_ip", default="127.0.0.1")
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--started_port", type=int, default=6070)
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--no_perf_flags", action="store_true",
                    help="skip the sysconfig.tpu_perf_flags XLA preset")
    ap.add_argument("training_script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    sys.exit(launch(args.training_script, args.script_args,
                    args.cluster_node_ips, args.node_ip, args.nproc_per_node,
                    args.started_port, args.log_dir,
                    perf_flags=not args.no_perf_flags))


if __name__ == "__main__":
    main()
