"""Distributed & parallelism — the TPU-native replacement for the reference's
ParallelExecutor/NCCL stack (SURVEY.md §2.3): device meshes + GSPMD shardings
+ shard_map collectives instead of SSA graphs + rings."""
from .mesh import MeshConfig, build_mesh, current_mesh, mesh_guard  # noqa: F401
from . import comm_opt  # noqa: F401
from . import env  # noqa: F401
from . import health  # noqa: F401
from . import remat  # noqa: F401
from .comm_opt import CommConfig  # noqa: F401
from .launch import (  # noqa: F401
    init_collective_with_retry, install_preemption_handler, launch,
    preemption_signal,
)
from .checkpoint import (  # noqa: F401
    CheckpointCorruptError, CheckpointError, ElasticCheckpointer,
    MeshMismatchError, ShardedCheckpointer, abstract_for_mesh,
    abstract_like, check_mesh_compatible, reshard_flat,
    restore_train_state,
)
