"""Crash-safe sharded checkpointing for the parallel training path.

The reference checkpoints through save/load *ops* on host tensors
(fluid/io.py:598,902 save_persistables; operators/save_op.cc) and PS-mode
checkpoint_notify — single-host, fully-replicated formats with no notion of
a partially written save.  Elastic TPU training needs more (ROADMAP item 4,
docs/elastic.md):

- **Atomic commit**: a step directory is only a restore candidate once its
  ``COMMIT`` marker lands (written last, via tmp+rename).  A worker killed
  mid-save leaves an uncommitted directory that is never selected as
  "latest" and is garbage-collected by the next save.
- **Integrity manifest**: ``manifest.json`` records per-leaf byte sizes and
  crc32 checksums plus the mesh shape and the comm_opt bucket layout of
  PR 5's dp-sharded flat moment buffers.  Restore verifies every leaf and
  raises :class:`CheckpointCorruptError` (naming the file and checksums) on
  a truncated or bit-flipped shard.
- **Reshard-on-restore**: a save at dp=8 restores at dp=4 or dp=16.
  Replicated/spec-sharded leaves are stored as full arrays and re-placed
  under the target sharding; the dp-sharded flat optimizer megabuffers are
  resharded bit-exactly through :func:`reshard_flat` (unpack the source
  bucket layout to per-leaf moments, repack into the target layout — pure
  data movement, following the portable-collective redistribution approach
  of arXiv:2112.01075).
- **No-orbax fallback**: :class:`ElasticCheckpointer` is pure
  numpy/filesystem (raw ``.bin`` leaves + JSON manifest) and fully covers
  replicated and single-process-addressable state; the orbax-backed
  :class:`ShardedCheckpointer` remains for true multi-host OCDBT shards and
  now shares the committed-step selection and retention rules.

Save metrics ride the PR 3 registry: ``paddle_checkpoint_save_ms`` and
``paddle_checkpoint_bytes_total`` (tools/metrics_check.py gates both).
"""
from __future__ import annotations

import json
import os
import queue
import re
import shutil
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax

from ..observability import goodput as _goodput
from ..observability import metrics as _obs_metrics
from ..observability import spans as _spans

_gp = _goodput.ledger()

__all__ = [
    "CheckpointError", "CheckpointCorruptError", "MeshMismatchError",
    "check_mesh_compatible",
    "ElasticCheckpointer", "ShardedCheckpointer",
    "abstract_for_mesh", "abstract_like",
    "serialize_layout", "deserialize_layout", "reshard_flat",
    "restore_train_state", "build_restore_broadcast_program",
]

MANIFEST_NAME = "manifest.json"
COMMIT_NAME = "COMMIT"
FORMAT = "paddle_tpu.elastic.v1"
_STEP_RX = re.compile(r"^step_(\d+)$")

_REG = _obs_metrics.default_registry()
_m_save_ms = _REG.histogram(
    "paddle_checkpoint_save_ms",
    "Wall time of one checkpoint save (host snapshot + write + commit)")
_m_bytes = _REG.counter(
    "paddle_checkpoint_bytes_total",
    "Bytes of checkpoint leaf data committed to disk")


class CheckpointError(RuntimeError):
    pass


class CheckpointCorruptError(CheckpointError):
    """A committed checkpoint failed integrity verification."""


class MeshMismatchError(CheckpointError):
    """The manifest's mesh/sharding metadata contradicts the live mesh
    and no reshard path covers the difference — restoring would place
    every shard wrong silently (ISSUE 12; the dynamic twin of the
    sharding checker's ``mesh_mismatch_at_restore`` finding)."""


def check_mesh_compatible(saved_mesh: Optional[Dict[str, int]],
                          live_mesh: Optional[Dict[str, int]], *,
                          reshardable: bool = False,
                          where: str = "checkpoint") -> None:
    """Raise :class:`MeshMismatchError` when ``saved_mesh`` (manifest
    metadata) cannot restore onto ``live_mesh``.

    Same axes + same sizes always pass; same axis NAMES with different
    sizes pass only when the caller has a reshard path
    (``reshardable=True`` — the flat-moment bucket relayout of
    :func:`reshard_flat`); different axis sets never pass. ``None`` on
    either side skips the check (older manifests / callers that don't
    know their mesh)."""
    if not saved_mesh or not live_mesh:
        return
    saved = {str(k): int(v) for k, v in dict(saved_mesh).items()}
    live = {str(k): int(v) for k, v in dict(live_mesh).items()}
    if saved == live:
        return
    if set(saved) == set(live) and reshardable:
        return
    detail = ("axis sets differ" if set(saved) != set(live) else
              "axis sizes differ and no reshardable layout was provided")
    raise MeshMismatchError(
        f"{where}: saved mesh {saved} does not match the live mesh "
        f"{live} ({detail}) — restoring would silently misplace shards. "
        "Restore onto the saved topology, or provide the source+target "
        "bucket layouts for the dp reshard path (docs/elastic.md, "
        "docs/sharding.md).")


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _leaf_key(path) -> str:
    return jax.tree_util.keystr(path)


_KEYSTR_RX = re.compile(r"\['((?:[^'\\]|\\.)*)'\]")


def _unflatten_keystrs(by_key: Dict[str, np.ndarray]):
    """{keystr: arr} -> the original nested-dict structure, when every
    keypath is a pure dict path ("['a']['b']"); otherwise the flat dict
    unchanged (list/attr paths have no unambiguous reconstruction)."""
    parsed = []
    for key, arr in by_key.items():
        segs = _KEYSTR_RX.findall(key)
        if "".join(f"['{s}']" for s in segs) != key:
            return dict(by_key)
        parsed.append((segs, arr))
    out: Dict[str, Any] = {}
    for segs, arr in parsed:
        cur = out
        for s in segs[:-1]:
            cur = cur.setdefault(s, {})
            if not isinstance(cur, dict):
                return dict(by_key)
        cur[segs[-1]] = arr
    return out


def _to_host(x) -> np.ndarray:
    # the save-time snapshot point: device arrays copy to host here; host
    # numpy arrays are copied too so a caller mutating its buffer cannot
    # corrupt an in-flight async write
    arr = np.asarray(x)
    if arr.dtype == object:
        raise CheckpointError(f"cannot checkpoint object-dtype leaf {arr!r}")
    if arr is x or isinstance(x, np.ndarray):
        arr = arr.copy()
    return arr


# ---------------------------------------------------------------------------
# Bucket-layout (de)serialization + bit-exact flat-moment resharding
# ---------------------------------------------------------------------------

def serialize_layout(layout, repl: int = 1) -> dict:
    """comm_opt.BucketLayout -> JSON-able manifest entry.  ``repl`` is the
    non-dp replication factor of the flat buffer (pp*tp: init_sharded lays
    the flat moments out sharded over EVERY mesh axis, so each dp shard
    appears pp*tp times in the addressable global vector)."""
    return {
        "ranks": int(layout.ranks),
        "repl": int(repl),
        "total_len": int(layout.total_len),
        "buckets": [
            {"dtype": b.dtype, "size": int(b.size), "pad": int(b.pad),
             "entries": [[int(i), list(map(int, shape)), int(n)]
                         for i, shape, n in b.entries]}
            for b in layout.buckets
        ],
    }


def deserialize_layout(d: dict):
    from .comm_opt import Bucket, BucketLayout

    buckets = tuple(
        Bucket(dtype=b["dtype"],
               entries=tuple((int(i), tuple(shape), int(n))
                             for i, shape, n in b["entries"]),
               size=int(b["size"]), pad=int(b["pad"]))
        for b in d["buckets"])
    return BucketLayout(buckets=buckets, ranks=int(d["ranks"]),
                        total_len=int(d["total_len"])), int(d.get("repl", 1))


def _layout_leaf_numels(layout) -> Dict[int, int]:
    return {idx: numel for b in layout.buckets
            for idx, _shape, numel in b.entries}


def reshard_flat(vec: np.ndarray, src_layout, dst_layout,
                 src_repl: int = 1, dst_repl: int = 1) -> np.ndarray:
    """Reshard a flat dp-sharded optimizer megabuffer between bucket
    layouts (dp=8 save -> dp=4 restore).  Pure data movement: unpack the
    source layout to per-leaf vectors, repack into the destination layout
    (destination pad regions are zeros — pad moments are exactly zero by
    construction, their gradients are the bucket zero-padding).  Bit-exact
    for any dtype; raises when the two layouts disagree on the leaf set.
    """
    src_nums = _layout_leaf_numels(src_layout)
    dst_nums = _layout_leaf_numels(dst_layout)
    if src_nums != dst_nums:
        raise CheckpointError(
            "cannot reshard: bucket layouts cover different leaf sets "
            f"(src {len(src_nums)} leaves / {sum(src_nums.values())} elems, "
            f"dst {len(dst_nums)} leaves / {sum(dst_nums.values())} elems)")
    vec = np.asarray(vec).reshape(-1)
    expect = src_layout.ranks * src_repl * src_layout.shard_len
    if vec.size != expect:
        raise CheckpointError(
            f"flat buffer length {vec.size} does not match source layout "
            f"(ranks={src_layout.ranks} repl={src_repl} "
            f"shard_len={src_layout.shard_len}; expected {expect})")

    # strip replication: each dp shard appears src_repl times back-to-back
    sl = src_layout.shard_len
    shards = [vec[d * src_repl * sl: d * src_repl * sl + sl]
              for d in range(src_layout.ranks)]
    flat_src = np.concatenate(shards) if len(shards) > 1 else shards[0]

    leaves: Dict[int, np.ndarray] = {}
    off = 0
    for b in src_layout.buckets:
        for idx, _shape, numel in b.entries:
            leaves[idx] = flat_src[off:off + numel]
            off += numel
        off += b.pad

    parts: List[np.ndarray] = []
    for b in dst_layout.buckets:
        for idx, _shape, numel in b.entries:
            parts.append(leaves[idx])
        if b.pad:
            parts.append(np.zeros((b.pad,), vec.dtype))
    flat_dst = np.concatenate(parts) if len(parts) > 1 else parts[0]

    dl = dst_layout.shard_len
    out = [np.tile(flat_dst[d * dl:(d + 1) * dl], dst_repl)
           for d in range(dst_layout.ranks)]
    return np.concatenate(out) if len(out) > 1 else out[0]


# ---------------------------------------------------------------------------
# ElasticCheckpointer: crash-safe numpy store (the no-orbax path)
# ---------------------------------------------------------------------------

class ElasticCheckpointer:
    """Crash-safe checkpoint store: raw per-leaf ``.bin`` files + integrity
    manifest + atomic ``COMMIT`` marker.

    ``save`` host-snapshots the state synchronously (device->host copy, so
    later donations cannot corrupt the write) and performs the file I/O on
    a background thread when ``use_async`` — the write overlaps the next
    training steps; ``wait()`` (or the next save / restore) joins it.
    ``keep_last=N`` retains the N newest committed steps and garbage-
    collects older ones plus any uncommitted debris.
    """

    def __init__(self, dirname: str, use_async: bool = True,
                 keep_last: Optional[int] = None):
        self.dirname = os.path.abspath(str(dirname))
        os.makedirs(self.dirname, exist_ok=True)
        self.keep_last = keep_last
        self._use_async = use_async
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._inflight: set = set()
        self._lock = threading.Lock()

    # -- paths / bookkeeping ------------------------------------------------

    def _path(self, step: int) -> str:
        return os.path.join(self.dirname, f"step_{int(step):08d}")

    def all_steps(self) -> List[int]:
        """Committed steps only — a directory without its COMMIT marker
        (mid-save kill) or without a manifest is never a candidate."""
        if not os.path.isdir(self.dirname):
            return []
        out = []
        for name in os.listdir(self.dirname):
            m = _STEP_RX.match(name)
            if not m:
                continue
            d = os.path.join(self.dirname, name)
            if os.path.exists(os.path.join(d, COMMIT_NAME)) and \
                    os.path.exists(os.path.join(d, MANIFEST_NAME)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_valid_step(self) -> Optional[int]:
        """Newest committed step that also passes integrity verification
        (sizes + crc32) — the restore target a supervisor restart uses."""
        self.wait()
        for step in reversed(self.all_steps()):
            if not self.verify(step):
                return step
        return None

    def manifest(self, step: int) -> dict:
        path = os.path.join(self._path(step), MANIFEST_NAME)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                f"checkpoint step {step}: unreadable manifest {path}: {e}")

    def verify(self, step: int) -> List[str]:
        """Integrity-check one committed step; returns a list of problems
        (empty == valid), each naming the offending file."""
        problems: List[str] = []
        d = self._path(step)
        try:
            man = self.manifest(step)
        except CheckpointCorruptError as e:
            return [str(e)]
        for leaf in man.get("leaves", []):
            path = os.path.join(d, leaf["file"])
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError as e:
                problems.append(f"{leaf['file']}: unreadable ({e})")
                continue
            if len(data) != leaf["bytes"]:
                problems.append(
                    f"{leaf['file']}: truncated — {len(data)} bytes on disk "
                    f"vs {leaf['bytes']} in manifest")
                continue
            crc = zlib.crc32(data)
            if crc != leaf["crc32"]:
                problems.append(
                    f"{leaf['file']}: checksum mismatch — crc32 {crc} on "
                    f"disk vs {leaf['crc32']} in manifest")
        return problems

    # -- save ---------------------------------------------------------------

    def save(self, step: int, state: Any, *, mesh: Optional[dict] = None,
             layout=None, layout_repl: int = 1,
             data_state: Optional[dict] = None,
             extra: Optional[dict] = None,
             keep_last: Optional[int] = None) -> str:
        """Snapshot ``state`` (a pytree) for ``step``.  ``mesh`` is a
        {axis: size} dict, ``layout`` the comm_opt BucketLayout of flat
        dp-sharded moment buffers (with ``layout_repl`` = pp*tp),
        ``data_state`` the dataset resume position — ``{"epoch",
        "offset"}``, plus an optional ``"stream"`` entry carrying a
        sharded stream's ``StreamState.to_dict()`` (shard-list hash,
        per-shard offsets, epoch, rng seed — docs/data.md) so a restart
        seeks the input instead of replaying it.
        Returns the step directory path (commit may still be in flight when
        async — ``wait()`` joins it)."""
        self._raise_pending()
        if data_state is not None:
            # fail at save time, in the caller's frame — an unserializable
            # resume token surfacing as an async-writer error at the NEXT
            # save would point at the wrong step
            try:
                json.dumps(data_state)
            except (TypeError, ValueError) as e:
                raise CheckpointError(
                    f"data_state for step {step} is not JSON-serializable "
                    f"({e}); stream states must be plain dicts "
                    "(StreamState.to_dict())") from e
        t0 = time.perf_counter_ns()
        # the synchronous share of a save (flatten + device->host snapshot)
        # is main-thread wall-clock; the async write overlaps the next
        # steps and is NOT charged to the ledger
        span_ctx = None
        with _gp.timer("checkpoint_save"), \
                _spans.span("checkpoint/save",
                            attrs={"step": int(step)}) as _sp:
            flat, _treedef = jax.tree_util.tree_flatten_with_path(state)
            # synchronous device->host snapshot: the background write then
            # holds plain numpy buffers that later donations cannot touch
            leaves = [(_leaf_key(path), _to_host(x)) for path, x in flat]
            # the writer thread's spans parent to THIS save span
            span_ctx = _spans.current_context()
        man: Dict[str, Any] = {
            "format": FORMAT, "step": int(step),
            "time": time.time(),
            "mesh": dict(mesh) if mesh else None,
            "layout": (serialize_layout(layout, layout_repl)
                       if layout is not None else None),
            "data": dict(data_state) if data_state else None,
            "extra": dict(extra) if extra else None,
        }
        keep = self.keep_last if keep_last is None else keep_last
        with self._lock:
            self._inflight.add(int(step))
        if self._use_async:
            self._ensure_thread()
            self._queue.put((step, leaves, man, keep, t0, span_ctx))
        else:
            with _gp.timer("checkpoint_save"):
                self._write(step, leaves, man, keep, t0, span_ctx)
        return self._path(step)

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._drain, name="elastic-ckpt-writer", daemon=True)
            self._thread.start()

    def _drain(self):
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._write(*job)
            except BaseException as e:  # surfaced by wait()/next save
                self._error = e
            finally:
                self._queue.task_done()

    def _write(self, step, leaves, man, keep, t0, span_ctx=None):
        with _spans.default_tracer().context(span_ctx), \
                _spans.span("checkpoint/write",
                            attrs={"step": int(step)}):
            self._write_inner(step, leaves, man, keep, t0)

    def _write_inner(self, step, leaves, man, keep, t0):
        d = self._path(step)
        # a re-save of the same step replaces any (necessarily partial or
        # stale) previous attempt
        if os.path.exists(d):
            shutil.rmtree(d, ignore_errors=True)
        os.makedirs(os.path.join(d, "leaves"), exist_ok=True)
        total = 0
        man_leaves = []
        for i, (key, arr) in enumerate(leaves):
            rel = os.path.join("leaves", f"leaf_{i}.bin")
            data = arr.tobytes()
            _atomic_write(os.path.join(d, rel), data)
            man_leaves.append({
                "key": key, "file": rel, "shape": list(arr.shape),
                "dtype": str(arr.dtype), "bytes": len(data),
                "crc32": zlib.crc32(data),
            })
            total += len(data)
        man = dict(man, leaves=man_leaves, total_bytes=total)
        _atomic_write(os.path.join(d, MANIFEST_NAME),
                      json.dumps(man, indent=1).encode())
        # the commit point: everything before this is invisible to restore
        _atomic_write(os.path.join(d, COMMIT_NAME),
                      json.dumps({"step": int(step),
                                  "time": time.time()}).encode())
        with self._lock:
            self._inflight.discard(int(step))
        _m_bytes.inc(total)
        _m_save_ms.observe((time.perf_counter_ns() - t0) / 1e6)
        if keep is not None:
            self.gc(keep_last=keep)

    def wait(self) -> None:
        """Join every in-flight async save; re-raises the first writer
        error.  Blocking here is checkpoint wall-time, so the ledger
        charges it to ``checkpoint_save``."""
        if self._use_async and self._thread is not None:
            with _gp.timer("checkpoint_save"):
                self._queue.join()
        self._raise_pending()

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointError(f"async checkpoint save failed: {err!r}") \
                from err

    # -- gc -----------------------------------------------------------------

    def gc(self, keep_last: Optional[int] = None) -> List[str]:
        """Remove uncommitted step directories (not currently being
        written) and, with ``keep_last``, committed steps beyond the N
        newest.  Returns the removed paths."""
        removed: List[str] = []
        if not os.path.isdir(self.dirname):
            return removed
        with self._lock:
            inflight = set(self._inflight)
        committed = self.all_steps()
        drop_committed = set()
        if keep_last is not None and keep_last >= 0:
            drop_committed = set(committed[:max(0, len(committed) - keep_last)])
        for name in sorted(os.listdir(self.dirname)):
            m = _STEP_RX.match(name)
            if not m:
                continue
            step = int(m.group(1))
            if step in inflight:
                continue
            d = os.path.join(self.dirname, name)
            committed_dir = os.path.exists(os.path.join(d, COMMIT_NAME)) \
                and os.path.exists(os.path.join(d, MANIFEST_NAME))
            if (not committed_dir) or step in drop_committed:
                shutil.rmtree(d, ignore_errors=True)
                removed.append(d)
        return removed

    # -- restore ------------------------------------------------------------

    def _restore_flat(self, step: Optional[int] = None,
                      verify: bool = True) -> Tuple[Dict[str, np.ndarray],
                                                    dict]:
        """Load one committed step as a flat {keypath: array} dict."""
        with _gp.timer("restore"), _spans.span("checkpoint/restore"):
            return self._restore_flat_inner(step, verify)

    def _restore_flat_inner(self, step: Optional[int] = None,
                            verify: bool = True
                            ) -> Tuple[Dict[str, np.ndarray], dict]:
        self.wait()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise CheckpointError(
                    f"no committed checkpoint under {self.dirname}")
        if verify:
            problems = self.verify(step)
            if problems:
                raise CheckpointCorruptError(
                    f"checkpoint step {step} under {self.dirname} is "
                    "corrupt:\n  " + "\n  ".join(problems) +
                    "\n(restore from an older committed step, or delete "
                    "this directory)")
        man = self.manifest(step)
        d = self._path(step)
        by_key: Dict[str, np.ndarray] = {}
        for leaf in man["leaves"]:
            with open(os.path.join(d, leaf["file"]), "rb") as f:
                data = f.read()
            import jax.numpy as jnp

            dt = jnp.dtype(leaf["dtype"])
            arr = np.frombuffer(data, dtype=dt).reshape(leaf["shape"])
            by_key[leaf["key"]] = arr
        return by_key, man

    def restore(self, step: Optional[int] = None, like: Any = None,
                verify: bool = True,
                mesh: Optional[Dict[str, int]] = None) -> Tuple[Any, dict]:
        """Load one committed step; returns ``(state, manifest)``.

        ``step=None`` selects the latest committed step.  ``verify=True``
        integrity-checks every leaf first and raises
        :class:`CheckpointCorruptError` naming the bad file.  With ``like``
        (a pytree of arrays/ShapeDtypeStructs with the same structure the
        state was saved from), leaves are matched by keypath and returned
        in that structure; otherwise the saved nested-dict structure is
        reconstructed from the keypaths (flat {keypath: array} fallback
        for non-dict pytrees).  Leaves come back as numpy arrays — callers
        place them on device (see :func:`restore_train_state` for the
        resharding path).

        ``mesh={axis: size}`` validates the manifest's saved mesh against
        the live one and raises :class:`MeshMismatchError` instead of a
        silently wrong placement (the plain restore has no reshard
        path — any topology difference is fatal here)."""
        by_key, man = self._restore_flat(step, verify=verify)
        check_mesh_compatible(man.get("mesh"), mesh, reshardable=False,
                              where=f"restore step {man['step']}")
        if like is None:
            return _unflatten_keystrs(by_key), man
        flat, treedef = jax.tree_util.tree_flatten_with_path(like)
        out = []
        for path, x in flat:
            key = _leaf_key(path)
            if key not in by_key:
                raise CheckpointError(
                    f"checkpoint step {man['step']} has no leaf {key!r} "
                    f"(saved leaves: {sorted(by_key)[:8]}...)")
            out.append(by_key[key])
        return jax.tree_util.tree_unflatten(treedef, out), man

    def close(self):
        if self._use_async and self._thread is not None \
                and self._thread.is_alive():
            self._queue.join()
            self._queue.put(None)
            self._thread.join(timeout=5)
        self._raise_pending()


# ---------------------------------------------------------------------------
# Engine-level restore with reshard (the dp=8 -> dp=4 path)
# ---------------------------------------------------------------------------

_FLAT_OPT_KEYS = ("m", "v", "ef")


def restore_train_state(ckpt: ElasticCheckpointer, params, opt, *,
                        layout=None, layout_repl: int = 1,
                        step: Optional[int] = None,
                        mesh: Optional[Dict[str, int]] = None):
    """Restore a ``(params, opt)`` train state saved by
    :meth:`ElasticCheckpointer.save`, resharding onto the CURRENT topology.

    ``params``/``opt`` are the live (freshly initialized) target pytrees —
    they provide structure, dtypes and target shardings.  ``layout`` is the
    current comm_opt BucketLayout when the optimizer state is the dp-sharded
    flat megabuffer form (``layout_repl`` = pp*tp); the saved layout comes
    from the manifest and :func:`reshard_flat` moves the moments bit-exactly
    between the two.  Returns ``(params, opt, manifest)``.
    """
    import jax.numpy as jnp

    raw, man = ckpt._restore_flat(step)
    src = man.get("layout")
    src_layout = src_repl = None
    if src is not None:
        src_layout, src_repl = deserialize_layout(src)
    # mesh validation (ISSUE 12): a topology change is only legal through
    # the flat-moment reshard path — both layouts must exist; anything
    # else raises the named error instead of resharding wrong silently
    check_mesh_compatible(
        man.get("mesh"), mesh,
        reshardable=(src_layout is not None and layout is not None),
        where=f"restore_train_state step {man['step']}")

    def place(key: str, target):
        if key not in raw:
            raise CheckpointError(
                f"checkpoint step {man['step']} has no leaf {key!r}")
        arr = raw[key]
        flat_opt = any(key == f"['opt']['{k}']" for k in _FLAT_OPT_KEYS)
        if flat_opt and src_layout is not None and layout is not None:
            same = (serialize_layout(src_layout, src_repl)
                    == serialize_layout(layout, layout_repl))
            if not same:
                arr = reshard_flat(arr, src_layout, layout,
                                   src_repl=src_repl, dst_repl=layout_repl)
        if tuple(arr.shape) != tuple(target.shape):
            raise CheckpointError(
                f"leaf {key!r}: saved shape {tuple(arr.shape)} does not "
                f"match target {tuple(target.shape)} (mesh change without "
                "a reshardable layout?)")
        arr = jnp.asarray(arr).astype(target.dtype)
        sh = getattr(target, "sharding", None)
        return jax.device_put(arr, sh) if sh is not None else arr

    state = {"params": params, "opt": opt}
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    out = [place(_leaf_key(path), x) for path, x in flat]
    new = jax.tree_util.tree_unflatten(treedef, out)
    return new["params"], new["opt"], man


def build_restore_broadcast_program(var_specs, ring_id: int = 0,
                                    axis: str = "dp",
                                    cond_name: str = "found_checkpoint"):
    """Fluid program for the multi-rank restore barrier: rank 0 loads the
    committed checkpoint and ``c_broadcast``s every persistable, under a
    found-checkpoint conditional — all ranks start bit-identical even when
    a peer's store read raced a GC.

    ``var_specs``: iterable of (name, shape, dtype).  Every collective is
    tagged ``__restore_reshard__`` so the static comm/precision checkers
    accept it (the conditional's predicate is rank-uniform — every rank
    selects the same committed step; paddle_lint reports it as INFO
    ``restore_conditional_collective`` instead of the deadlock ERROR,
    docs/elastic.md)."""
    from ..framework.program import Program

    main = Program()
    block = main.global_block()
    block.create_var(name=cond_name, shape=(1,), dtype="bool", is_data=True)
    for name, shape, dtype in var_specs:
        block.create_var(name=name, shape=tuple(shape), dtype=str(dtype),
                         persistable=True)
    sub = main._create_block()
    for name, _shape, _dtype in var_specs:
        sub.append_op("c_broadcast", {"X": name}, {"Out": name},
                      {"ring_id": int(ring_id), "root": 0,
                       "__restore_reshard__": True})
    main._rollback()
    block.append_op("conditional_block", {"Cond": cond_name}, {},
                    {"sub_block": sub.idx})
    main._annotations["mesh"] = {"mode": "shard_map",
                                 "axes": [(axis, 0)], "data_axis": axis,
                                 "ring_axes": {int(ring_id): axis}}
    return main


# ---------------------------------------------------------------------------
# Orbax-backed multi-host path (OCDBT shards), hardened step selection
# ---------------------------------------------------------------------------

def _checkpointer(use_async: bool):
    import orbax.checkpoint as ocp

    if use_async:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


class ShardedCheckpointer:
    """Save/restore a (params, opt_state, step) training state via orbax
    (every host writes only its own OCDBT shards).

    ``save`` is non-blocking when ``use_async`` (the write overlaps the
    next training steps; call ``wait`` or save again to join); pass
    ``keep_last=N`` to retain only the N newest committed steps.
    ``restore`` takes the *target* shardings — restoring onto a different
    mesh shape reshards automatically.  Step selection skips uncommitted
    directories: an orbax checkpoint is committed once its
    ``_CHECKPOINT_METADATA`` lands (tmp directories carry an
    ``.orbax-checkpoint-tmp`` suffix and never match).
    """

    def __init__(self, dirname: str, use_async: bool = True):
        self.dirname = os.path.abspath(str(dirname))
        os.makedirs(self.dirname, exist_ok=True)
        self._ckptr = _checkpointer(use_async)

    def _path(self, step: int) -> str:
        return os.path.join(self.dirname, f"step_{int(step):08d}")

    def save(self, step: int, state: Any, force: bool = False,
             keep_last: Optional[int] = None) -> str:
        path = self._path(step)
        self._ckptr.save(path, state, force=force)
        if keep_last is not None:
            # join the write first: GC during an in-flight async save could
            # otherwise delete the step it is told to keep
            self.wait()
            self.gc(keep_last=keep_last)
        return path

    def wait(self) -> None:
        w = getattr(self._ckptr, "wait_until_finished", None)
        if w is not None:
            w()

    def _is_committed(self, name: str) -> Optional[int]:
        """step number iff ``name`` is a committed step dir, else None."""
        m = _STEP_RX.match(name)
        if not m:
            return None
        d = os.path.join(self.dirname, name)
        if not os.path.isdir(d):
            return None
        # committed orbax dirs carry _CHECKPOINT_METADATA; our own COMMIT
        # marker is accepted too so the two stores share selection rules
        if os.path.exists(os.path.join(d, "_CHECKPOINT_METADATA")) or \
                os.path.exists(os.path.join(d, COMMIT_NAME)):
            return int(m.group(1))
        return None

    def all_steps(self) -> List[int]:
        if not os.path.isdir(self.dirname):
            return []
        out = []
        for name in os.listdir(self.dirname):
            step = self._is_committed(name)
            if step is not None:
                out.append(step)
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def gc(self, keep_last: Optional[int] = None) -> List[str]:
        """Drop uncommitted debris (killed mid-save, orbax tmp dirs) and,
        with ``keep_last``, committed steps beyond the N newest."""
        removed: List[str] = []
        if not os.path.isdir(self.dirname):
            return removed
        committed = self.all_steps()
        drop = set()
        if keep_last is not None and keep_last >= 0:
            drop = set(committed[:max(0, len(committed) - keep_last)])
        for name in sorted(os.listdir(self.dirname)):
            full = os.path.join(self.dirname, name)
            if not os.path.isdir(full):
                continue
            if not (name.startswith("step_") or
                    ".orbax-checkpoint-tmp" in name):
                continue
            step = self._is_committed(name)
            if step is None or step in drop:
                shutil.rmtree(full, ignore_errors=True)
                removed.append(full)
        return removed

    def restore(self, step: int, abstract_state: Any) -> Any:
        """``abstract_state``: a pytree of jax.ShapeDtypeStruct with the
        TARGET shardings (build with :func:`abstract_like` from live
        arrays, or from init metadata) — orbax reshards each leaf onto
        them, so a dp=2/tp=4 save restores onto a dp=4/tp=2 mesh."""
        self.wait()
        if self._is_committed(f"step_{int(step):08d}") is None:
            raise CheckpointError(
                f"step {step} under {self.dirname} is missing or "
                "uncommitted (killed mid-save?) — pick one of "
                f"{self.all_steps()}")
        return self._ckptr.restore(self._path(step), abstract_state)

    def close(self):
        self.wait()
        c = getattr(self._ckptr, "close", None)
        if c is not None:
            c()


def abstract_like(tree: Any) -> Any:
    """Live pytree -> ShapeDtypeStruct pytree carrying each leaf's current
    sharding (the restore target for the same topology)."""
    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=getattr(x, "sharding", None))
        return x
    return jax.tree_util.tree_map(one, tree)


def abstract_for_mesh(tree: Any, specs: Any, mesh) -> Any:
    """ShapeDtypeStruct pytree for restoring onto ``mesh`` with PartitionSpec
    tree ``specs`` (cross-topology restore: pass the NEW mesh).

    ``specs`` leaves are PartitionSpecs (tuples — hence the is_leaf guard,
    same convention as parallelize.py's sharding builders)."""
    from jax.sharding import NamedSharding, PartitionSpec

    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    return jax.tree_util.tree_map(
        lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
        tree, shardings)
