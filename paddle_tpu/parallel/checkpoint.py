"""Sharded async checkpointing for the 4D-parallel training path.

The reference checkpoints through save/load *ops* on host tensors
(fluid/io.py:598,902 save_persistables; operators/save_op.cc) and PS-mode
checkpoint_notify — single-host, fully-replicated formats.  At GPT scale the
TPU-native equivalent is an orbax-backed sharded checkpoint: every host
writes only its own shards (OCDBT), saves run async behind the training
step, and a restore may use a DIFFERENT mesh/topology — orbax reshards on
load against the target shardings (the reference has no analogue; its
closest capability is pserver-side sharded tables, SURVEY §5).

The fluid-path formats (persistables / inference-model / ProgramDesc wire)
stay in paddle_tpu.io — this module is the parallel engine's counterpart
for ``parallelize.init_sharded``-style pytrees.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _checkpointer(use_async: bool):
    import orbax.checkpoint as ocp

    if use_async:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


class ShardedCheckpointer:
    """Save/restore a (params, opt_state, step) training state.

    ``save`` is non-blocking when ``use_async`` (the write overlaps the
    next training steps; call ``wait`` or save again to join). ``restore``
    takes the *target* shardings — restoring onto a different mesh shape
    reshards automatically.
    """

    def __init__(self, dirname: str, use_async: bool = True):
        self.dirname = os.path.abspath(dirname)
        os.makedirs(self.dirname, exist_ok=True)
        self._ckptr = _checkpointer(use_async)

    def _path(self, step: int) -> str:
        return os.path.join(self.dirname, f"step_{int(step):08d}")

    def save(self, step: int, state: Any, force: bool = False) -> str:
        path = self._path(step)
        self._ckptr.save(path, state, force=force)
        return path

    def wait(self) -> None:
        w = getattr(self._ckptr, "wait_until_finished", None)
        if w is not None:
            w()

    def all_steps(self):
        if not os.path.isdir(self.dirname):
            return []
        out = []
        for name in os.listdir(self.dirname):
            if name.startswith("step_"):
                try:
                    out.append(int(name.split("_", 1)[1]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step: int, abstract_state: Any) -> Any:
        """``abstract_state``: a pytree of jax.ShapeDtypeStruct with the
        TARGET shardings (build with :func:`abstract_like` from live
        arrays, or from init metadata) — orbax reshards each leaf onto
        them, so a dp=2/tp=4 save restores onto a dp=4/tp=2 mesh."""
        self.wait()
        return self._ckptr.restore(self._path(step), abstract_state)

    def close(self):
        self.wait()
        c = getattr(self._ckptr, "close", None)
        if c is not None:
            c()


def abstract_like(tree: Any) -> Any:
    """Live pytree -> ShapeDtypeStruct pytree carrying each leaf's current
    sharding (the restore target for the same topology)."""
    def one(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=getattr(x, "sharding", None))
        return x
    return jax.tree_util.tree_map(one, tree)


def abstract_for_mesh(tree: Any, specs: Any, mesh) -> Any:
    """ShapeDtypeStruct pytree for restoring onto ``mesh`` with PartitionSpec
    tree ``specs`` (cross-topology restore: pass the NEW mesh).

    ``specs`` leaves are PartitionSpecs (tuples — hence the is_leaf guard,
    same convention as parallelize.py's sharding builders)."""
    from jax.sharding import NamedSharding, PartitionSpec

    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    return jax.tree_util.tree_map(
        lambda x, sh: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
        tree, shardings)
