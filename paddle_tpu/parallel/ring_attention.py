"""Ring attention — context/sequence parallelism for long sequences.

Absent from the 2020-era reference (SURVEY.md §5 "Long-context/sequence
parallelism: none"), but first-class here: sequences longer than one chip's
HBM are sharded over a ``cp`` mesh axis and attention runs as a ring —
each device holds its sequence chunk of Q permanently and passes K/V chunks
around the ring with ``lax.ppermute`` (one ICI hop per step), combining
partial attention with an online-softmax accumulator exactly like
FlashAttention combines KV tiles (ops/pallas_kernels.py does the same
within a chip; this does it across chips).

Peak memory per device: O((T/cp)^2) logits per ring step instead of O(T^2);
comms: cp-1 ppermutes of the local K/V chunk, fully overlappable with
compute by XLA (latency hiding via collective-permute pipelining).

Differentiable: the ring is a ``lax.scan`` over ppermutes, both of which
JAX transposes automatically (the VJP is itself a reverse ring).

Use under ``shard_map`` with q/k/v sharded on the sequence dim over
``axis_name``; see tests/test_ring_attention.py and models/gpt.py (cp axis).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_MASK = -1e30


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   sm_scale: Optional[float] = None):
    """Blockwise ring attention. q,k,v: local chunks [B, T/cp, nh, hd],
    sequence-sharded over ``axis_name`` (chunk i = rows [i*Tl, (i+1)*Tl)).
    Returns local output chunk [B, T/cp, nh, hd]. Call inside shard_map.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    cp = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, tl, nh, hd = q.shape

    q32 = q.astype(jnp.float32)

    def accumulate(kc, vc, m, l, acc, i):
        # kc originated on device (my - i) mod cp == its global chunk index.
        src = (my - i) % cp
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, kc.astype(jnp.float32)) * sm_scale
        if causal:
            # chunk-level causal: src > my fully masked; src == my intra-chunk.
            qpos = jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 0)
            kpos = jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 1)
            intra = qpos >= kpos                       # [tl, tl]
            keep = jnp.where(src == my, intra,
                             jnp.broadcast_to(src < my, (tl, tl)))
            s = jnp.where(keep[None, None], s, _MASK)
        m_curr = jnp.max(s, axis=-1)                   # [b, nh, tl]
        m_new = jnp.maximum(m, m_curr)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])              # [b, nh, tl, tk]
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, vc.astype(jnp.float32))
        acc_new = acc * alpha[..., None] + pv
        return m_new, l_new, acc_new

    def step(carry, i):
        kc, vc, m, l, acc = carry
        m, l, acc = accumulate(kc, vc, m, l, acc, i)
        kc, vc = jax.lax.ppermute(
            (kc, vc), axis_name, perm=[(j, (j + 1) % cp) for j in range(cp)])
        return (kc, vc, m, l, acc), None

    # Derive initial accumulators from q so they carry the same manual-axes
    # "varying over cp" type as the scan outputs (jax>=0.9 shard_map typing).
    qt = q32.transpose(0, 2, 1, 3)                     # [b, nh, tl, hd]
    m0 = jnp.full_like(qt[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(qt[..., 0])
    a0 = jnp.zeros_like(qt)
    # cp-1 rotate-and-accumulate steps in the scan, then the last chunk is
    # consumed outside it — no wasted final ppermute (one full K/V ICI hop
    # per layer forward and its transpose in backward).
    (kc, vc, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, a0), jnp.arange(cp - 1))
    m, l, acc = accumulate(kc, vc, m, l, acc, cp - 1)

    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / l[..., None]                           # [b, nh, tl, hd]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
