"""Reader decorators + DataLoader — the Python data pipeline.

Capability parity with the reference's reader stack:
  * reader decorators (python/paddle/reader/decorator.py): ``shuffle``,
    ``buffered``, ``batch``, ``compose``, ``chain``, ``map_readers``,
    ``xmap_readers``, ``cache``, ``firstn``, ``multiprocess_reader``.
  * ``DataLoader`` (fluid/reader.py + fluid/dataloader/): both the
    ``from_generator`` capacity-buffered feed path and the map-style
    ``DataLoader(dataset, batch_size, num_workers, ...)`` with real
    multiprocess workers (fluid/dataloader/dataloader_iter.py).

TPU-first design: instead of the reference's LoDTensorBlockingQueue +
buffered_reader double-buffering onto a CUDA stream (operators/reader/
buffered_reader.cc), batches are staged as numpy on a background thread and
handed to the Executor, which device-puts them; under jit the transfer
overlaps with the previous step's compute because JAX dispatch is async.
"""
from __future__ import annotations

import itertools
import multiprocessing
import queue as _queue
import random
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

__all__ = [
    "shuffle", "buffered", "batch", "compose", "chain", "map_readers",
    "xmap_readers", "cache", "firstn", "multiprocess_reader",
    "Dataset", "IterableDataset", "BatchSampler", "DataLoader",
    "prefetch_to_device", "ClosingIterator",
]


class ClosingIterator:
    """Iterator wrapper with a deterministic shutdown surface for
    producer-thread readers (``buffered``, ``prefetch_to_device``).

    A consumer that exits early (exception, ``break``) used to leave the
    daemon producer blocked on its bounded queue until interpreter exit.
    ``close()`` (also via ``with`` or garbage collection) closes the
    underlying generator — which signals the producer to stop and drains
    the queue — and then JOINS the producer thread, so no run ends with a
    leaked reader thread still holding file handles or device buffers.
    """

    def __init__(self, gen, thread_holder: Optional[list] = None,
                 join_timeout: float = 5.0):
        self._gen = gen
        self._threads = thread_holder if thread_holder is not None else []
        self._join_timeout = join_timeout
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._gen.close()    # runs the generator's finally: stop + drain
        for t in list(self._threads):
            if t is not None and t.is_alive():
                t.join(timeout=self._join_timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_to_device(batches, size: int = 2):
    """Overlap host batch assembly and host->device transfer with the
    in-flight step.

    Wraps an iterator of batches (feed dicts or tuples of arrays): a
    background thread pulls the next batches, moves every array onto the
    device with ``jax.device_put``, and parks at most ``size`` ready batches
    in a bounded queue. While the Executor's asynchronously dispatched step
    runs, the next batch's assembly + transfer proceed concurrently — the
    TPU-native analogue of the reference's buffered_reader double-buffering
    onto a CUDA stream (operators/reader/buffered_reader.cc). Device arrays
    flow through the Executor's dispatch fast path untouched (no re-
    normalization, no extra host copy).

    Producer exceptions re-raise in the consumer; abandoning the iterator
    unblocks, stops AND joins the producer (the returned
    :class:`ClosingIterator` exposes ``close()`` and works as a context
    manager — a consumer that breaks early leaks no thread).

    Self-reporting: the metrics registry carries the ready-batch queue
    depth (``paddle_prefetch_queue_depth`` — sampled at every consumer
    get: a depth pinned at 0 means the device is starving on data, pinned
    at ``size`` means the pipeline is step-bound) and the staged-batch /
    consumer-stall totals.
    """
    import jax

    from .observability import goodput as _goodput
    from .observability import metrics as _obs_metrics
    from .observability import spans as _spans

    _gp = _goodput.ledger()
    _reg = _obs_metrics.default_registry()
    _g_depth = _reg.gauge(
        "paddle_prefetch_queue_depth",
        "Ready device-staged batches in the prefetch queue")
    _c_batches = _reg.counter(
        "paddle_prefetch_batches_total",
        "Batches staged onto the device by prefetch_to_device")
    _c_stall = _reg.counter(
        "paddle_prefetch_consumer_stall_ms_total",
        "Time the training loop spent waiting on the prefetch queue (ms)")

    def to_device(item):
        if isinstance(item, dict):
            return {k: jax.device_put(v) if isinstance(v, np.ndarray) else v
                    for k, v in item.items()}
        if isinstance(item, (tuple, list)):
            return type(item)(
                jax.device_put(v) if isinstance(v, np.ndarray) else v
                for v in item)
        return item

    _end = object()
    q: _queue.Queue = _queue.Queue(maxsize=max(1, int(size)))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    # span-context propagation: the producer thread's staging spans parent
    # to whatever span the training loop opened around this call, instead
    # of orphaning on a fresh trace (ISSUE 10 satellite)
    _ctx = _spans.current_context()
    _tracer = _spans.default_tracer()

    def produce():
        try:
            with _tracer.context(_ctx):
                for b in batches:
                    with _tracer.span("input/stage_batch"):
                        staged = to_device(b)
                    if not put((False, staged)):
                        return
        except BaseException as e:
            put((True, e))
        else:
            put((False, _end))

    threads: list = []

    def consume():
        # pipeline spin-up (thread start) is input-side wall time: charge
        # it to input_stall so the first batch's latency is attributed,
        # not lost
        with _gp.timer("input_stall"):
            t = threading.Thread(target=produce, daemon=True,
                                 name="device_prefetch")
            threads.append(t)
            t.start()
        try:
            import time as _time

            while True:
                _g_depth.set(q.qsize())
                t0 = _time.perf_counter_ns()
                # the consumer's queue wait is the run's input stall: the
                # device had nothing staged to chew on
                with _gp.timer("input_stall"):
                    is_err, item = q.get()
                _c_stall.inc((_time.perf_counter_ns() - t0) / 1e6)
                if is_err:
                    raise item
                if item is _end:
                    break
                _c_batches.inc()
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass
            t.join(timeout=5)

    return ClosingIterator(consume(), threads)


# ---------------------------------------------------------------------------
# reader decorators (a "reader" is a zero-arg callable returning an iterator
# of samples — the reference's reader protocol)
# ---------------------------------------------------------------------------

def map_readers(func, *readers):
    """Apply func elementwise over samples zipped from several readers."""
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size: int):
    """Pool-shuffle with a bounded buffer — decorator.py shuffle."""
    def shuffled():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
            # fall through keeps filling
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b
    return shuffled


def chain(*readers):
    def chained():
        for r in readers:
            for s in r():
                yield s
    return chained


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment: bool = True):
    """Zip readers into tuple samples; flattens tuple elements like the
    reference (reader/decorator.py compose): with check_alignment=True,
    length mismatch raises ComposeNotAligned; with False, silently truncates
    to the shortest reader."""
    _missing = object()

    def _flatten(x):
        out = []
        for e in x:
            if isinstance(e, tuple):
                out.extend(e)
            else:
                out.append(e)
        return tuple(out)

    def composed():
        rs = [r() for r in readers]
        if check_alignment:
            for vals in itertools.zip_longest(*rs, fillvalue=_missing):
                if any(v is _missing for v in vals):
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                yield _flatten(vals)
        else:
            for vals in zip(*rs):
                yield _flatten(vals)
    return composed


def buffered(reader, size: int):
    """Producer-thread read-ahead buffer — decorator.py buffered.
    Producer exceptions are re-raised in the consumer, not swallowed.

    Returns a reader whose iterator is a :class:`ClosingIterator`: a
    consumer that stops early (``break``/exception/``close()``) unblocks
    the producer's bounded put and joins the thread instead of leaking it.
    """
    _end = object()

    def buffered_reader():
        q: _queue.Queue = _queue.Queue(maxsize=max(1, int(size)))
        stop = threading.Event()
        threads: list = []

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def produce():
            try:
                for s in reader():
                    if not put((False, s)):
                        return
            except BaseException as e:
                put((True, e))
            else:
                put((False, _end))

        def consume():
            t = threading.Thread(target=produce, daemon=True,
                                 name="buffered_reader")
            threads.append(t)
            t.start()
            try:
                while True:
                    is_err, s = q.get()
                    if is_err:
                        raise s
                    if s is _end:
                        break
                    yield s
            finally:
                stop.set()
                try:
                    while True:
                        q.get_nowait()
                except _queue.Empty:
                    pass
                t.join(timeout=5)

        return ClosingIterator(consume(), threads)
    return buffered_reader


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group samples into lists of batch_size — paddle.batch."""
    def batched():
        b = []
        for s in reader():
            b.append(s)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batched


def cache(reader):
    all_data: List[Any] = []
    filled = [False]

    def cached():
        if not filled[0]:
            all_data.extend(reader())
            filled[0] = True
        for s in all_data:
            yield s
    return cached


def firstn(reader, n: int):
    def firstn_reader():
        for i, s in enumerate(reader()):
            if i >= n:
                break
            yield s
    return firstn_reader


_XMAP_ERR = object()


def xmap_readers(mapper, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over a reader using worker threads (reference uses
    threads too — decorator.py xmap_readers).

    Exception safety (docs/health.md): a ``mapper`` or ``reader`` that
    raises used to kill its thread silently, leaving the consumer blocked
    forever on an empty queue — exactly the silent-hang class the hang
    watchdog exists to catch.  Worker/feeder exceptions now travel to the
    consumer and re-raise on the next pull."""
    _end = object()

    def xreader():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
            except BaseException as e:   # surface in the consumer
                out_q.put((_XMAP_ERR, e))
            finally:
                # workers always get their end markers, even on a feeder
                # crash — nobody is left blocked on in_q
                for _ in range(process_num):
                    in_q.put(_end)

        def work():
            while True:
                item = in_q.get()
                if item is _end:
                    out_q.put(_end)
                    return
                i, s = item
                try:
                    out_q.put((i, mapper(s)))
                except BaseException as e:
                    out_q.put((_XMAP_ERR, e))
                    out_q.put(_end)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            pending: Dict[int, Any] = {}
            next_i = 0
            while finished < process_num:
                item = out_q.get()
                if item is _end:
                    finished += 1
                    continue
                i, v = item
                if i is _XMAP_ERR:
                    raise v
                pending[i] = v
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is _end:
                    finished += 1
                    continue
                if item[0] is _XMAP_ERR:
                    raise item[1]
                yield item[1]
    return xreader


_MP_END = ("__paddle_tpu_mp_end__",)
_MP_ERR = "__paddle_tpu_mp_err__"


def multiprocess_reader(readers, use_pipe: bool = True, queue_size: int = 1000):
    """Fan-in several readers, each in its own OS process (decorator.py
    multiprocess_reader).  Worker exceptions propagate to the consumer as
    RuntimeError (exceptions may not pickle across the process boundary, so
    the traceback travels as text); samples that are literally None are fine
    because the end-of-stream sentinel is a distinct marker."""
    def mreader():
        import traceback
        ctx = multiprocessing.get_context("fork")
        q = ctx.Queue(queue_size)

        def work(r):
            try:
                for s in r():
                    q.put(("s", s))
            except BaseException:
                q.put((_MP_ERR, traceback.format_exc()))
            else:
                q.put(_MP_END)

        procs = [ctx.Process(target=work, args=(r,), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        finished = 0
        try:
            while finished < len(readers):
                try:
                    item = q.get(timeout=1.0)
                except _queue.Empty:
                    # a worker killed outright (OOM, SIGKILL) never sends
                    # its end marker: raise instead of blocking forever
                    if not any(p.is_alive() for p in procs):
                        raise RuntimeError(
                            "multiprocess_reader: worker process died "
                            "without reporting end-of-stream (killed?)")
                    continue
                if item == _MP_END:
                    finished += 1
                elif isinstance(item, tuple) and len(item) == 2 \
                        and item[0] == _MP_ERR:
                    raise RuntimeError(
                        f"multiprocess_reader worker failed:\n{item[1]}")
                else:
                    yield item[1]
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                p.join()
    return mreader


# ---------------------------------------------------------------------------
# map/iterable datasets + samplers (fluid/dataloader/dataset.py,
# batch_sampler.py)
# ---------------------------------------------------------------------------

class Dataset:
    """Map-style dataset: __getitem__ + __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class BatchSampler:
    def __init__(self, dataset=None, indices=None, shuffle: bool = False,
                 batch_size: int = 1, drop_last: bool = False):
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.shuffle = shuffle
        if indices is None:
            indices = list(range(len(dataset)))
        self.indices = list(indices)

    def __iter__(self):
        idx = list(self.indices)
        if self.shuffle:
            random.shuffle(idx)
        b = []
        for i in idx:
            b.append(i)
            if len(b) == self.batch_size:
                yield b
                b = []
        if b and not self.drop_last:
            yield b

    def __len__(self):
        n = len(self.indices)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


def default_collate_fn(samples: Sequence[Any]):
    """Stack a list of samples (each a tuple/list of field arrays) into
    per-field numpy batches — fluid/dataloader/collate.py."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return tuple(default_collate_fn([s[i] for s in samples])
                     for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate_fn([s[k] for s in samples]) for k in first}
    if isinstance(first, np.ndarray):
        return np.stack(samples)
    if isinstance(first, (int, np.integer)):
        return np.asarray(samples, dtype=np.int64)
    if isinstance(first, (float, np.floating)):
        return np.asarray(samples, dtype=np.float32)
    return np.asarray(samples)


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------

_WORKER_END = "__paddle_tpu_worker_end__"


def _worker_loop(dataset, index_queue, data_queue, collate_fn):
    while True:
        item = index_queue.get()
        if item == _WORKER_END:
            return
        seq, indices = item
        try:
            samples = [dataset[i] for i in indices]
            data_queue.put((seq, collate_fn(samples)))
        except BaseException as e:  # surface worker errors to the parent
            try:
                data_queue.put((seq, e))
            except Exception:  # unpicklable exception: send a summary
                data_queue.put((seq, RuntimeError(
                    f"DataLoader worker failed: {type(e).__name__}: {e}")))


class DataLoader:
    """paddle.io.DataLoader / fluid.io.DataLoader capability.

    Two construction paths, like the reference:
      * ``DataLoader(dataset, feed_list=..., batch_size=..., num_workers=N)``
      * ``DataLoader.from_generator(feed_list, capacity)`` then
        ``set_sample_generator`` / ``set_sample_list_generator`` /
        ``set_batch_generator``.

    Iterating yields feed dicts (name -> numpy array) when feed_list is given,
    else tuples of numpy arrays.
    """

    def __init__(self, dataset=None, feed_list=None, batch_size: int = 1,
                 shuffle: bool = False, drop_last: bool = False,
                 num_workers: int = 0, collate_fn=None,
                 batch_sampler: Optional[BatchSampler] = None,
                 return_list: bool = True, capacity: int = 8,
                 device_prefetch: int = 0):
        self.dataset = dataset
        self.feed_list = list(feed_list) if feed_list else None
        self.num_workers = int(num_workers)
        self.collate_fn = collate_fn or default_collate_fn
        self.capacity = capacity
        self.device_prefetch = int(device_prefetch)
        self.return_list = return_list
        self._generator: Optional[Callable] = None
        self._gen_kind: Optional[str] = None
        if dataset is not None and not isinstance(dataset, IterableDataset):
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
        else:
            self.batch_sampler = None
        self.batch_size = batch_size
        self.drop_last = drop_last

    # -- from_generator path (fluid/reader.py DataLoader.from_generator) ----
    @classmethod
    def from_generator(cls, feed_list=None, capacity: int = 8,
                       use_double_buffer: bool = True, iterable: bool = True,
                       return_list: bool = False, drop_last: bool = True):
        return cls(feed_list=feed_list, capacity=capacity,
                   return_list=return_list, drop_last=drop_last)

    def set_sample_generator(self, reader, batch_size: int,
                             drop_last: bool = True, places=None):
        self._generator = batch(reader, batch_size, drop_last=drop_last)
        self._gen_kind = "sample_list"
        return self

    def set_sample_list_generator(self, reader, places=None):
        self._generator = reader
        self._gen_kind = "sample_list"
        return self

    def set_batch_generator(self, reader, places=None):
        self._generator = reader
        self._gen_kind = "batch"
        return self

    # -- iteration ----------------------------------------------------------
    def _names(self):
        if not self.feed_list:
            return None
        return [v if isinstance(v, str) else v.name for v in self.feed_list]

    def _emit(self, fields):
        names = self._names()
        if names is None:
            return tuple(fields)
        return {n: f for n, f in zip(names, fields)}

    def __iter__(self):
        if self._generator is not None:
            it = self._iter_generator()
        elif isinstance(self.dataset, IterableDataset):
            it = self._iter_iterable()
        elif self.num_workers > 0:
            it = self._iter_multiprocess()
        else:
            it = self._iter_single()
        if self.device_prefetch > 0:
            # stage batches onto the device ahead of the training loop
            it = prefetch_to_device(it, size=self.device_prefetch)
        yield from it

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("DataLoader over a generator has no len()")

    def _iter_generator(self):
        assert self._generator is not None
        gen = buffered(self._generator, self.capacity)
        if self._gen_kind == "batch":
            for fields in gen():
                fields = [np.asarray(f) for f in (
                    fields if isinstance(fields, (tuple, list)) else [fields])]
                yield self._emit(fields)
        else:  # sample_list: list of per-sample tuples
            for samples in gen():
                cols = self.collate_fn(samples)
                cols = cols if isinstance(cols, tuple) else (cols,)
                yield self._emit([np.asarray(c) for c in cols])

    def _iter_iterable(self):
        b = []
        for s in iter(self.dataset):
            b.append(s)
            if len(b) == self.batch_size:
                cols = self.collate_fn(b)
                yield self._emit(list(cols if isinstance(cols, tuple) else (cols,)))
                b = []
        if b and not self.drop_last:
            cols = self.collate_fn(b)
            yield self._emit(list(cols if isinstance(cols, tuple) else (cols,)))

    def _iter_single(self):
        for indices in self.batch_sampler:
            cols = self.collate_fn([self.dataset[i] for i in indices])
            yield self._emit(list(cols if isinstance(cols, tuple) else (cols,)))

    def _iter_multiprocess(self):
        ctx = multiprocessing.get_context("fork")
        index_q = ctx.Queue()
        data_q = ctx.Queue(self.capacity)
        workers = [ctx.Process(target=_worker_loop,
                               args=(self.dataset, index_q, data_q,
                                     self.collate_fn), daemon=True)
                   for _ in range(self.num_workers)]
        for w in workers:
            w.start()
        try:
            batches = list(self.batch_sampler)
            for seq, indices in enumerate(batches):
                index_q.put((seq, indices))
            for _ in workers:
                index_q.put(_WORKER_END)
            pending: Dict[int, Any] = {}
            next_seq = 0
            received = 0
            while received < len(batches):
                try:
                    seq, cols = data_q.get(timeout=1.0)
                except _queue.Empty:
                    # every worker dead with results still owed: a child
                    # was killed outright (OOM, SIGKILL) — raise instead
                    # of leaving the training loop blocked forever
                    if not any(w.is_alive() for w in workers):
                        raise RuntimeError(
                            f"DataLoader: worker processes died with "
                            f"{len(batches) - received} batches "
                            "outstanding (killed?)")
                    continue
                received += 1
                if isinstance(cols, Exception):
                    raise cols
                pending[seq] = cols
                while next_seq in pending:
                    cols = pending.pop(next_seq)
                    next_seq += 1
                    yield self._emit(
                        list(cols if isinstance(cols, tuple) else (cols,)))
        finally:
            for w in workers:
                w.terminate()
            for w in workers:
                w.join()
